"""On-chip paged-vs-dense decode probe (645M bf16, bs=8, 128+128).

Token-exact equality holds on the CPU f32 test fixtures; on an
UNTRAINED bf16 645M model the two attention formulations round
differently and near-tie argmaxes flip, so this probe checks (a) the
two paths' first tokens agree, and wherever they don't, the target's
own top-2 logit margin is eps-scale (a real mask/position bug shifts
logits by O(1), flipping LARGE-margin tokens — which the assert
rejects) and (b) wall-clock of both paths.

Run: python tools/paged_decode_probe.py  (uses the attached chip)

MEASURED (v5e, 2026-07-31, 645M bf16, bs=8, 128+128, block 128):
first-token agreement 1.00 (later-token divergence on the untrained
model is cascaded near-tie bf16 argmax flips, margins < 0.05); dense
372 ms/call vs paged 3659 ms/call — the jnp gather/scatter block
program is ~10x slower than the dense dynamic-update-slice scan at
these shapes. The paged path's value on this build is its CACHE
SEMANTICS (pads never enter the pool, block-table layout = the
reference serving interface); the dense scan stays the fast path and
the decode bench measures it. A competitive paged decode needs a
custom paged-attention kernel (Pallas), not an XLA gather program.
"""
import os
import sys
import time

# repo import WITHOUT the PYTHONPATH env var: exporting PYTHONPATH breaks
# the axon plugin's helper subprocess (module shadowing), so tools add
# the repo root to sys.path in-process instead
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

paddle.seed(0)
cfg = LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                  num_hidden_layers=10, num_attention_heads=16,
                  num_key_value_heads=16, max_position_embeddings=2048)
m = LlamaForCausalLM(cfg)
m.bfloat16(); m.eval()
ids_np = np.random.RandomState(0).randint(1, 32000, (8, 128)).astype("int64")
ids = paddle.to_tensor(ids_np)

# (a) semantic equivalence: full-forward last-position logits vs the
# paged prefill's logits for the same prompt
import jax.numpy as jnp

full_logits = np.asarray(
    m(ids)._value[:, -1, :].astype(jnp.float32))
d1 = m.generate(ids, max_new_tokens=1).numpy()[:, -1]
p1 = m.generate(ids, max_new_tokens=1, paged=True,
                block_size=128).numpy()[:, -1]
agree = (d1 == p1).mean()
print(f"first-token agreement dense-vs-paged: {agree:.2f} "
      f"(near-ties may flip on an untrained bf16 model)")

# margin analysis: where they disagree, the top-2 margin must be tiny
srt = np.sort(full_logits, axis=-1)
margin = srt[:, -1] - srt[:, -2]
for r in range(8):
    if d1[r] != p1[r]:
        print(f"  row {r}: top-2 margin {margin[r]:.4f} (bf16 eps-scale "
              f"tie)" )
        assert margin[r] < 0.05, "LARGE-margin divergence = real bug"

# (b) wall-clock
def run(**kw):
    out = m.generate(ids, max_new_tokens=128, **kw)
    np.asarray(out._value)
    return out

run(); run(paged=True, block_size=128)      # compile
for name, kw in (("dense", {}), ("paged", dict(paged=True,
                                               block_size=128))):
    t0 = time.perf_counter()
    for _ in range(3):
        run(**kw)
    dt = (time.perf_counter() - t0) / 3
    print(f"{name}: {dt*1e3:.0f} ms/call for 8x128 new tokens "
          f"({8*128/dt:.0f} tok/s incl prefill)")
