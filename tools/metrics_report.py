#!/usr/bin/env python
"""Render a paddle_tpu.observability metrics dump as a human report.

Usage:
    python tools/metrics_report.py metrics.json [--events N]

The input is the JSON written by ``paddle_tpu.observability.dump(path)``
or by running any workload with ``PADDLE_TPU_METRICS_DUMP=metrics.json``
in the environment. Rendering goes through the same
``observability.report.render_report`` the in-process ``summary()``
uses, so the dump round-trips by construction. Exits non-zero on a file
that is not a metrics dump.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump", help="JSON file written by observability.dump()")
    ap.add_argument("--events", type=int, default=20,
                    help="how many trailing events to show (default 20)")
    args = ap.parse_args(argv)

    try:
        with open(args.dump) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"metrics_report: cannot read {args.dump!r}: {e}",
              file=sys.stderr)
        return 1

    from paddle_tpu.observability.report import render_report

    try:
        report = render_report(d, max_events=args.events)
    except ValueError as e:
        print(f"metrics_report: {args.dump!r}: {e}", file=sys.stderr)
        return 1
    generated = d.get("generated_unix")
    if generated:
        import time

        print(f"metrics dump v{d.get('version', '?')} generated "
              f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(generated))}"
              f" (enabled={d.get('enabled')})\n")
    print(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
