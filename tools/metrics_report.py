#!/usr/bin/env python
"""Render a paddle_tpu.observability metrics dump as a human report.

Usage:
    python tools/metrics_report.py metrics.json [--events N] [--top N]
    python tools/metrics_report.py flight-1234-1.json   # flight dumps too
    python tools/metrics_report.py /tmp/flight_dir      # a whole incident

Input is either the JSON written by ``paddle_tpu.observability.dump(path)``
(or any workload run with ``PADDLE_TPU_METRICS_DUMP=metrics.json``), or a
flight-recorder crash dump written to ``PADDLE_TPU_FLIGHT_DIR`` — the
kind is auto-detected. Metric rows come out grouped by subsystem
(``dispatch``, ``executor``, ``train``, ``comm``, ``elastic``, ...);
``--top`` keeps only the N largest series per metric. Rendering goes
through the same ``observability.report`` code the in-process
``summary()`` uses, so dumps round-trip by construction.

Passing a DIRECTORY renders every ``flight-*.json`` in it — the shape an
elastic incident leaves behind (each surviving worker dumps
``peer_death`` when it detects the kill; each rejoined worker dumps
``rejoin`` after resuming from checkpoint), prefixed by a one-line
per-dump index. Exits non-zero on a file that is neither kind of dump.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _render_flight_dir(dirname: str, events, top) -> int:
    """Render every flight dump in an incident directory, newest last,
    with a one-line index first so the story (peer_death ... rejoin)
    reads before the detail."""
    import glob

    from paddle_tpu.observability.report import render_flight

    paths = sorted(glob.glob(os.path.join(dirname, "flight-*.json")))
    if not paths:
        print(f"metrics_report: no flight-*.json dumps in {dirname!r}",
              file=sys.stderr)
        return 1
    docs = []
    for path in paths:
        try:
            with open(path) as f:
                docs.append((path, json.load(f)))
        except (OSError, json.JSONDecodeError) as e:
            print(f"metrics_report: skipping {path!r}: {e}",
                  file=sys.stderr)
    docs.sort(key=lambda pd: pd[1].get("generated_unix", 0))
    print(f"{len(docs)} flight dump(s) in {dirname}:")
    for path, d in docs:
        ctx = d.get("context") or {}
        ctx_s = " ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
        print(f"  {os.path.basename(path)}  reason={d.get('reason')}  "
              f"pid={d.get('pid')}  {ctx_s}")
    for path, d in docs:
        print("\n" + "=" * 72)
        print(os.path.basename(path))
        print("=" * 72)
        n_events = (len(d.get("events") or []) if events is None
                    else events)
        try:
            print(render_flight(d, max_events=n_events, top=top))
        except ValueError as e:
            print(f"metrics_report: {path!r}: {e}", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump", help="JSON written by observability.dump(), a "
                                 "flight-recorder crash dump, or a "
                                 "directory of flight dumps")
    ap.add_argument("--events", type=int, default=None,
                    help="how many trailing events to show (default 20 for "
                         "metrics dumps, the full ring for flight dumps)")
    ap.add_argument("--top", type=int, default=None,
                    help="show only the N largest series per metric")
    args = ap.parse_args(argv)

    if os.path.isdir(args.dump):
        return _render_flight_dir(args.dump, args.events, args.top)

    try:
        with open(args.dump) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"metrics_report: cannot read {args.dump!r}: {e}",
              file=sys.stderr)
        return 1

    from paddle_tpu.observability.flight import FLIGHT_DUMP_KIND
    from paddle_tpu.observability.report import render_flight, render_report

    try:
        if isinstance(d, dict) and d.get("kind") == FLIGHT_DUMP_KIND:
            n_events = (len(d.get("events") or []) if args.events is None
                        else args.events)
            print(render_flight(d, max_events=n_events, top=args.top))
            return 0
        report = render_report(
            d, max_events=20 if args.events is None else args.events,
            top=args.top)
    except ValueError as e:
        print(f"metrics_report: {args.dump!r}: {e}", file=sys.stderr)
        return 1
    generated = d.get("generated_unix")
    if generated:
        import time

        print(f"metrics dump v{d.get('version', '?')} generated "
              f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(generated))}"
              f" (enabled={d.get('enabled')})\n")
    print(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
