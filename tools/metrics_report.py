#!/usr/bin/env python
"""Render a paddle_tpu.observability metrics dump as a human report.

Usage:
    python tools/metrics_report.py metrics.json [--events N] [--top N]
    python tools/metrics_report.py flight-1234-1.json   # flight dumps too
    python tools/metrics_report.py /tmp/flight_dir      # a whole incident
    python tools/metrics_report.py --fleet /tmp/fleet   # cross-rank view
    python tools/metrics_report.py --serve-trace /tmp/serve_trace
    python tools/metrics_report.py --opprof /tmp/opprof.json
    python tools/metrics_report.py --health metrics.json  # trend tables

Input is either the JSON written by ``paddle_tpu.observability.dump(path)``
(or any workload run with ``PADDLE_TPU_METRICS_DUMP=metrics.json``), or a
flight-recorder crash dump written to ``PADDLE_TPU_FLIGHT_DIR`` — the
kind is auto-detected. Metric rows come out grouped by subsystem
(``dispatch``, ``executor``, ``train``, ``comm``, ``elastic``, ...);
``--top`` keeps only the N largest series per metric. Rendering goes
through the same ``observability.report`` code the in-process
``summary()`` uses, so dumps round-trip by construction. The ``opt``
section leads with the lint->rewrite per-code fixed/remaining table,
and the ``cost`` section with the static cost model's
predicted-vs-measured FLOPs/peak-HBM and step-time tables
(``render_cost_table``) plus the per-collective predicted comm-cost
table (``render_comm_table``) — wire bytes and seconds per collective
kind, the decomposition behind ``cost.predicted_step_seconds``.

Passing a DIRECTORY renders every ``flight-*.json`` in it — the shape an
elastic incident leaves behind (each surviving worker dumps
``peer_death`` when it detects the kill; each rejoined worker dumps
``rejoin`` after resuming from checkpoint), prefixed by a one-line
per-dump index. Exits non-zero on a file that is neither kind of dump.

``--fleet <dir>`` renders a MULTI-PROCESS incident as one report: the
per-rank metric dumps the launcher writes (``metrics.rank<N>.json``),
flight dumps, and the launcher-side aggregated ``fleet_metrics.json``
become a per-rank step/skew summary, a merged metric table (counters
summed, gauges rank-labeled), the clock-aligned cross-rank event
interleaving and the flight-dump index
(``observability.fleet.render_incident``).

``--serve-trace <dir-or-file>`` renders the request-lifecycle trace a
``ServeTracer`` writes (``tools/serve_load.py --trace-out DIR``):
header, per-phase p50/p99 latency-attribution table, tail exemplars —
then runs the serve-trace lint (PTL404 decode-burst gaps, PTL405
preemption thrash), the serving analog of the ``--fleet`` PTL203 lint.

``--opprof <file>`` renders an op-level execution-profile dump
(``OpProfiler.dump()`` JSON): the top-K ops table of the last profiled
step — measured ms, predicted ms, measured/predicted drift, roofline %
against the device peak, and cumulative step share — then runs the
op-profile lint inline (PTL501 hot-op drift, PTL502 attribution
shortfall), the training-plane analog of ``--serve-trace``. ``--top``
bounds the table.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _render_flight_dir(dirname: str, events, top) -> int:
    """Render every flight dump in an incident directory, newest last,
    with a one-line index first so the story (peer_death ... rejoin)
    reads before the detail."""
    import glob

    from paddle_tpu.observability.report import render_flight

    paths = sorted(glob.glob(os.path.join(dirname, "flight-*.json")))
    if not paths:
        print(f"metrics_report: no flight-*.json dumps in {dirname!r}",
              file=sys.stderr)
        return 1
    docs = []
    for path in paths:
        try:
            with open(path) as f:
                docs.append((path, json.load(f)))
        except (OSError, json.JSONDecodeError) as e:
            print(f"metrics_report: skipping {path!r}: {e}",
                  file=sys.stderr)
    docs.sort(key=lambda pd: pd[1].get("generated_unix", 0))
    print(f"{len(docs)} flight dump(s) in {dirname}:")
    for path, d in docs:
        ctx = d.get("context") or {}
        ctx_s = " ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
        print(f"  {os.path.basename(path)}  reason={d.get('reason')}  "
              f"pid={d.get('pid')}  {ctx_s}")
    for path, d in docs:
        print("\n" + "=" * 72)
        print(os.path.basename(path))
        print("=" * 72)
        n_events = (len(d.get("events") or []) if events is None
                    else events)
        try:
            print(render_flight(d, max_events=n_events, top=top))
        except ValueError as e:
            print(f"metrics_report: {path!r}: {e}", file=sys.stderr)
    return 0


def _render_fleet_dir(dirname: str, events, top) -> int:
    """Render a fleet-telemetry incident directory (per-rank metric
    dumps + flight dumps + the aggregated fleet dump) as one report."""
    from paddle_tpu.observability.fleet import (load_incident_dir,
                                                render_incident)

    inc = load_incident_dir(dirname)
    if not inc["rank_dumps"] and inc["fleet"] is None \
            and not inc["flights"]:
        print(f"metrics_report: no per-rank dumps, fleet dump or flight "
              f"dumps in {dirname!r}", file=sys.stderr)
        return 1
    print(render_incident(inc, max_events=40 if events is None else events,
                          top=top))
    trace_path = os.path.join(dirname, "fleet_trace.json")
    if os.path.exists(trace_path):
        # cross-rank trace lint: collectives the schedule serializes
        # against compute (PTL203) read straight off the merged timeline
        from paddle_tpu.static.analysis import lint_fleet_trace

        try:
            with open(trace_path) as f:
                report = lint_fleet_trace(json.load(f))
        except (OSError, json.JSONDecodeError) as e:
            print(f"metrics_report: cannot lint {trace_path!r}: {e}",
                  file=sys.stderr)
        else:
            print()
            print(report.render(
                f"fleet trace lint ({os.path.basename(trace_path)}):"))
    return 0


def _render_serve_trace(path: str) -> int:
    """Render one serve_trace dump (a ``serve_requests.json`` file or
    the ``--trace-out`` directory holding it) + the PTL404/PTL405 lint."""
    from paddle_tpu.observability.tracing import render_serve_trace
    from paddle_tpu.static.analysis import lint_serve_trace

    if os.path.isdir(path):
        path = os.path.join(path, "serve_requests.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"metrics_report: cannot read {path!r}: {e}",
              file=sys.stderr)
        return 1
    try:
        print(render_serve_trace(doc))
        report = lint_serve_trace(doc)
    except ValueError as e:
        print(f"metrics_report: {path!r}: {e}", file=sys.stderr)
        return 1
    print()
    print(report.render(
        f"serve trace lint ({os.path.basename(path)}):"))
    return 0


def _render_opprof(path: str, top) -> int:
    """Render one op-profile dump (``OpProfiler.dump()`` JSON) + the
    PTL501/PTL502 lint over every retained profile."""
    from paddle_tpu.observability.opprof import (lint_op_profile,
                                                 render_op_profile)

    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"metrics_report: cannot read {path!r}: {e}",
              file=sys.stderr)
        return 1
    try:
        print(render_op_profile(doc, top=10 if top is None else top))
    except ValueError as e:
        print(f"metrics_report: {path!r}: {e}", file=sys.stderr)
        return 1
    from paddle_tpu.static.analysis.diagnostics import DiagnosticReport

    report = DiagnosticReport()
    for p in doc.get("profiles") or ():
        report.extend(lint_op_profile(p))
    print()
    print(report.render(
        f"op profile lint ({os.path.basename(path)}):"))
    return 0


def _render_health(path: str) -> int:
    """Render the health view of a dump: recorded time-series trend
    tables + sparklines, alerts, and the latched ``health.alerts``
    counts. Accepts a metrics dump from a ``PADDLE_TPU_HEALTH`` run, a
    ``health_alert`` flight dump (or a directory of flight dumps, the
    health ones selected), or a fleet_metrics.json with per-rank
    lanes."""
    from paddle_tpu.observability.flight import FLIGHT_DUMP_KIND
    from paddle_tpu.observability.report import render_health

    paths = [path]
    if os.path.isdir(path):
        import glob

        fleet_dump = os.path.join(path, "fleet_metrics.json")
        paths = sorted(glob.glob(os.path.join(path, "flight-*.json")))
        if os.path.exists(fleet_dump):
            paths.insert(0, fleet_dump)
        if not paths:
            print(f"metrics_report: no flight-*.json or "
                  f"fleet_metrics.json in {path!r}", file=sys.stderr)
            return 1
    shown = 0
    for p in paths:
        try:
            with open(p) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            print(f"metrics_report: cannot read {p!r}: {e}",
                  file=sys.stderr)
            return 1
        if (isinstance(d, dict) and d.get("kind") == FLIGHT_DUMP_KIND
                and d.get("reason") != "health_alert"):
            continue  # directory mode: only health dumps are relevant
        if shown:
            print("\n" + "=" * 72)
        if len(paths) > 1:
            print(f"{os.path.basename(p)}:")
        print(render_health(d))
        shown += 1
    if not shown:
        print(f"metrics_report: no health_alert dumps under {path!r}",
              file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump", help="JSON written by observability.dump(), a "
                                 "flight-recorder crash dump, or a "
                                 "directory of flight dumps")
    ap.add_argument("--events", type=int, default=None,
                    help="how many trailing events to show (default 20 for "
                         "metrics dumps, the full ring for flight dumps)")
    ap.add_argument("--top", type=int, default=None,
                    help="show only the N largest series per metric")
    ap.add_argument("--fleet", action="store_true",
                    help="treat the path as a fleet incident directory: "
                         "per-rank metric dumps + flight dumps + the "
                         "launcher's fleet_metrics.json rendered as one "
                         "cross-rank report")
    ap.add_argument("--serve-trace", action="store_true",
                    help="treat the path as a ServeTracer dump "
                         "(serve_requests.json or the --trace-out dir): "
                         "per-phase breakdown + tail exemplars + the "
                         "PTL404/PTL405 serve-trace lint")
    ap.add_argument("--opprof", action="store_true",
                    help="treat the path as an op-profile dump "
                         "(OpProfiler.dump() JSON): top-K ops table "
                         "(measured/predicted ms, drift, roofline %%, "
                         "cumulative step share) + the PTL501/PTL502 "
                         "op-profile lint")
    ap.add_argument("--health", action="store_true",
                    help="health view: recorded metric time-series as "
                         "trend tables + sparklines, fired alerts and "
                         "latched health.alerts counts (metrics dump "
                         "from a PADDLE_TPU_HEALTH run, a health_alert "
                         "flight dump/directory, or fleet_metrics.json "
                         "per-rank lanes)")
    args = ap.parse_args(argv)

    if args.health:
        return _render_health(args.dump)

    if args.opprof:
        return _render_opprof(args.dump, args.top)

    if args.serve_trace:
        return _render_serve_trace(args.dump)

    if args.fleet:
        if not os.path.isdir(args.dump):
            print(f"metrics_report: --fleet needs a directory, got "
                  f"{args.dump!r}", file=sys.stderr)
            return 1
        return _render_fleet_dir(args.dump, args.events, args.top)

    if os.path.isdir(args.dump):
        return _render_flight_dir(args.dump, args.events, args.top)

    try:
        with open(args.dump) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"metrics_report: cannot read {args.dump!r}: {e}",
              file=sys.stderr)
        return 1

    from paddle_tpu.observability.flight import FLIGHT_DUMP_KIND
    from paddle_tpu.observability.report import render_flight, render_report

    try:
        if isinstance(d, dict) and d.get("kind") == FLIGHT_DUMP_KIND:
            n_events = (len(d.get("events") or []) if args.events is None
                        else args.events)
            print(render_flight(d, max_events=n_events, top=args.top))
            return 0
        report = render_report(
            d, max_events=20 if args.events is None else args.events,
            top=args.top)
    except ValueError as e:
        print(f"metrics_report: {args.dump!r}: {e}", file=sys.stderr)
        return 1
    generated = d.get("generated_unix")
    if generated:
        import time

        print(f"metrics dump v{d.get('version', '?')} generated "
              f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(generated))}"
              f" (enabled={d.get('enabled')})\n")
    print(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
