#!/usr/bin/env python
"""Render a paddle_tpu.observability metrics dump as a human report.

Usage:
    python tools/metrics_report.py metrics.json [--events N] [--top N]
    python tools/metrics_report.py flight-1234-1.json   # flight dumps too

Input is either the JSON written by ``paddle_tpu.observability.dump(path)``
(or any workload run with ``PADDLE_TPU_METRICS_DUMP=metrics.json``), or a
flight-recorder crash dump written to ``PADDLE_TPU_FLIGHT_DIR`` — the
kind is auto-detected. Metric rows come out grouped by subsystem
(``dispatch``, ``executor``, ``train``, ``comm``, ``io``, ...); ``--top``
keeps only the N largest series per metric. Rendering goes through the
same ``observability.report`` code the in-process ``summary()`` uses, so
dumps round-trip by construction. Exits non-zero on a file that is
neither kind of dump.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump", help="JSON written by observability.dump() or "
                                 "a flight-recorder crash dump")
    ap.add_argument("--events", type=int, default=None,
                    help="how many trailing events to show (default 20 for "
                         "metrics dumps, the full ring for flight dumps)")
    ap.add_argument("--top", type=int, default=None,
                    help="show only the N largest series per metric")
    args = ap.parse_args(argv)

    try:
        with open(args.dump) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"metrics_report: cannot read {args.dump!r}: {e}",
              file=sys.stderr)
        return 1

    from paddle_tpu.observability.flight import FLIGHT_DUMP_KIND
    from paddle_tpu.observability.report import render_flight, render_report

    try:
        if isinstance(d, dict) and d.get("kind") == FLIGHT_DUMP_KIND:
            n_events = (len(d.get("events") or []) if args.events is None
                        else args.events)
            print(render_flight(d, max_events=n_events, top=args.top))
            return 0
        report = render_report(
            d, max_events=20 if args.events is None else args.events,
            top=args.top)
    except ValueError as e:
        print(f"metrics_report: {args.dump!r}: {e}", file=sys.stderr)
        return 1
    generated = d.get("generated_unix")
    if generated:
        import time

        print(f"metrics dump v{d.get('version', '?')} generated "
              f"{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(generated))}"
              f" (enabled={d.get('enabled')})\n")
    print(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
