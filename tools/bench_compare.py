#!/usr/bin/env python
"""Compare two BENCH records and gate CI on perf regressions (PTL605).

The repo's BENCH_r*.json records (and any ``python bench.py`` stdout)
carry one machine-readable line per benchmark config::

    {"metric": "resnet50 25M ... images/sec/chip (...)", "value": 330.2,
     "unit": "images/sec/chip", "vs_baseline": 1.05}

This tool extracts those lines from a *baseline* and a *current*
record, matches configs by the metric's leading word (``resnet50``,
``bert-base``, ``sdxl-unet``, ...), derives the goodness direction from
the unit (``ms/step`` lower-is-better, ``*/sec*`` higher-is-better),
and compares the per-config delta against a noise band. A config whose
headline metric moved beyond the band *in the bad direction* files a
PTL605 diagnostic and the process exits nonzero — turning the
flat-since-r03 BENCH trajectory into an enforced gate instead of a
directory of unread JSON.

Usage:
    python tools/bench_compare.py BENCH_r04.json BENCH_r05.json
    python tools/bench_compare.py --noise-pct 3 old.json new.json
    python tools/bench_compare.py --latest      # two newest BENCH_r*.json
    python tools/bench_compare.py --json ...    # machine-readable output

Exit codes: 0 = clean (including a missing/empty baseline — a first
record has nothing to regress against), 1 = at least one regression,
2 = usage/input error.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

#: default noise band, percent: smaller moves are run-to-run jitter.
DEFAULT_NOISE_PCT = 5.0

_METRIC_LINE_RE = re.compile(r'^\{"metric":.*\}\s*$', re.MULTILINE)


def _direction(unit: str) -> str:
    """'higher' or 'lower' is better, from the unit string."""
    u = (unit or "").lower()
    if "/sec" in u or "per_sec" in u or "mfu" in u:
        return "higher"
    return "lower"  # ms/step, seconds, bytes, ...


def extract_results(doc) -> Dict[str, Dict[str, Any]]:
    """Per-config benchmark results from a BENCH record.

    Accepts a BENCH_r*.json dict (metric lines ride the ``tail`` text),
    raw ``bench.py`` stdout text, or an already-extracted list of
    ``{"metric", "value", "unit"}`` dicts. Returns ``{config: row}``
    keyed by the metric string's first word; a config appearing twice
    keeps the LAST line (reruns supersede)."""
    rows: List[Dict[str, Any]] = []
    if isinstance(doc, dict) and "metric" in doc:
        rows = [doc]
    elif isinstance(doc, list):
        rows = [r for r in doc if isinstance(r, dict) and "metric" in r]
    else:
        text = doc.get("tail", "") if isinstance(doc, dict) else str(doc)
        for line in _METRIC_LINE_RE.findall(text):
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(d, dict) and "metric" in d and "value" in d:
                rows.append(d)
    out: Dict[str, Dict[str, Any]] = {}
    for r in rows:
        name = str(r.get("metric", "")).split()
        if not name:
            continue
        try:
            value = float(r["value"])
        except (KeyError, TypeError, ValueError):
            continue
        out[name[0]] = {"metric": r["metric"], "value": value,
                        "unit": str(r.get("unit", ""))}
    return out


def compare_docs(baseline, current, *,
                 noise_pct: float = DEFAULT_NOISE_PCT
                 ) -> List[Dict[str, Any]]:
    """Per-config comparison rows, sorted worst-first.

    Each row: ``config``, ``unit``, ``direction``, ``baseline``,
    ``current``, ``delta_pct`` (sign: positive = metric went up),
    ``status`` in {"ok", "regressed", "improved", "new", "dropped"}.
    ``delta_pct`` is None for new/dropped configs."""
    base = extract_results(baseline)
    cur = extract_results(current)
    out: List[Dict[str, Any]] = []
    for config in sorted(set(base) | set(cur)):
        b, c = base.get(config), cur.get(config)
        row: Dict[str, Any] = {
            "config": config,
            "unit": (c or b or {}).get("unit", ""),
            "direction": _direction((c or b or {}).get("unit", "")),
            "baseline": b["value"] if b else None,
            "current": c["value"] if c else None,
            "delta_pct": None,
        }
        if b is None:
            row["status"] = "new"
        elif c is None:
            row["status"] = "dropped"
        elif b["value"] == 0:
            row["status"] = "ok"  # nothing sane to divide by
        else:
            delta = 100.0 * (c["value"] - b["value"]) / abs(b["value"])
            row["delta_pct"] = round(delta, 3)
            bad = -delta if row["direction"] == "higher" else delta
            good = -bad
            if bad > noise_pct:
                row["status"] = "regressed"
            elif good > noise_pct:
                row["status"] = "improved"
            else:
                row["status"] = "ok"
        out.append(row)

    def worst_key(r):
        if r["status"] != "regressed" or r["delta_pct"] is None:
            return 0.0
        return -(abs(r["delta_pct"]))

    out.sort(key=lambda r: (worst_key(r), r["config"]))
    return out


def regression_report(rows: List[Dict[str, Any]], *,
                      baseline_name: str = "baseline",
                      current_name: str = "current",
                      noise_pct: float = DEFAULT_NOISE_PCT):
    """A DiagnosticReport carrying one PTL605 per regressed config."""
    from paddle_tpu.static.analysis.diagnostics import (DiagnosticReport,
                                                        Severity)

    report = DiagnosticReport()
    for r in rows:
        if r["status"] != "regressed":
            continue
        worse = (f"{r['delta_pct']:+.2f}%"
                 if r["delta_pct"] is not None else "?")
        report.add(
            "PTL605", Severity.WARNING,
            f"BENCH regression: {r['config']} {r['unit']} moved {worse} "
            f"({r['baseline']:g} -> {r['current']:g}, "
            f"{r['direction']}-is-better, noise band "
            f"{noise_pct:g}%) from {baseline_name} to {current_name}",
            hint="rerun the config to rule out machine noise, then "
                 "bisect the commits between the two BENCH records",
            suggestion={"config": r["config"], "unit": r["unit"],
                        "baseline": r["baseline"],
                        "current": r["current"],
                        "delta_pct": r["delta_pct"],
                        "noise_pct": noise_pct})
    return report


def _load(path: str):
    with open(path) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        return text  # raw bench.py stdout: metric lines extracted as-is


def latest_bench_records(root: str = _REPO_ROOT) -> List[str]:
    """The BENCH_r*.json paths in record order (r01, r02, ...)."""
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))


def render_rows(rows: List[Dict[str, Any]],
                noise_pct: float) -> str:
    header = (f"{'Config':<14}{'Baseline':>12}{'Current':>12}"
              f"{'Delta':>10}{'Better':>8}  Status")
    lines = [header, "-" * len(header)]
    for r in rows:
        delta = (f"{r['delta_pct']:+.2f}%" if r["delta_pct"] is not None
                 else "-")
        fmt_v = lambda v: f"{v:g}" if v is not None else "-"
        lines.append(f"{r['config'][:14]:<14}{fmt_v(r['baseline']):>12}"
                     f"{fmt_v(r['current']):>12}{delta:>10}"
                     f"{r['direction']:>8}  {r['status']}")
    n_reg = sum(1 for r in rows if r["status"] == "regressed")
    lines.append(f"{n_reg} regression(s) beyond the {noise_pct:g}% "
                 f"noise band")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("baseline", nargs="?",
                    help="baseline BENCH record (JSON or raw bench.py "
                         "stdout)")
    ap.add_argument("current", nargs="?",
                    help="current BENCH record to gate")
    ap.add_argument("--latest", action="store_true",
                    help="compare the two newest BENCH_r*.json in the "
                         "repo root")
    ap.add_argument("--noise-pct", type=float, default=DEFAULT_NOISE_PCT,
                    help="ignore moves within this band (default "
                         f"{DEFAULT_NOISE_PCT:g}%%)")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison as one JSON document")
    args = ap.parse_args(argv)

    if args.latest:
        records = latest_bench_records()
        if len(records) < 2:
            print("bench_compare: fewer than two BENCH_r*.json records "
                  "— nothing to compare (not a failure)")
            return 0
        base_path, cur_path = records[-2], records[-1]
    elif args.baseline and args.current:
        base_path, cur_path = args.baseline, args.current
    else:
        ap.print_usage(sys.stderr)
        print("bench_compare: need BASELINE and CURRENT (or --latest)",
              file=sys.stderr)
        return 2

    if not os.path.exists(cur_path):
        print(f"bench_compare: current record {cur_path!r} missing",
              file=sys.stderr)
        return 2
    if not os.path.exists(base_path):
        # a first record has nothing to regress against — note and pass
        print(f"bench_compare: baseline {base_path!r} missing — "
              f"nothing to compare (not a failure)")
        return 0

    try:
        rows = compare_docs(_load(base_path), _load(cur_path),
                            noise_pct=args.noise_pct)
    except (OSError, ValueError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2
    if not rows:
        print(f"bench_compare: no benchmark metric lines found in "
              f"{base_path!r}/{cur_path!r}", file=sys.stderr)
        return 2

    report = regression_report(
        rows, baseline_name=os.path.basename(base_path),
        current_name=os.path.basename(cur_path),
        noise_pct=args.noise_pct)
    regressed = len(report) > 0

    if args.json:
        print(json.dumps({
            "baseline": base_path, "current": cur_path,
            "noise_pct": args.noise_pct, "rows": rows,
            "regressed": regressed,
            "diagnostics": [
                {"code": d.code, "severity": str(d.severity),
                 "message": d.message, "suggestion": d.suggestion}
                for d in report],
        }, indent=1))
    else:
        print(f"bench_compare: {os.path.basename(base_path)} -> "
              f"{os.path.basename(cur_path)}")
        print(render_rows(rows, args.noise_pct))
        if regressed:
            print()
            print(report.render("bench_compare:"))
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
