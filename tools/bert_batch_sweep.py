"""Re-measure the BERT batch curve bs 32-48 with the CURRENT kernel
(in-kernel flash-attention dropout included) — round-4 verdict Weak #6:
the shipped bs=36 choice rested on a sweep whose bs>=40 points predated
in-kernel dropout. One subprocess per point (fresh TPU client), same
isolation as bench.py --config bert.

Run: python tools/bert_batch_sweep.py [--steps N]
"""
import argparse
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=12)
    ap.add_argument("--batches", default="32,36,40,44,48")
    args = ap.parse_args()
    results = {}
    for bs in (int(b) for b in args.batches.split(",")):
        env = dict(os.environ, PTPU_BENCH_BERT_BS=str(bs))
        proc = subprocess.run(
            [sys.executable, os.path.join(HERE, "bench.py"),
             "--config", "bert", "--steps", str(args.steps)],
            env=env, capture_output=True, text=True, timeout=3600)
        line = None
        for ln in proc.stdout.splitlines():
            try:
                d = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if "metric" in d:
                line = d
        if line is None:
            print(f"bs={bs}: FAILED rc={proc.returncode}\n"
                  f"{proc.stderr[-500:]}", flush=True)
            continue
        import re

        m = re.search(r"mfu=([0-9.]+)", line["metric"])
        results[bs] = {"seq_per_s": line["value"],
                       "mfu": float(m.group(1)) if m else None}
        print(f"bs={bs}: {line['value']} seq/s, mfu={results[bs]['mfu']}",
              flush=True)
    print(json.dumps({"bert_batch_sweep": results}), flush=True)


if __name__ == "__main__":
    main()
