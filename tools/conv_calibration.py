"""ResNet-50 conv ceiling calibration on this chip.

Answers the question "would a Pallas implicit-GEMM conv beat the XLA
conv lowering?" with measurements instead of conjecture, per the three
bounds that order any conv implementation on a TPU:

  conv_tf      — what XLA's conv_general_dilated actually achieves at
                 each ResNet-50 shape (the current bench path);
  gemm_tf      — the SAME arithmetic expressed as its implicit-GEMM
                 matmul [M=N*H*W, K=C_in*kh*kw] x [K, C_out] via XLA's
                 matmul emitter: an UPPER bound for any matmul-based
                 conv kernel, because an implicit-GEMM kernel does this
                 matmul PLUS in-VMEM patch assembly and halo handling;
  pallas_tf    — a naively-tiled Pallas matmul at the same shape: what
                 hand-written Mosaic achieves without deep tuning (on
                 this stack it trails the XLA emitter even on pure
                 GEMMs — see bench history).

Run: python tools/conv_calibration.py [--iters 30] (or --shape i to
measure one shape per process — the remote-compile tunnel occasionally
hangs, so a driving shell should give each shape its own timeout).
Prints a per-shape table and the FLOP-weighted ResNet-50 forward bound.

MEASURED CONCLUSION (v5e, bf16, batch 64, 20-iter carry-chained scans,
2026-07-31 — the round-3 calibration this module exists to reproduce):

    shape                      conv lowering   implicit-GEMM bound
    64x56x56  -> 64  3x3       3.4 TF/s        3.3 TF/s  [M=200704,K=576,N=64]
    128x28x28 -> 128 3x3       4.1 TF/s        3.4 TF/s  [M=50176,K=1152,N=128]
    512x7x7   -> 512 3x3       2.5 TF/s        3.8 TF/s  [M=3136,K=4608,N=512]
    64x56x56  -> 256 1x1       1.6 TF/s        1.5 TF/s  [M=200704,K=64,N=256]

The conv lowering is ALREADY at (or above) the throughput of its own
implicit-GEMM formulation: ResNet's K=64..4608 / N=64..512 GEMM shapes
sit at the floor of this chip's width-scaling curve (same harness:
[16k,2048]x[2048,W] reaches 115 TF/s at W=5632 but 49 at W=1408 — and
collapses to single digits at the K/N widths conv produces). A Pallas
implicit-GEMM conv is bounded by its inner matmul plus patch-assembly
and halo overheads, and a naively-tiled Pallas matmul measures ~30%
BELOW the XLA emitter on this stack (36 vs 52 TF/s at the MoE expert
shape). Therefore the bench's ResNet-50 MFU (~0.13 end-to-end, within
the 0.12-0.19 bare-conv band measured in round 2) is this chip's
ceiling for conv-shaped arithmetic in any matmul-based formulation —
not a lowering deficiency a custom kernel could bypass. The chip's MXU
wants wide GEMMs; ResNet at 224px does not produce them.
"""
from __future__ import annotations

import argparse
import time

import numpy as np

# (C_in, H, W, C_out, kernel, stride, count_in_resnet50)
RESNET50_CONVS = [
    (3, 224, 224, 64, 7, 2, 1),      # stem
    (64, 56, 56, 64, 1, 1, 1),       # conv2 reduce (first block)
    (64, 56, 56, 64, 3, 1, 3),       # conv2 3x3
    (64, 56, 56, 256, 1, 1, 4),      # conv2 expand (+projection)
    (256, 56, 56, 64, 1, 1, 2),
    (256, 56, 56, 128, 1, 1, 1),
    (128, 56, 56, 128, 3, 2, 1),     # conv3 entry stride
    (128, 28, 28, 128, 3, 1, 3),
    (128, 28, 28, 512, 1, 1, 5),
    (512, 28, 28, 128, 1, 1, 3),
    (512, 28, 28, 256, 1, 1, 1),
    (256, 28, 28, 256, 3, 2, 1),
    (256, 14, 14, 256, 3, 1, 5),
    (256, 14, 14, 1024, 1, 1, 7),
    (1024, 14, 14, 256, 1, 1, 5),
    (1024, 14, 14, 512, 1, 1, 1),
    (512, 14, 14, 512, 3, 2, 1),
    (512, 7, 7, 512, 3, 1, 2),
    (512, 7, 7, 2048, 1, 1, 4),
    (2048, 7, 7, 512, 1, 1, 2),
]


def _timed(fn, x0, iters, tries=3):
    import jax
    import jax.numpy as jnp

    def body(carry, _):
        y = fn((x0 * (1.0 + carry)).astype(x0.dtype))
        s = (jnp.mean(y.astype(jnp.float32)) * 1e-12).astype(jnp.float32)
        return s, ()

    g = jax.jit(
        lambda: jax.lax.scan(body, jnp.float32(0), jnp.arange(iters))[0])
    for attempt in range(tries):
        try:
            r = g()
            r.block_until_ready()
            t0 = time.perf_counter()
            float(g())
            return (time.perf_counter() - t0) / iters
        except Exception:
            if attempt == tries - 1:
                raise
            time.sleep(10)


def measure_shape(cin, h, w, cout, kk, stride, batch, iters):
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    rng = np.random.RandomState(0)
    ho, wo = h // stride, w // stride
    flops = 2.0 * batch * ho * wo * cout * cin * kk * kk

    # --- XLA conv (NCHW, same-padding) ---
    x = jnp.asarray(rng.randn(batch, cin, h, w), jnp.bfloat16)
    wgt = jnp.asarray(rng.randn(cout, cin, kk, kk) * 0.05, jnp.bfloat16)
    pad = ((kk // 2, kk // 2),) * 2

    def conv(xx):
        return jax.lax.conv_general_dilated(
            xx, wgt, (stride, stride), pad,
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    t_conv = _timed(conv, x, iters)

    # --- implicit-GEMM equivalent via the XLA matmul emitter ---
    m = batch * ho * wo
    k = cin * kk * kk
    a = jnp.asarray(rng.randn(m, k), jnp.bfloat16)
    b = jnp.asarray(rng.randn(k, cout) * 0.05, jnp.bfloat16)
    t_gemm = _timed(lambda aa: aa @ b, a, iters)

    # --- naively-tiled Pallas matmul at the same shape ---
    t_pallas = None
    bm = 512
    kp = ((k + 127) // 128) * 128
    np_ = ((cout + 127) // 128) * 128
    if m % bm == 0 and (bm * kp + kp * np_ + bm * np_) * 2 * 2 < 14e6:
        ap = jnp.zeros((m, kp), jnp.bfloat16).at[:, :k].set(a)
        bp = jnp.zeros((kp, np_), jnp.bfloat16).at[:k, :cout].set(b)

        def mk(x_ref, w_ref, o_ref):
            o_ref[...] = jnp.dot(
                x_ref[...], w_ref[...],
                preferred_element_type=jnp.float32).astype(o_ref.dtype)

        def pallas_mm(aa):
            return pl.pallas_call(
                mk, grid=(m // bm,),
                in_specs=[pl.BlockSpec((bm, kp), lambda i: (i, 0)),
                          pl.BlockSpec((kp, np_), lambda i: (0, 0))],
                out_specs=pl.BlockSpec((bm, np_), lambda i: (i, 0)),
                out_shape=jax.ShapeDtypeStruct((m, np_), aa.dtype),
            )(aa)

        try:
            t_pallas = _timed(pallas_mm, ap, iters)
        except Exception:
            t_pallas = None

    return flops, t_conv, t_gemm, t_pallas


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--shape", type=int, default=None,
                    help="measure only RESNET50_CONVS[i] (emit one "
                         "json line) — lets a driving shell give each "
                         "shape its own timeout against tunnel hangs")
    args = ap.parse_args()

    if args.shape is not None:
        import json

        cin, h, w, cout, kk, stride, cnt = RESNET50_CONVS[args.shape]
        flops, t_conv, t_gemm, t_pal = measure_shape(
            cin, h, w, cout, kk, stride, args.batch, args.iters)
        print(json.dumps({
            "desc": f"{cin}x{h}x{w}->{cout} k{kk}s{stride}",
            "flops": flops, "count": cnt, "t_conv": t_conv,
            "t_gemm": t_gemm, "t_pallas": t_pal}), flush=True)
        return

    peak = 197e12
    rows = []
    tot_flops = tot_conv = tot_gemm = 0.0
    print(f"{'shape':>34} | {'conv TF/s':>9} | {'gemm TF/s':>9} | "
          f"{'pallas':>7} | count")
    for cin, h, w, cout, kk, stride, cnt in RESNET50_CONVS:
        flops, t_conv, t_gemm, t_pal = measure_shape(
            cin, h, w, cout, kk, stride, args.batch, args.iters)
        conv_tf = flops / t_conv / 1e12
        gemm_tf = flops / t_gemm / 1e12
        pal_tf = flops / t_pal / 1e12 if t_pal else float("nan")
        desc = f"{cin}x{h}x{w}->{cout} k{kk}s{stride}"
        print(f"{desc:>34} | {conv_tf:9.1f} | {gemm_tf:9.1f} | "
              f"{pal_tf:7.1f} | x{cnt}", flush=True)
        rows.append((desc, conv_tf, gemm_tf, pal_tf, cnt))
        tot_flops += flops * cnt
        tot_conv += t_conv * cnt
        tot_gemm += t_gemm * cnt
    conv_mfu = tot_flops / tot_conv / peak
    gemm_mfu = tot_flops / tot_gemm / peak
    print(f"\nFLOP-weighted ResNet-50 fwd: conv lowering MFU "
          f"{conv_mfu:.3f}; implicit-GEMM matmul UPPER BOUND MFU "
          f"{gemm_mfu:.3f} (a real conv kernel lands below it: patch "
          f"assembly + halos come out of the same budget)")


if __name__ == "__main__":
    main()
