"""GEMM width-scaling calibration on this chip.

Measures achieved TF/s of bf16 ``[M, K] x [K, W]`` as the output width
W varies — the curve that explains most single-chip MFU differences in
this repo (llama 0.695 at W=5632 FFN widths vs MoE 0.546 at W=1408
expert widths vs resnet 0.131 at conv-class widths), feeds the
auto-tuner's cost model (distributed/auto_tuner width_efficiency), and
motivated the measured-null experiments recorded in
models/llama.py (fused_qkv) and incubate .../moe/moe_layer.py (swiglu).

MEASURED RECORD (v5e, bf16, M=16384, K=2048, 50-iter carry-chained
scan, round-3, reproduced by this tool):

    W=5632 -> 115 TF/s      W=2816 -> 72      W=1536 -> 59
    W=1408 -> 49            (single digits at conv-class widths)

Protocol notes (hard-won, see memory of rounds 2-3):
- ALWAYS carry-chain the iterations inside one ``lax.scan`` — timing a
  Python loop of independent matmuls lets XLA hoist the op out of the
  loop and reports fantasy numbers;
- >= 30 iterations, because the tunneled per-call latency (~1s) must be
  amortized; use ``--iters`` to raise further on a flaky tunnel;
- a driving shell should give each width its own process/timeout — the
  remote-compile tunnel occasionally hangs (HTTP 500 / broken pipe).

Run: python tools/gemm_width_calibration.py [--widths 1408,2816,5632]
[--m 16384] [--k 2048] [--iters 50]
"""
from __future__ import annotations

import argparse
import time

import numpy as np


def measure_width(m: int, k: int, w: int, iters: int) -> float:
    """Achieved TF/s of [m,k]x[k,w] bf16, carry-chained over iters."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (m, k), jnp.bfloat16)
    a = jax.random.normal(key, (k, w), jnp.bfloat16)
    b = jax.random.normal(key, (w, k), jnp.bfloat16) * 0.01

    def body(carry, _):
        # carry-chain through BOTH matmuls so no iteration is hoistable;
        # the [w,k] bounce keeps the operand of interest at width w
        h = jnp.dot(carry, a, preferred_element_type=jnp.bfloat16)
        return jnp.dot(h, b, preferred_element_type=jnp.bfloat16), ()

    @jax.jit
    def run(x0):
        out, _ = lax.scan(body, x0, None, length=iters)
        return out

    run(x).block_until_ready()          # compile
    t0 = time.perf_counter()
    out = run(x)
    np.asarray(out[0, 0])               # full sync through the tunnel
    dt = time.perf_counter() - t0
    flops = 2.0 * m * k * w * iters + 2.0 * m * w * k * iters
    return flops / dt / 1e12


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--widths", default="1408,1536,2816,5632")
    ap.add_argument("--m", type=int, default=16384)
    ap.add_argument("--k", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=50)
    args = ap.parse_args()

    import jax

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    print(f"# device: {jax.devices()[0]}, "
          f"{'REAL accelerator' if on_tpu else 'CPU (numbers meaningless)'}")
    print(f"# [M={args.m}, K={args.k}] x [K, W] bf16, "
          f"{args.iters}-iter carry-chained scan")
    for w in (int(s) for s in args.widths.split(",")):
        tf = measure_width(args.m, args.k, w, args.iters)
        print(f"W={w:<6d} {tf:7.1f} TF/s")


if __name__ == "__main__":
    main()
