"""GEMM width-scaling calibration on this chip.

Measures achieved TF/s of bf16 ``[M, K] x [K, W]`` as the output width
W varies — the curve that explains most single-chip MFU differences in
this repo (llama 0.695 at W=5632 FFN widths vs MoE 0.546 at W=1408
expert widths vs resnet 0.131 at conv-class widths), feeds the
auto-tuner's cost model (distributed/auto_tuner width_efficiency), and
motivated the measured-null experiments recorded in
models/llama.py (fused_qkv) and incubate .../moe/moe_layer.py (swiglu).

MEASURED RECORDS (v5e, bf16, M=16384, K=2048):

    round-3 harness (bounce-chained pair, counts both GEMMs):
        W=5632 -> 115 TF/s   W=2816 -> 72   W=1536 -> 59   W=1408 -> 49
    this tool (pool-of-8 cycled inputs, single GEMM, 2026-07-31):
        W=5632 -> 68         W=2816 -> ~43  W=1536 -> ~28  W=1408 -> 34

ABSOLUTE TF/s is protocol-dependent (the bounce variant amortizes
operand traffic across two GEMMs; this tool streams a fresh [M,K]
per iteration). The LOAD-BEARING, protocol-INVARIANT fact is the
monotone collapse with output width — 2-2.9x between W=5632 and
W=1408 across protocols, 2.3x in the round-3 record — which is what the auto-tuner's
width_efficiency ranking and the MoE/conv ceiling analyses consume
(all relative). Single digits at conv-class widths under every
protocol tried.

Protocol notes (hard-won, see rounds 2-4):
- NEVER time independent iterations inside one jit without data
  dependence or per-iter inputs: XLA hoists/CSEs the op and reports
  fantasy numbers (a multiply-by-zero dependency gets folded too —
  183 "TF/s" was measured that way);
- a bounce-chain ([K,W] then [W,K]) measures the PAIR and goes
  pathological at some widths (6 TF/s at W=1408);
- >= 30 iterations, because the tunneled per-call latency (~1s) must be
  amortized; use ``--iters`` to raise further on a flaky tunnel;
- a driving shell should give each width its own process/timeout — the
  remote-compile tunnel occasionally hangs (HTTP 500 / broken pipe).

Run: python tools/gemm_width_calibration.py [--widths 1408,2816,5632]
[--m 16384] [--k 2048] [--iters 50]
"""
from __future__ import annotations

import argparse
import time

import numpy as np


def measure_width(m: int, k: int, w: int, iters: int) -> float:
    """Achieved TF/s of [m,k]x[k,w] bf16, carry-chained over iters."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    key = jax.random.PRNGKey(0)
    # DISTINCT input per iteration, consumed by lax.scan: XLA cannot
    # hoist or CSE any matmul (each sees fresh data), and no auxiliary
    # GEMM pollutes the number (an earlier [w,k] bounce-chain variant
    # measured pathological at some widths). The per-iter max-reduction
    # keeps only a scalar live; its cost is O(m*w) reads ≪ 2*m*k*w.
    # A small cycled POOL (not one buffer per iteration) keeps HBM
    # bounded however high --iters goes on a flaky tunnel.
    pool = 8
    xs = jax.random.normal(key, (pool, m, k), jnp.bfloat16)
    a = jax.random.normal(key, (k, w), jnp.bfloat16)

    @jax.jit
    def run(xs_in):
        global_idx = jnp.arange(iters) % pool

        def body(carry, idx):
            h = jnp.dot(xs_in[idx], a,
                        preferred_element_type=jnp.bfloat16)
            return carry, jnp.max(h)

        _, outs = lax.scan(body, jnp.bfloat16(0.0), global_idx)
        return outs

    run(xs).block_until_ready()         # compile
    t0 = time.perf_counter()
    out = run(xs)
    np.asarray(out)                     # full sync through the tunnel
    dt = time.perf_counter() - t0
    flops = 2.0 * m * k * w * iters
    return flops / dt / 1e12


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--widths", default="1408,1536,2816,5632")
    ap.add_argument("--m", type=int, default=16384)
    ap.add_argument("--k", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=50)
    args = ap.parse_args()

    import jax

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    print(f"# device: {jax.devices()[0]}, "
          f"{'REAL accelerator' if on_tpu else 'CPU (numbers meaningless)'}")
    print(f"# [M={args.m}, K={args.k}] x [K, W] bf16, "
          f"{args.iters}-iter carry-chained scan")
    for w in (int(s) for s in args.widths.split(",")):
        tf = measure_width(args.m, args.k, w, args.iters)
        print(f"W={w:<6d} {tf:7.1f} TF/s")


if __name__ == "__main__":
    main()
