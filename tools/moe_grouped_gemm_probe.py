"""Measure batched-einsum vs lax.ragged_dot expert GEMMs at bench MoE
shapes on the real chip (round-4 verdict item 6: the untried lever for
the MoE 0.556-vs-0.696 MFU gap is a grouped/ragged GEMM formulation
that turns E narrow GEMMs into one wide MXU pass at the kernel level).

Shapes mirror bench_moe: N=8192 tokens, E=8, top2, capacity 4096
(factor 2.0) -> dispatched [8, 4096, 2048], w0 [8, 2048, 1408]. The
ragged form additionally gets to SKIP the ~50% capacity padding via
real group_sizes (mean tokens/expert = 2048 vs capacity 4096).

Run: python tools/moe_grouped_gemm_probe.py  (uses the attached chip)
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

E, C, D, H = 8, 4096, 2048, 1408
M = E * C
STEPS = 30


def bench(fn, x0, *rest):
    """Carry-chained timing: the axon tunnel pipelines async dispatch, so
    a Python loop of jit calls reports impossible TF/s; one lax.scan
    whose output feeds the next input forces serialization on-device."""

    @jax.jit
    def chained(x):
        def body(carry, _):
            out = fn(carry, *rest)
            # renormalize so the chain neither overflows nor denorms
            out = (out / (jnp.max(jnp.abs(out)) + 1e-6)).astype(x.dtype)
            return out, ()
        final, _ = jax.lax.scan(body, x, None, length=STEPS)
        return final

    out = chained(x0)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = chained(x0)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / STEPS


def main():
    rng = np.random.default_rng(0)
    disp = jnp.asarray(rng.normal(size=(E, C, D)), jnp.bfloat16)
    w0 = jnp.asarray(rng.normal(size=(E, D, H)) * D ** -0.5, jnp.bfloat16)
    w1 = jnp.asarray(rng.normal(size=(E, H, D)) * H ** -0.5, jnp.bfloat16)
    disp_flat = disp.reshape(M, D)
    uniform = jnp.full((E,), C, jnp.int32)
    # realistic ragged load: ~mean C/2 tokens per expert
    sizes_np = rng.multinomial(M // 2, np.ones(E) / E).astype(np.int32)
    ragged = jnp.asarray(sizes_np)

    def einsum_pair(d, a0, a1):
        h1 = jnp.einsum("ecd,edh->ech", d, a0,
                        preferred_element_type=jnp.float32)
        act = jax.nn.gelu(h1).astype(jnp.bfloat16)
        return jnp.einsum("ech,ehd->ecd", act, a1,
                          preferred_element_type=jnp.float32)

    def ragged_pair(dflat, a0, a1, gs):
        h1 = jax.lax.ragged_dot(dflat, a0, gs,
                                preferred_element_type=jnp.float32)
        act = jax.nn.gelu(h1).astype(jnp.bfloat16)
        return jax.lax.ragged_dot(act, a1, gs,
                                  preferred_element_type=jnp.float32)

    flops = 2 * M * D * H * 2  # two GEMMs
    t_e = bench(einsum_pair, disp, w0, w1)
    print(f"batched einsum pair: {t_e*1e3:.2f} ms  "
          f"{flops/t_e/1e12:.1f} TF/s")
    t_u = bench(ragged_pair, disp_flat, w0, w1, uniform)
    print(f"ragged_dot (uniform full C): {t_u*1e3:.2f} ms  "
          f"{flops/t_u/1e12:.1f} TF/s")
    t_r = bench(ragged_pair, disp_flat, w0, w1, ragged)
    eff_flops = 2 * int(sizes_np.sum()) * D * H * 2
    print(f"ragged_dot (real sizes, {int(sizes_np.sum())} rows): "
          f"{t_r*1e3:.2f} ms  {eff_flops/t_r/1e12:.1f} TF/s effective, "
          f"{flops/t_r/1e12:.1f} TF/s padded-equivalent")


if __name__ == "__main__":
    main()
