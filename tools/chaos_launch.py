#!/usr/bin/env python
"""Fault-injection launcher: run an elastic training job on localhost
and SIGKILL a chosen worker at a chosen step, then verify recovery.

The drill the elastic layer exists for, as one command::

    python tools/chaos_launch.py train.py \\
        --nnodes 2 --kill_rank 1 --kill_step 5 \\
        --flight_dir /tmp/flight -- --your-script-args

spawns ``--nnodes`` real `paddle_tpu.distributed.launch` controllers on
localhost (the CI device trick: each worker gets
``--xla_force_host_platform_device_count`` virtual CPU devices, so the
global mesh spans processes without chips). The worker whose global rank
is ``--kill_rank`` SIGKILLs itself after completing step ``--kill_step``
(fault injection rides ``PADDLE_TPU_CHAOS_KILL_*``, read by
``distributed.elastic_train``). Survivors detect the death by stale
heartbeat, dump flight-recorder post-mortems (reason ``peer_death``)
into ``--flight_dir``, and exit for the coordinated restart; the rejoined
world resumes from the latest complete checkpoint and dumps ``rejoin``.

Afterwards the tool prints each node's exit code and a one-line summary
of every flight dump it finds (render them fully with
``python tools/metrics_report.py <flight_dir>``).

The training script must drive its loop through
``paddle_tpu.distributed.elastic_train.run_elastic`` (or honor the same
chaos/checkpoint conventions) for the kill point and the resume to mean
anything.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import socket
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _free_port_block(span: int = 8) -> int:
    """Base port with `span` consecutive free ports (launcher store,
    jax coordinator, trainer store ride base, +1..+3)."""
    for _ in range(64):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
        s.close()
        if base + span >= 65535:
            continue
        ok = True
        for off in range(1, span):
            t = socket.socket()
            try:
                t.bind(("127.0.0.1", base + off))
            except OSError:
                ok = False
            finally:
                t.close()
            if not ok:
                break
        if ok:
            return base
    raise RuntimeError("no free port block found")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("training_script")
    ap.add_argument("--nnodes", type=int, default=2)
    ap.add_argument("--kill_rank", type=int, default=1,
                    help="global worker rank to SIGKILL (-1: no kill — "
                         "e.g. a pure straggler drill)")
    ap.add_argument("--kill_step", type=int, default=2,
                    help="step after which the victim dies")
    ap.add_argument("--slow_rank", type=int, default=None,
                    help="straggler injection: this rank sleeps "
                         "--slow_seconds inside every step region; with "
                         "--fleet_dir the aggregator must name it")
    ap.add_argument("--slow_seconds", type=float, default=0.25,
                    help="extra host-side seconds per step for the "
                         "slow rank")
    ap.add_argument("--creep_rank", type=int, default=None,
                    help="creeping-slowdown drill: this rank gets "
                         "--creep_pct percent slower EACH step (gradual "
                         "degradation a constant threshold never trips); "
                         "workers run with PADDLE_TPU_HEALTH=1 and the "
                         "drill asserts the PTL601 drift detector fired")
    ap.add_argument("--creep_pct", type=float, default=25.0,
                    help="per-step slowdown growth, percent of the base "
                         "sleep (PADDLE_TPU_CHAOS_CREEP_BASE, 0.05s)")
    ap.add_argument("--fleet_dir", type=str, default=None,
                    help="enable fleet telemetry: aggregated "
                         "fleet_metrics.json + merged fleet_trace.json "
                         "land here (default: <log_dir>/fleet when "
                         "--slow_rank is given)")
    ap.add_argument("--kill_gen", type=int, default=0,
                    help="only kill at this restart generation "
                         "(default 0: the first incarnation)")
    ap.add_argument("--devices_per_proc", type=int, default=2,
                    help="virtual CPU devices per worker "
                         "(xla_force_host_platform_device_count)")
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("--log_dir", type=str, default="chaos_log")
    ap.add_argument("--flight_dir", type=str, default=None,
                    help="flight-recorder dump directory "
                         "(default: <log_dir>/flight)")
    ap.add_argument("--timeout", type=float, default=600.0)
    ap.add_argument("script_args", nargs="*",
                    help="args after -- go to the training script")
    # split on a literal "--" ourselves: argparse.REMAINDER would
    # swallow every option that happens to follow the script path (the
    # documented `chaos_launch.py train.py --nnodes 2` form silently
    # misparsed into all-defaults)
    argv = sys.argv[1:] if argv is None else list(argv)
    if "--" in argv:
        split = argv.index("--")
        argv, passthrough = argv[:split], argv[split + 1:]
    else:
        passthrough = []
    args = ap.parse_args(argv)
    args.script_args = list(args.script_args) + passthrough

    flight_dir = args.flight_dir or os.path.join(args.log_dir, "flight")
    fleet_dir = args.fleet_dir or (
        os.path.join(args.log_dir, "fleet")
        if args.slow_rank is not None or args.creep_rank is not None
        else None)
    os.makedirs(args.log_dir, exist_ok=True)
    port = _free_port_block()
    master = f"127.0.0.1:{port}"
    script_args = list(args.script_args)

    env = dict(os.environ)
    env["PYTHONPATH"] = (_REPO_ROOT + os.pathsep
                         + env.get("PYTHONPATH", ""))
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                        f"{args.devices_per_proc}")
    if args.creep_rank is not None:
        # the creep drill exists to exercise the health monitor: the
        # gradual slowdown must trip the PTL601 drift detector, and
        # detectors only run where PADDLE_TPU_HEALTH installs them
        env["PADDLE_TPU_HEALTH"] = "1"

    procs = []
    for rank in range(args.nnodes):
        cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
               "--nnodes", str(args.nnodes), "--node_rank", str(rank),
               "--master", master, "--log_dir", args.log_dir,
               "--max_restarts", str(args.max_restarts),
               "--flight_dir", flight_dir]
        if args.kill_rank >= 0:
            cmd += ["--chaos_kill_rank", str(args.kill_rank),
                    "--chaos_kill_step", str(args.kill_step)]
        if args.slow_rank is not None:
            cmd += ["--chaos_slow_rank", str(args.slow_rank),
                    "--chaos_slow_seconds", str(args.slow_seconds)]
        if args.creep_rank is not None:
            cmd += ["--chaos_creep_rank", str(args.creep_rank),
                    "--chaos_creep_pct", str(args.creep_pct)]
        if fleet_dir:
            cmd += ["--fleet_dir", fleet_dir]
        cmd += [args.training_script] + script_args
        node_env = dict(env)
        node_env["PADDLE_TPU_CHAOS_KILL_GEN"] = str(args.kill_gen)
        procs.append(subprocess.Popen(cmd, env=node_env))

    rcs = []
    try:
        for p in procs:
            rcs.append(p.wait(timeout=args.timeout))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        print("chaos_launch: TIMED OUT — job did not finish; see "
              f"{args.log_dir}/workerlog.*", file=sys.stderr)
        return 2

    print(f"chaos_launch: node exit codes {rcs}")
    dumps = sorted(glob.glob(os.path.join(flight_dir, "flight-*.json")))
    for path in dumps:
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        ctx = d.get("context") or {}
        ctx_s = " ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
        print(f"  {os.path.basename(path)}: reason={d.get('reason')} "
              f"{ctx_s}")
    if dumps:
        print(f"chaos_launch: render dumps with: python "
              f"tools/metrics_report.py {flight_dir}")
    if fleet_dir:
        fpath = os.path.join(fleet_dir, "fleet_metrics.json")
        try:
            with open(fpath) as f:
                fdoc = json.load(f)
        except (OSError, json.JSONDecodeError):
            fdoc = None
        if fdoc:
            skew = fdoc.get("step_skew_seconds")
            print(f"chaos_launch: fleet view — ranks reporting "
                  f"{fdoc.get('ranks_reporting')}, step skew "
                  f"{skew if skew is None else round(skew, 4)}s, "
                  f"slowest rank {fdoc.get('slowest_rank')}")
            for e in fdoc.get("events", []):
                if e.get("kind") == "fleet.straggler":
                    print(f"chaos_launch: STRAGGLER rank {e.get('rank')}"
                          f" — mean step {e.get('mean_step_seconds')}s ="
                          f" {e.get('ratio')}x peer median")
            print(f"chaos_launch: render the incident with: python "
                  f"tools/metrics_report.py --fleet {fleet_dir}")
    if any(rcs):
        print("chaos_launch: FAILED — a node exited non-zero after "
              "exhausting restarts", file=sys.stderr)
        return 1
    reasons = set()
    for path in dumps:
        try:
            with open(path) as f:
                reasons.add(json.load(f).get("reason"))
        except (OSError, json.JSONDecodeError):
            pass
    if args.creep_rank is not None:
        # health-drill verdict: the creeping slowdown must have tripped
        # the drift detector — a PTL601 health_alert flight dump whose
        # context carries the offending series window, and a nonzero
        # health.alerts counter in the dumping worker's registry
        alert_codes, windowed, alerts_total = set(), 0, 0
        for path in dumps:
            try:
                with open(path) as f:
                    d = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            if d.get("reason") != "health_alert":
                continue
            ctx = d.get("context") or {}
            alert_codes.add(ctx.get("code"))
            if ctx.get("window"):
                windowed += 1
            for s in (d.get("metrics", {}).get("health.alerts", {})
                      .get("series", [])):
                alerts_total += int(s.get("value", 0))
        if ("PTL601" in alert_codes and windowed and alerts_total
                and args.kill_rank < 0):
            print("chaos_launch: OK — creep drill: the gradual "
                  f"slowdown tripped PTL601 (health.alerts="
                  f"{alerts_total}, {windowed} windowed "
                  f"health_alert dump(s))")
            return 0
        if args.kill_rank < 0:
            print("chaos_launch: FAILED — creep drill expected a "
                  "PTL601 health_alert dump with a series window and "
                  f"health.alerts > 0; saw codes={sorted(alert_codes)} "
                  f"windowed={windowed} alerts={alerts_total}",
                  file=sys.stderr)
            return 1
    if args.kill_rank < 0:
        if args.slow_rank is not None and "straggler" in reasons:
            print("chaos_launch: OK — straggler drill: the slow rank "
                  "was named and dumped its flight ring on request")
        else:
            print("chaos_launch: job finished clean")
        return 0
    if "peer_death" in reasons and "rejoin" in reasons:
        print("chaos_launch: OK — worker killed, peers dumped, world "
              "re-formed and resumed from checkpoint")
    else:
        print("chaos_launch: job finished clean but expected "
              f"peer_death+rejoin dumps, saw {sorted(reasons)} — did the "
              "kill point fire before the job ended?", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
