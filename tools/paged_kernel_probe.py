"""Probe: paged-attention decode kernels vs this repo's jnp
block-gather decode attention — equivalence + carry-chained speed at
645M serving shapes. Three contenders over the same pool:

1. jax's official TPU Pallas ``paged_attention`` (generic long-context
   kernel: per-compute-block async-copy pipeline);
2. the jnp gather reference (what ``block_mha_p`` decode does);
3. THIS repo's decode-specialized kernel
   (``paddle_tpu/ops/pallas/paged_attention.py``): grid
   ``(batch, pages)``, whole page per program for all heads, block
   tables/lengths in SMEM via scalar prefetch, online-softmax scratch
   in VMEM, fused length masking — the short-context overhead the
   official kernel pays is exactly what it strips.

MEASURED (v5e, 2026-07-31, B=8/NH=16/DH=128, 256-slot pool; official
kernel vs gather): official kernel matches the masked-softmax
reference (max err 1e-3, bf16 scale) and runs 1350 us/step vs 2155
for the jnp gather — 1.6x faster, but still ~6x the dense scan's
ENTIRE per-layer decode budget (~200 us incl. matmuls) at this
context length, because its multi-compute-block pipeline is
overhead-bound at 2 pages/seq.

MEASURED (CPU interpret, 2026-08-04, decode-specialized kernel): the
new kernel is numerically equivalent to the masked-softmax reference
(max abs err < 2e-3 at bf16 scale, bit-level vs the fp32 reference in
f32 — pinned by tests/test_paged_attention_kernel.py, which is this
probe's equivalence check promoted to pytest). TPU wall-clock: rerun
this probe on a v5e to refresh the numbers; the decode kernel issues
one fused pass per (sequence, page) with zero gathered K/V
materialization, eliminating both the gather's HBM round-trip (path 2)
and the per-compute-block pipeline overhead (path 1) that dominate at
short context.

Equivalence runs on every backend (CPU uses interpret mode for the
decode kernel and skips the official kernel, which has no interpret
path); timing loops run on TPU only.
"""
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from paddle_tpu.ops.pallas.paged_attention import (  # noqa: E402
    paged_attention_decode_kernel, paged_attention_decode_reference)

ON_TPU = jax.default_backend() == "tpu"

B, NH, KVH, DH = 8, 16, 16, 128
PAGE = 128
PAGES_PER_SEQ = 2          # 256 max positions
NPAGES = B * PAGES_PER_SEQ
STEPS = 50

rng = np.random.default_rng(0)
q = jnp.asarray(rng.normal(size=(B, NH, DH)) * 0.3, jnp.bfloat16)
k_pages = jnp.asarray(rng.normal(size=(KVH, NPAGES, PAGE, DH)) * 0.3,
                      jnp.bfloat16)
v_pages = jnp.asarray(rng.normal(size=(KVH, NPAGES, PAGE, DH)) * 0.3,
                      jnp.bfloat16)
lengths = jnp.asarray(rng.integers(100, 250, size=(B,)), jnp.int32)
page_indices = jnp.asarray(
    np.arange(NPAGES, dtype=np.int32).reshape(B, PAGES_PER_SEQ))


def official_kernel(q, kp, vp, lens, idx):
    from jax.experimental.pallas.ops.tpu.paged_attention import \
        paged_attention

    return paged_attention(q, kp, vp, lens, idx,
                           pages_per_compute_block=PAGES_PER_SEQ)


def decode_kernel(q, kp, vp, lens, idx):
    return paged_attention_decode_kernel(q, kp, vp, lens, idx,
                                         interpret=not ON_TPU)


def reference(q, kp, vp, lens, idx):
    # the masked-softmax oracle == block_mha_p's decode gather
    return paged_attention_decode_reference(q, kp, vp, lens, idx)


def reference_unscaled(q, kp, vp, lens, idx):
    # jax's official paged_attention applies NO sm scale (the caller
    # pre-scales q) — compare it against the same unscaled softmax
    return paged_attention_decode_reference(q, kp, vp, lens, idx,
                                            sm_scale=1.0)


def _err(a, b):
    return np.max(np.abs(np.asarray(a, np.float32)
                         - np.asarray(b, np.float32)))


out_r = jax.jit(reference)(q, k_pages, v_pages, lengths, page_indices)
out_d = jax.jit(decode_kernel)(q, k_pages, v_pages, lengths, page_indices)
err_d = _err(out_d, out_r)
print(f"decode-kernel-vs-reference max abs err: {err_d:.4f} (bf16 scale)")
assert err_d < 0.05, \
    "decode kernel output diverges from masked-softmax reference"
if ON_TPU:
    out_k = jax.jit(official_kernel)(q, k_pages, v_pages, lengths,
                                     page_indices)
    out_ru = jax.jit(reference_unscaled)(q, k_pages, v_pages, lengths,
                                         page_indices)
    err_k = _err(out_k, out_ru)
    print(f"official-kernel-vs-reference max abs err: {err_k:.4f}")
    assert err_k < 0.05, \
        "official kernel output diverges from masked-softmax reference"


def bench(fn):
    # carry-chain (axon tunnel): feed output back as q
    @jax.jit
    def chained(q0):
        def body(qc, _):
            o = fn(qc, k_pages, v_pages, lengths, page_indices)
            o = (o / (jnp.max(jnp.abs(o)).astype(o.dtype) + 1)).astype(
                qc.dtype)
            return o, ()
        out, _ = jax.lax.scan(body, q0, None, length=STEPS)
        return out
    o = chained(q); jax.block_until_ready(o)
    t0 = time.perf_counter()
    o = chained(q); jax.block_until_ready(o)
    return (time.perf_counter() - t0) / STEPS


if ON_TPU:
    t_k = bench(official_kernel)
    t_r = bench(reference)
    t_d = bench(decode_kernel)
    print(f"official pallas paged_attention: {t_k*1e6:.0f} us/step")
    print(f"jnp gather reference:            {t_r*1e6:.0f} us/step")
    print(f"decode-specialized kernel:       {t_d*1e6:.0f} us/step")
else:
    print("no TPU attached: equivalence verified (interpret mode); "
          "timing loops skipped")
