"""Probe: jax's TPU Pallas paged_attention kernel vs this repo's jnp
block-gather decode attention — equivalence + carry-chained speed at
645M serving shapes. Decides whether the serving decode step can ride
the kernel (tools/paged_decode_probe.py measured the jnp gather
program at ~10x the dense scan).

MEASURED (v5e, 2026-07-31, B=8/NH=16/DH=128, 256-slot pool): kernel
matches the masked-softmax reference (max err 1e-3, bf16 scale) and
runs 1350 us/step vs 2155 for the jnp gather — 1.6x faster, but still
~6x the dense scan's ENTIRE per-layer decode budget (~200 us incl.
matmuls) at this context length. Conclusion: at 645M/short-context
serving shapes, paged attention (even the official Pallas kernel) is
overhead-bound; the paged path's value is cache MEMORY semantics
(pad-free pooling, no per-sequence S_max allocation), and the dense
single-jit scan remains the throughput path the decode bench measures.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental.pallas.ops.tpu.paged_attention import paged_attention

B, NH, KVH, DH = 8, 16, 16, 128
PAGE = 128
PAGES_PER_SEQ = 2          # 256 max positions
NPAGES = B * PAGES_PER_SEQ
STEPS = 50

rng = np.random.default_rng(0)
q = jnp.asarray(rng.normal(size=(B, NH, DH)) * 0.3, jnp.bfloat16)
k_pages = jnp.asarray(rng.normal(size=(KVH, NPAGES, PAGE, DH)) * 0.3,
                      jnp.bfloat16)
v_pages = jnp.asarray(rng.normal(size=(KVH, NPAGES, PAGE, DH)) * 0.3,
                      jnp.bfloat16)
lengths = jnp.asarray(rng.integers(100, 250, size=(B,)), jnp.int32)
page_indices = jnp.asarray(
    np.arange(NPAGES, dtype=np.int32).reshape(B, PAGES_PER_SEQ))


def kernel(q, kp, vp, lens, idx):
    return paged_attention(q, kp, vp, lens, idx,
                           pages_per_compute_block=PAGES_PER_SEQ)


def reference(q, kp, vp, lens, idx):
    # gather each row's pages -> [B, S_pad, KVH, DH], masked softmax
    s_pad = PAGES_PER_SEQ * PAGE
    k_rows = kp[:, idx].transpose(1, 2, 3, 0, 4).reshape(
        B, s_pad, KVH, DH)
    v_rows = vp[:, idx].transpose(1, 2, 3, 0, 4).reshape(
        B, s_pad, KVH, DH)
    scores = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                        k_rows.astype(jnp.float32))
    valid = jnp.arange(s_pad)[None, :] < lens[:, None]
    scores = jnp.where(valid[:, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhs,bshd->bhd", probs,
                      v_rows.astype(jnp.float32)).astype(q.dtype)


out_k = jax.jit(kernel)(q, k_pages, v_pages, lengths, page_indices)
out_r = jax.jit(reference)(q, k_pages, v_pages, lengths, page_indices)
err = np.max(np.abs(np.asarray(out_k, np.float32)
                    - np.asarray(out_r, np.float32)))
print(f"kernel-vs-reference max abs err: {err:.4f} (bf16 scale)")
assert err < 0.05, "kernel output diverges from masked-softmax reference"


def bench(fn):
    # carry-chain (axon tunnel): feed output back as q
    @jax.jit
    def chained(q0):
        def body(qc, _):
            o = fn(qc, k_pages, v_pages, lengths, page_indices)
            o = (o / (jnp.max(jnp.abs(o)).astype(o.dtype) + 1)).astype(
                qc.dtype)
            return o, ()
        out, _ = jax.lax.scan(body, q0, None, length=STEPS)
        return out
    o = chained(q); jax.block_until_ready(o)
    t0 = time.perf_counter()
    o = chained(q); jax.block_until_ready(o)
    return (time.perf_counter() - t0) / STEPS


t_k = bench(kernel)
t_r = bench(reference)
print(f"pallas paged_attention: {t_k*1e6:.0f} us/step")
print(f"jnp gather reference:   {t_r*1e6:.0f} us/step")
