#!/usr/bin/env python
"""Synthetic heavy-traffic load test against a local ServeEngine.

Builds a Llama model, stands up a continuous-batching
``paddle_tpu.serve.ServeEngine`` and drives it with Poisson arrivals of
mixed prompt/output lengths (``paddle_tpu/serve/load.py``), then prints
one JSON line with exact sample-based p50/p99 TTFT (queue wait
included), aggregate tokens/sec, preemption and step counts.

Run::

    python tools/serve_load.py --rate 300 --requests 32
    python tools/serve_load.py --metrics    # + observability roll-up
                                            # (same keys as bench.py)
    python tools/serve_load.py --trace-out /tmp/serve_trace \
        --slo '[{"name":"ttft","kind":"ttft_p99","threshold":0.2}]'

``--trace-out DIR`` runs the engine with request-lifecycle tracing and
writes three artifacts into DIR: ``serve_requests.json`` (the
``serve_trace`` dump — per-request span trees, per-phase breakdowns,
decode-step records, tail exemplars; render with
``tools/metrics_report.py --serve-trace DIR``), ``serve_chrome.json``
(one lane per decode slot in ``chrome://tracing`` format, mergeable
into a fleet timeline by ``fleet.merge_chrome_trace_files``) and
``tail_report.txt`` (the worst-TTFT / worst-latency exemplar
breakdowns as text). ``--slo`` attaches SLO rules (inline JSON or a
rules-file path, same syntax as ``PADDLE_TPU_SLO``); breaches print
and, when ``PADDLE_TPU_FLIGHT_DIR`` is set, dump flight recorders
with the exemplars attached.

``bench.py --config serve --metrics`` produces the canonical BENCH
record with the same generator; this CLI is the knob-turning surface
(rate sweeps, pool-pressure experiments via --num_blocks, sampled
streams via --temperature).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Poisson load test against a local ServeEngine")
    ap.add_argument("--rate", type=float, default=None,
                    help="mean arrival rate, requests/sec "
                         "(default: 300 CPU / 30 TPU)")
    ap.add_argument("--requests", type=int, default=None,
                    help="number of requests (default: 16 CPU / 48 TPU)")
    ap.add_argument("--slots", type=int, default=None,
                    help="decode slots (continuous-batching width)")
    ap.add_argument("--num_blocks", type=int, default=None,
                    help="KV pool size in blocks (small values force "
                         "queueing + preemption)")
    ap.add_argument("--block_size", type=int, default=None)
    ap.add_argument("--max_seq_len", type=int, default=None)
    ap.add_argument("--prompt_len", type=int, nargs=2, default=None,
                    metavar=("LO", "HI"))
    ap.add_argument("--max_new", type=int, nargs=2, default=None,
                    metavar=("LO", "HI"))
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="0 = greedy; >0 samples every stream")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--prefix-cache", action="store_true",
                    help="enable cross-request KV prefix sharing "
                         "(PADDLE_TPU_PREFIX_CACHE)")
    ap.add_argument("--decode-burst", type=int, default=1,
                    help="fuse up to N decode steps into one on-chip "
                         "scan dispatch (PADDLE_TPU_DECODE_BURST; "
                         "default 1 = one round-trip per token)")
    ap.add_argument("--shared-prefix-tokens", type=int, default=0,
                    metavar="N",
                    help="prepend one synthetic N-token system prompt "
                         "to a fraction of requests (the prefix-cache "
                         "workload); report blocks-saved in the record")
    ap.add_argument("--shared-prefix-frac", type=float, default=0.0,
                    metavar="P",
                    help="fraction of requests sharing the synthetic "
                         "system prompt (0.0 .. 1.0)")
    ap.add_argument("--metrics", action="store_true",
                    help="enable observability and print the serve_* "
                         "roll-up keys (bench.py --metrics parity)")
    ap.add_argument("--trace-out", default=None, metavar="DIR",
                    help="trace every request and write "
                         "serve_requests.json + serve_chrome.json + "
                         "tail_report.txt into DIR")
    ap.add_argument("--slo", default=None, metavar="RULES",
                    help="SLO rules: inline JSON list or a JSON file "
                         "path (PADDLE_TPU_SLO syntax); breaches print "
                         "after the run")
    args = ap.parse_args(argv)

    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM
    from paddle_tpu.serve import ServeEngine, run_load
    from paddle_tpu.serve.load import default_serving_setup, warm_engine

    if args.metrics or args.trace_out or args.slo:
        import paddle_tpu.observability as obs

        obs.enable()

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    paddle.seed(0)
    # defaults shared with bench.py --config serve (ONE serving shape)
    config, defaults = default_serving_setup(on_tpu)

    def pick(cli_value, key):
        # explicit `is None` check: `--rate 0` must reach the engine
        # (and fail its own validation) rather than silently running
        # the default load
        return defaults[key] if cli_value is None else cli_value

    rate = pick(args.rate, "rate")
    n_req = pick(args.requests, "requests")
    slots = pick(args.slots, "slots")
    num_blocks = pick(args.num_blocks, "num_blocks")
    block_size = pick(args.block_size, "block_size")
    max_seq_len = pick(args.max_seq_len, "max_seq_len")
    plen = tuple(pick(args.prompt_len, "prompt_len"))
    mnew = tuple(pick(args.max_new, "max_new"))
    if rate <= 0:
        ap.error(f"--rate must be > 0 requests/sec, got {rate}")

    model = LlamaForCausalLM(config)
    if on_tpu:
        model.bfloat16()
    model.eval()
    if args.shared_prefix_frac and not 0.0 <= args.shared_prefix_frac <= 1.0:
        ap.error(f"--shared-prefix-frac must be in [0, 1], got "
                 f"{args.shared_prefix_frac}")
    engine = ServeEngine(model, max_slots=slots, block_size=block_size,
                         num_blocks=num_blocks, max_seq_len=max_seq_len,
                         name="serve_load",
                         trace=bool(args.trace_out) or None,
                         slo=args.slo,
                         prefix_cache=args.prefix_cache or None,
                         decode_burst=args.decode_burst)
    warm_engine(engine)     # decode + burst scans + every prefill bucket

    res = run_load(engine, rate=rate, n_requests=n_req, prompt_len=plen,
                   max_new=mnew, temperature=args.temperature,
                   seed=args.seed,
                   shared_prefix_tokens=args.shared_prefix_tokens,
                   shared_prefix_frac=args.shared_prefix_frac)
    record = {"load": res.to_dict()}
    record["load"].update(
        rate_rps=rate, slots=slots, num_blocks=num_blocks,
        block_size=block_size, decode_traces=engine.decode_traces,
        prefill_traces=engine.prefill_traces,
        pool_blocks_leaked=engine.pool.used_blocks,
        prefix_cache=bool(args.prefix_cache),
        decode_burst=args.decode_burst,
        shared_prefix_tokens=args.shared_prefix_tokens,
        shared_prefix_frac=args.shared_prefix_frac)
    if engine.slo is not None:
        record["load"]["slo_breaches"] = list(engine.slo.breaches)
    if args.trace_out:
        out = args.trace_out
        os.makedirs(out, exist_ok=True)
        tracer = engine.tracer
        paths = {
            "requests": tracer.dump(
                os.path.join(out, "serve_requests.json")),
            "chrome": tracer.write_chrome_trace(
                os.path.join(out, "serve_chrome.json")),
        }
        tail = os.path.join(out, "tail_report.txt")
        with open(tail, "w") as f:
            f.write(tracer.exemplars.render() + "\n")
        paths["tail"] = tail
        record["trace_out"] = paths
    print(json.dumps(record), flush=True)
    if args.metrics:
        from bench import _emit_metrics_block

        _emit_metrics_block()
    return 0


if __name__ == "__main__":
    sys.exit(main())
