#!/usr/bin/env python
"""Codebase-level registry lint: primitive registry + ``__all__`` audit.

The static-program analysis layer (paddle_tpu/static/analysis) checks
captured programs; this script applies the same discipline to the code
that *defines* the ops. It verifies, over the fully-imported package:

1. every ``dispatch.PRIMITIVES`` entry has a callable ``forward``
   (backward-only registrations — ``pylayer::*``, ``recompute::replay``
   — with a callable ``vjp`` are the one sanctioned exception);
2. grad wiring is mutually consistent: ``save`` without a ``vjp`` is
   dead weight (the fallback path saves inputs itself), and ``vjp``/
   ``save`` must be callables whose signatures can accept the engine's
   calling convention (``vjp(grads_out, saved, **static)``,
   ``save(arrays_in, outs)``);
3. every name in each imported ``paddle_tpu`` module's ``__all__``
   actually resolves on that module;
4. every metric registered at import time in the observability registry
   is unique, documented, matches the ``subsystem.noun_verb`` naming
   scheme, and its subsystem prefix is claimed in
   ``observability.metrics.CLAIMED_SUBSYSTEMS`` (the metric analog of
   the ``PTLxxx`` diagnostic-code claiming convention);
5. the diagnostic-code registry is closed both ways: every registered
   lint (``lint.LINTS``, the sharding lints) and every lint-fix rewrite
   pass claims a code documented in ``diagnostics.CODES``, and every
   documented ``PTLxxx`` code is exercised by at least one test under
   ``tests/`` — a code nothing can trigger (or nothing proves
   triggerable) is registry rot either way.

Exits non-zero listing every violation — wired into the test session via
a session-scoped fixture in tests/conftest.py (skippable with
``PADDLE_TPU_SKIP_REGISTRY_LINT=1``), so registry drift fails tier-1
instead of surfacing as an AttributeError in production.
"""
from __future__ import annotations

import os
import sys
from typing import List

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _can_take_two(capacity) -> bool:
    """capacity is dispatch.positional_capacity's (min, max|None)."""
    if capacity is None:
        return True  # opaque signature: give the benefit of the doubt
    _min, _max = capacity
    if _min is None:
        return True
    return _max is None or _max >= 2


def check_primitives() -> List[str]:
    from paddle_tpu.core import dispatch

    problems = []
    for name, prim in sorted(dispatch.PRIMITIVES.items()):
        meta = dispatch.primitive_metadata(name)
        if prim.forward is None:
            # sanctioned backward-only registrations (pylayer::*,
            # recompute::replay) carry the op through the eager tape and
            # exist solely for their custom vjp — the vjp must be there
            if callable(prim.vjp):
                continue
            problems.append(
                f"primitive {name!r}: forward is None and there is no "
                f"callable vjp (backward-only registrations must provide "
                f"one; everything else must provide a forward)")
            continue
        if not callable(prim.forward):
            problems.append(
                f"primitive {name!r}: forward is not callable "
                f"({type(prim.forward).__name__})")
        if prim.vjp is not None and not callable(prim.vjp):
            problems.append(f"primitive {name!r}: vjp is not callable")
        if prim.save is not None and not callable(prim.save):
            problems.append(f"primitive {name!r}: save is not callable")
        if prim.save is not None and prim.vjp is None:
            problems.append(
                f"primitive {name!r}: has save= but no vjp — the generic "
                f"jax.vjp fallback ignores save and rematerializes from "
                f"inputs, so the save hook is dead weight (add the vjp or "
                f"drop the save)")
        if callable(prim.vjp) and not _can_take_two(meta["vjp_capacity"]):
            problems.append(
                f"primitive {name!r}: vjp cannot accept "
                f"(grads_out, saved) — dispatch.call_vjp passes two "
                f"positionals")
        if callable(prim.save) and not _can_take_two(meta["save_capacity"]):
            problems.append(
                f"primitive {name!r}: save cannot accept "
                f"(arrays_in, outs) — the engine passes two "
                f"positionals at forward time")
    return problems


def check_all_exports() -> List[str]:
    problems = []
    for mod_name in sorted(sys.modules):
        if not (mod_name == "paddle_tpu" or
                mod_name.startswith("paddle_tpu.")):
            continue
        mod = sys.modules[mod_name]
        if mod is None:
            continue
        exported = getattr(mod, "__all__", None)
        if not exported:
            continue
        for sym in exported:
            if not isinstance(sym, str):
                problems.append(
                    f"{mod_name}.__all__ contains a non-string entry "
                    f"{sym!r}")
            elif not hasattr(mod, sym):
                problems.append(
                    f"{mod_name}.__all__ exports {sym!r} but the module "
                    f"has no such attribute")
    return problems


#: comm.collective_* series MUST carry these labels — an unlabeled
#: collective metric cannot be attributed to a mesh axis, which defeats
#: the per-mesh accounting the subsystem exists for.
COLLECTIVE_REQUIRED_LABELS = ("group", "op")

#: same discipline for the elastic recovery series: a restart that can't
#: say WHY, or a peer death that can't say WHO, is an alert nobody can
#: act on. Keys are metric names, values the labels every recorded
#: series must carry.
ELASTIC_REQUIRED_LABELS = {
    "elastic.restarts": ("reason",),
    "elastic.peer_deaths": ("peer",),
}

#: lint->rewrite driver label discipline (static/analysis/rewrite.py):
#: a fixed/remaining count that can't say WHICH code, or a rewrite
#: timing that can't say WHICH pass, defeats the measured-benefit
#: scheduling the opt. subsystem exists for.
OPT_REQUIRED_LABELS = {
    "opt.findings_fixed": ("code",),
    "opt.findings_remaining": ("code",),
    "opt.rewrite_seconds": ("name",),
    "opt.passes_skipped": ("name",),
}

#: cost/memory-analysis label discipline (static/analysis/cost.py +
#: memory.py): every predicted/measured series must say WHICH program
#: it describes — a predicted-vs-measured table with unattributable
#: rows cannot catch cost-model rot per workload.
COST_REQUIRED_LABELS = {
    "cost.predicted_flops": ("name",),
    "cost.measured_flops": ("name",),
    "cost.model_flops_error_pct": ("name",),
    "cost.predicted_peak_hbm_bytes": ("name",),
    "cost.measured_peak_hbm_bytes": ("name",),
    "cost.predicted_oom": ("name",),
    "cost.estimate_seconds": ("kind",),
    # step-time model + comm cost (static/analysis/comm_cost.py): the
    # comm series additionally say WHICH collective kind, so the
    # per-collective table in observability/report.py can render
    "cost.predicted_step_seconds": ("name",),
    "cost.measured_step_seconds": ("name",),
    "cost.model_step_error_pct": ("name",),
    "cost.comm_predicted_bytes": ("kind", "name"),
    "cost.comm_predicted_seconds": ("kind", "name"),
}

#: fleet-telemetry label discipline (observability/fleet.py): per-rank
#: series must say WHICH rank, ship failures must say WHY. Additionally
#: no ``fleet.`` GAUGE may record an unlabeled series at all — an
#: unattributable fleet gauge (no rank, no job) is exactly the
#: single-process myopia the subsystem exists to end.
FLEET_REQUIRED_LABELS = {
    "fleet.clock_offset_seconds": ("rank",),
    "fleet.snapshots_shipped": ("rank",),
    "fleet.snapshots_received": ("rank",),
    "fleet.rank_step_seconds": ("rank",),
    "fleet.stragglers_detected": ("rank",),
    "fleet.ship_failures": ("reason",),
    "fleet.ranks_reporting": ("job",),
    "fleet.step_skew_seconds": ("job",),
    "fleet.slowest_rank": ("job",),
}

#: serving-engine label discipline (serve/engine.py): every series must
#: say WHICH engine (multi-replica serving merges registries through the
#: fleet plane, and an unattributable server metric is useless there);
#: finish/reject/preempt/stall series must additionally carry the WHY.
SERVE_REQUIRED_LABELS = {
    "serve.requests_finished": ("engine", "reason"),
    "serve.requests_rejected": ("engine", "reason"),
    "serve.preemptions": ("engine", "reason"),
    "serve.admission_stalls": ("engine", "reason"),
    "serve.requests_admitted": ("engine",),
    "serve.tokens_generated": ("engine",),
    "serve.decode_steps": ("engine",),
    "serve.decode_traces": ("engine",),
    "serve.prefill_traces": ("engine",),
    "serve.ttft_seconds": ("engine",),
    "serve.request_seconds": ("engine",),
    "serve.decode_step_seconds": ("engine",),
    "serve.prefill_seconds": ("engine",),
    "serve.prefix_hits": ("engine",),
    "serve.prefix_blocks_shared": ("engine",),
    "serve.cow_copies": ("engine",),
    "serve.burst_tokens": ("engine",),
    "serve.host_roundtrips": ("engine",),
}

#: request-tracing / SLO label discipline (observability/tracing.py +
#: slo.py): per-phase series must say WHICH phase, breaches WHICH rule,
#: malformed-tree findings WHICH reason, exemplar retention WHICH kind —
#: and everything says WHICH engine, same as the serve. subsystem it
#: instruments.
TRACE_REQUIRED_LABELS = {
    "trace.requests_traced": ("engine",),
    "trace.spans_recorded": ("engine", "phase"),
    "trace.phase_seconds": ("engine", "phase"),
    "trace.decode_gap_seconds": ("engine",),
    "trace.exemplars_kept": ("engine", "kind"),
    "trace.spans_malformed": ("engine", "reason"),
    "trace.overhead_pct": ("engine",),
    "trace.slo_breaches": ("engine", "rule"),
}

#: op-profiler label discipline (observability/opprof.py): every series
#: attributes the profile name (which program was measured), and the
#: per-op series say WHICH primitive class — the join key the
#: cost-model calibration fits against.
OPPROF_REQUIRED_LABELS = {
    "opprof.steps_profiled": ("name",),
    "opprof.steps_skipped": ("name",),
    "opprof.op_seconds": ("name", "prim"),
    "opprof.step_seconds": ("name",),
    "opprof.attributed_pct": ("name",),
    "opprof.overhead_pct": ("name",),
    "opprof.drift_ratio": ("name", "prim"),
}

HEALTH_REQUIRED_LABELS = {
    "health.alerts": ("rule", "series"),
    "health.evaluations": ("rule",),
    "ts.points_recorded": ("series",),
}

#: one audit loop serves every per-subsystem required-labels table —
#: add the next subsystem as a row here, not as another copied loop
REQUIRED_LABEL_TABLES = (
    (ELASTIC_REQUIRED_LABELS, "elastic recovery series must attribute "
                              "the incident (who died / why the restart)"),
    (OPT_REQUIRED_LABELS, "opt. series must attribute the PTL code / "
                          "rewrite pass"),
    (COST_REQUIRED_LABELS, "cost. series must attribute the program "
                           "(or the analysis kind)"),
    (FLEET_REQUIRED_LABELS, "fleet series must attribute the rank (or "
                            "the reason/job)"),
    (SERVE_REQUIRED_LABELS, "serve series must attribute the engine "
                            "(and the reason where one applies)"),
    (TRACE_REQUIRED_LABELS, "trace series must attribute the engine "
                            "(and the phase/rule/reason/kind where one "
                            "applies)"),
    (OPPROF_REQUIRED_LABELS, "opprof series must attribute the profile "
                             "name (and the prim for per-op series)"),
    (HEALTH_REQUIRED_LABELS, "health/ts series must attribute the "
                             "detector rule and/or the recorded series"),
)

#: gauge-prefix discipline: no gauge under these prefixes may record an
#: UNLABELED series — a fleet gauge without rank/job, or a serve gauge
#: without engine=, cannot be attributed once registries merge.
NO_UNLABELED_GAUGE_PREFIXES = {
    "fleet.": "every fleet gauge must carry at least a rank= or job= "
              "label",
    "serve.": "every serve gauge must carry at least an engine= label",
    "cost.": "every cost gauge must carry at least a name= label (the "
             "program the prediction describes)",
    "trace.": "every trace gauge must carry at least an engine= label "
              "(serve-trace series merge through the fleet plane too)",
    "opprof.": "every opprof gauge must carry at least a name= label "
               "(the profile the measurement attributes)",
    "health.": "every health gauge must carry at least a rule= or "
               "series= label (an unlabeled health series cannot be "
               "attributed to a detector once registries merge)",
}


def check_metric_registry() -> List[str]:
    from paddle_tpu import observability
    # the runtime-telemetry modules register their metrics at import;
    # pull them in explicitly so the audit always covers the train./
    # device./comm./io. subsystems even when the workload under test
    # never touched them
    import paddle_tpu.distributed.communication.watchdog  # noqa: F401
    import paddle_tpu.distributed.elastic  # noqa: F401
    import paddle_tpu.io.dataloader  # noqa: F401
    import paddle_tpu.observability.fleet  # noqa: F401
    import paddle_tpu.observability.health  # noqa: F401
    import paddle_tpu.observability.opprof  # noqa: F401
    import paddle_tpu.observability.runtime  # noqa: F401
    import paddle_tpu.observability.slo  # noqa: F401
    import paddle_tpu.observability.timeseries  # noqa: F401
    import paddle_tpu.observability.tracing  # noqa: F401
    import paddle_tpu.serve  # noqa: F401
    from paddle_tpu.observability.metrics import (CLAIMED_SUBSYSTEMS,
                                                  NAME_RE)

    problems = []
    # the registry is define-or-get, so a reused name silently SHARES one
    # series family; uniqueness is audited via definition sites instead —
    # a name claimed from two different modules is an accidental collision
    for name, sites in sorted(observability.registry
                              .definition_sites().items()):
        if len(sites) > 1:
            problems.append(
                f"metric {name!r}: defined from {len(sites)} different "
                f"modules ({', '.join(sites)}) — metric names are claimed "
                f"per subsystem; pick a name under your own prefix")
    for m in observability.registry:
        if not NAME_RE.match(m.name):
            problems.append(
                f"metric {m.name!r}: does not match the "
                f"'subsystem.noun_verb' naming scheme ({NAME_RE.pattern})")
            continue
        subsystem = m.name.split(".", 1)[0]
        if subsystem not in CLAIMED_SUBSYSTEMS:
            problems.append(
                f"metric {m.name!r}: subsystem {subsystem!r} is not "
                f"claimed in observability.metrics.CLAIMED_SUBSYSTEMS — "
                f"claim the prefix next to your first metric (the PTLxxx "
                f"code-claiming convention)")
        if not m.doc:
            problems.append(
                f"metric {m.name!r}: registered without a doc string")
        if m.name.startswith("comm.collective"):
            for labels in m.labelsets():
                missing = [k for k in COLLECTIVE_REQUIRED_LABELS
                           if k not in labels]
                if missing:
                    problems.append(
                        f"metric {m.name!r}: series {labels!r} is missing "
                        f"required label(s) {missing} — collective metrics "
                        f"must be attributable to a mesh axis (label every "
                        f"record with op= and group=)")
        for table, why in REQUIRED_LABEL_TABLES:
            required = table.get(m.name)
            if not required:
                continue
            for labels in m.labelsets():
                missing = [k for k in required if k not in labels]
                if missing:
                    problems.append(
                        f"metric {m.name!r}: series {labels!r} is missing "
                        f"required label(s) {missing} — {why}")
        if m.kind == "gauge":
            for prefix, why in NO_UNLABELED_GAUGE_PREFIXES.items():
                if not m.name.startswith(prefix):
                    continue
                for labels in m.labelsets():
                    if not labels:
                        problems.append(
                            f"metric {m.name!r}: recorded an UNLABELED "
                            f"gauge series — {why}")
    return problems


def check_diagnostic_registry() -> List[str]:
    """The PTLxxx registry, closed both ways: every lint and lint-fix
    pass claims a documented code; every documented code is exercised
    by at least one test (string-presence scan over ``tests/``)."""
    from paddle_tpu.distributed import passes as passes_mod
    from paddle_tpu.distributed.passes.lint_fix_passes import LintFixPass
    from paddle_tpu.observability import health as health_mod
    from paddle_tpu.observability import opprof as opprof_mod
    from paddle_tpu.observability import slo as slo_mod
    from paddle_tpu.observability import tracing as tracing_mod
    from paddle_tpu.static.analysis import cost as cost_mod
    from paddle_tpu.static.analysis import diagnostics, serve_trace_lint
    from paddle_tpu.static.analysis import sharding_lint
    from paddle_tpu.static.analysis import lint as lint_mod

    problems = []
    for code, _severity, fn in lint_mod.LINTS:
        if code not in diagnostics.CODES:
            problems.append(
                f"lint {fn.__name__!r}: emits code {code!r} which is not "
                f"documented in diagnostics.CODES — claim the code next "
                f"to the registration")
    for code in sharding_lint.SHARDING_LINT_CODES:
        if code not in diagnostics.CODES:
            problems.append(
                f"sharding lint code {code!r} is not documented in "
                f"diagnostics.CODES")
    for code in cost_mod.COST_ANALYSIS_CODES:
        if code not in diagnostics.CODES:
            problems.append(
                f"cost-analysis code {code!r} is not documented in "
                f"diagnostics.CODES")
    for claimed_by, codes in (
            ("serve_trace_lint", serve_trace_lint.SERVE_TRACE_LINT_CODES),
            ("observability.tracing", tracing_mod.TRACE_CODES),
            ("observability.slo", slo_mod.SLO_CODES),
            ("observability.opprof", opprof_mod.OPPROF_CODES),
            ("observability.health", health_mod.HEALTH_CODES)):
        for code in codes:
            if code not in diagnostics.CODES:
                problems.append(
                    f"{claimed_by} code {code!r} is not documented in "
                    f"diagnostics.CODES")
    for name, cls in sorted(passes_mod._PASS_REGISTRY.items()):
        if isinstance(cls, type) and issubclass(cls, LintFixPass):
            code = getattr(cls, "code", "")
            if not code:
                problems.append(
                    f"rewrite pass {name!r}: LintFixPass subclass with no "
                    f"claimed code — a lint-fix pass must name the PTL "
                    f"code it fixes")
            elif code not in diagnostics.CODES:
                problems.append(
                    f"rewrite pass {name!r}: claims code {code!r} which "
                    f"is not documented in diagnostics.CODES")

    tests_dir = os.path.join(_REPO_ROOT, "tests")
    corpus = []
    try:
        for fn_ in sorted(os.listdir(tests_dir)):
            if fn_.endswith(".py"):
                with open(os.path.join(tests_dir, fn_),
                          errors="replace") as f:
                    corpus.append(f.read())
    except OSError as e:
        return problems + [f"cannot scan tests/ for PTL codes: {e}"]
    corpus = "\n".join(corpus)
    for code in sorted(diagnostics.CODES):
        if code not in corpus:
            problems.append(
                f"diagnostic code {code!r} has no test that references "
                f"it — every documented PTLxxx code needs at least one "
                f"test triggering (or asserting the absence of) it")
    return problems


def main(argv=None) -> int:
    import paddle_tpu  # noqa: F401 — populates the registry + sys.modules
    from paddle_tpu.core import dispatch

    problems = (check_primitives() + check_all_exports()
                + check_metric_registry() + check_diagnostic_registry())
    n_mods = sum(1 for m in sys.modules
                 if m == "paddle_tpu" or m.startswith("paddle_tpu."))
    from paddle_tpu import observability

    if problems:
        print(f"lint_registry: {len(problems)} violation(s) over "
              f"{len(dispatch.PRIMITIVES)} primitives / {n_mods} modules / "
              f"{len(observability.registry)} metrics:",
              file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print(f"lint_registry: OK ({len(dispatch.PRIMITIVES)} primitives, "
          f"{n_mods} modules, {len(observability.registry)} metrics "
          f"audited)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
