#!/usr/bin/env python
"""Fit the analytical comm model to a measured metrics dump.

Usage:
    python tools/comm_calibrate.py metrics.json [-o comm_params.json]
    python tools/comm_calibrate.py metrics.json --predicted-flops 1.2e12
    export PADDLE_TPU_COMM_PARAMS=comm_params.json   # picked up by program_cost

Input is a JSON metrics dump written by ``paddle_tpu.observability.dump``
(or any run with ``PADDLE_TPU_METRICS_DUMP=metrics.json``) that contains
the PR 5 comm telemetry counters — ``comm.collective_calls`` /
``comm.collective_bytes`` / ``comm.collective_seconds``, labeled by
``op=`` and ``group=``. The alpha-beta fit
(``calibrate_comm_model``) turns those into ``link_latency_seconds`` and
``link_bytes_per_second``; with ``--predicted-flops`` (the
``program_cost(...).flops`` of the program the dump came from) the
``train.step_seconds`` histogram additionally pins
``flops_per_second`` (``calibrate_step_time_model``), so the whole
predicted-step-time model is fitted, not just the comm term.

The fitted parameters are written as JSON in exactly the shape
``PADDLE_TPU_COMM_PARAMS`` accepts — point the env var at the output
file (or paste the JSON inline) and every subsequent ``program_cost`` /
``search_shard_plans`` call prices collectives with the measured
machine constants instead of the built-in defaults. Exits non-zero if
the dump cannot be read; a dump with no comm series still produces the
(default) parameters, with a warning on stderr, so the tool is safe to
wire into pipelines that sometimes run single-chip.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("dump", help="JSON metrics dump containing "
                                 "comm.collective_* series")
    ap.add_argument("-o", "--output", default=None,
                    help="write fitted params JSON here (default: stdout)")
    ap.add_argument("--predicted-flops", type=float, default=None,
                    help="model-predicted FLOPs of the program the dump "
                         "came from; with train.step_seconds in the dump "
                         "this also fits flops_per_second")
    args = ap.parse_args(argv)

    try:
        with open(args.dump) as f:
            dump = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"comm_calibrate: cannot read {args.dump!r}: {e}",
              file=sys.stderr)
        return 1

    from paddle_tpu.static.analysis.comm_cost import (
        CommModelParams, calibrate_comm_model, calibrate_step_time_model)

    metrics = dump.get("metrics", dump) if isinstance(dump, dict) else {}
    if not (metrics.get("comm.collective_seconds") or {}).get("series"):
        print("comm_calibrate: dump has no comm.collective_seconds series; "
              "emitting default link parameters", file=sys.stderr)

    if args.predicted_flops is not None:
        params = calibrate_step_time_model(dump, args.predicted_flops)
    else:
        params = calibrate_comm_model(dump)

    defaults = CommModelParams()
    doc = params.to_dict()
    text = json.dumps(doc, indent=2, sort_keys=True)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text + "\n")
        print(f"comm_calibrate: wrote {args.output}  "
              f"(export PADDLE_TPU_COMM_PARAMS={args.output})",
              file=sys.stderr)
    else:
        print(text)
    for key, fitted, base in (
            ("link_bytes_per_second", params.link_bytes_per_second,
             defaults.link_bytes_per_second),
            ("link_latency_seconds", params.link_latency_seconds,
             defaults.link_latency_seconds)):
        if fitted != base:
            print(f"comm_calibrate: {key}: {base:.3g} -> {fitted:.3g}",
                  file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
