"""FLOPs accounting.

Reference: python/paddle/utils/flops.py (per-op registry keyed by op_type)
and python/paddle/hapi/dynamic_flops.py (`paddle.flops(net, input_size)`
layer-walking summary). The TPU build adds an XLA-native third path:
``xla_flops(fn, *args)`` reads the compiled executable's cost analysis, which
is exactly what the hardware will execute after fusion.
"""
from __future__ import annotations

from typing import Callable

__all__ = ["flops", "register_flops", "dynamic_flops", "xla_flops"]

_FLOPS_COMPUTE_FUNC_MAP: dict[str, Callable] = {}


def _prod(s):
    out = 1
    for v in s:
        out *= v
    return out


def flops(op_type: str, input_shapes: dict, attrs: dict) -> int:
    """Count FLOPs for one op invocation; unknown op types count 0."""
    func = _FLOPS_COMPUTE_FUNC_MAP.get(op_type)
    if func is None:
        return 0
    try:
        return func(input_shapes, attrs)
    except Exception:
        return 0


def register_flops(op_type: str):
    def register(func):
        _FLOPS_COMPUTE_FUNC_MAP[op_type] = func
        return func

    return register


@register_flops("matmul")
@register_flops("matmul_v2")
def _matmul_flops(input_shapes, attrs):
    x = list(input_shapes.get("X", input_shapes.get("x"))[0])
    y = list(input_shapes.get("Y", input_shapes.get("y"))[0])
    if attrs.get("transpose_X") or attrs.get("transpose_x") or attrs.get("trans_x"):
        x[-1], x[-2] = x[-2], x[-1]
    if attrs.get("transpose_Y") or attrs.get("transpose_y") or attrs.get("trans_y"):
        y[-1], y[-2] = y[-2], y[-1]
    batch = _prod(x[:-2]) if len(x) > 2 else (_prod(y[:-2]) if len(y) > 2 else 1)
    return 2 * batch * x[-2] * x[-1] * y[-1]


@register_flops("conv2d")
def _conv2d_flops(input_shapes, attrs):
    inp = input_shapes.get("Input", input_shapes.get("x"))[0]
    filt = input_shapes.get("Filter", input_shapes.get("weight"))[0]
    strides = attrs.get("strides", [1, 1])
    paddings = attrs.get("paddings", [0, 0])
    dilations = attrs.get("dilations", [1, 1])
    groups = attrs.get("groups", 1)
    n, _, h, w = inp
    c_out, c_in_g, kh, kw = filt
    ho = (h + 2 * paddings[0] - dilations[0] * (kh - 1) - 1) // strides[0] + 1
    wo = (w + 2 * paddings[1] - dilations[1] * (kw - 1) - 1) // strides[1] + 1
    return 2 * n * c_out * ho * wo * c_in_g * kh * kw


@register_flops("relu")
@register_flops("gelu")
@register_flops("silu")
@register_flops("dropout")
@register_flops("softmax")
@register_flops("elementwise_add")
@register_flops("elementwise_mul")
@register_flops("elementwise_div")
def _elementwise_flops(input_shapes, attrs):
    key = next(iter(input_shapes))
    return _prod(input_shapes[key][0])


@register_flops("layer_norm")
@register_flops("rms_norm")
def _norm_flops(input_shapes, attrs):
    key = next(iter(input_shapes))
    return 5 * _prod(input_shapes[key][0])


@register_flops("c_embedding")
@register_flops("embedding")
def _embedding_flops(input_shapes, attrs):
    return 0


def xla_flops(fn, *args, **kwargs) -> int:
    """FLOPs of `fn(*args)` as XLA's compiled cost analysis reports them —
    the post-fusion count the TPU actually executes."""
    import jax

    from ..core.tensor import Tensor

    def unwrap(a):
        return a._value if isinstance(a, Tensor) else a

    args = [unwrap(a) for a in args]
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    analysis = compiled.cost_analysis()
    if isinstance(analysis, list):
        analysis = analysis[0]
    return int(analysis.get("flops", 0))


def dynamic_flops(net, input_size, custom_ops=None, print_detail=False) -> int:
    """`paddle.flops(net, input_size)` — hook-based per-layer FLOPs table.

    Reference: python/paddle/hapi/dynamic_flops.py:28.
    """
    import numpy as np

    from .. import nn
    from ..core.tensor import Tensor

    custom_ops = custom_ops or {}
    counts: dict[int, dict] = {}
    handles = []

    def count_linear(layer, inp, out):
        w = layer.weight.shape
        return _prod(out.shape) * w[0] * 2

    def count_conv(layer, inp, out):
        kshape = layer.weight.shape  # [C_out, C_in/g, kh, kw]
        return 2 * _prod(out.shape) * _prod(kshape[1:])

    def count_norm(layer, inp, out):
        return 5 * _prod(out.shape)

    def count_act(layer, inp, out):
        return _prod(out.shape)

    def count_pool(layer, inp, out):
        return _prod(out.shape)

    handlers = {
        nn.Linear: count_linear,
        nn.Conv2D: count_conv,
        nn.BatchNorm2D: count_norm,
        nn.BatchNorm1D: count_norm,
        nn.LayerNorm: count_norm,
        nn.ReLU: count_act,
        nn.GELU: count_act,
        nn.Sigmoid: count_act,
        nn.Softmax: count_act,
        nn.MaxPool2D: count_pool,
        nn.AvgPool2D: count_pool,
        nn.AdaptiveAvgPool2D: count_pool,
    }
    handlers.update(custom_ops)

    def make_hook(handler):
        def hook(layer, inp, out):
            o = out[0] if isinstance(out, (tuple, list)) else out
            i = inp[0] if isinstance(inp, (tuple, list)) else inp
            entry = counts.get(id(layer))
            if entry is None:
                n_params = sum(
                    _prod(p.shape)
                    for p in layer.parameters(include_sublayers=False)
                )
                counts[id(layer)] = {
                    "layer": layer,
                    "flops": handler(layer, i, o),
                    "params": n_params,
                    "output_shape": list(o.shape),
                }
            else:
                # shared module applied more than once: accumulate flops
                entry["flops"] += handler(layer, i, o)
                entry["output_shape"] = list(o.shape)

        return hook

    for sub in net.sublayers(include_self=True):
        handler = handlers.get(type(sub))
        if handler is not None:
            handles.append(sub.register_forward_post_hook(make_hook(handler)))

    was_training = getattr(net, "training", True)
    net.eval()
    x = Tensor(np.zeros(input_size, dtype="float32"))
    try:
        net(x)
    finally:
        for h in handles:
            h.remove()
        if was_training:
            net.train()

    total_flops = sum(v["flops"] for v in counts.values())
    total_params = sum(v["params"] for v in counts.values())
    if print_detail:
        print(f"{'Layer':<30}{'Output Shape':<24}{'Params':>12}{'FLOPs':>16}")
        for v in counts.values():
            print(f"{type(v['layer']).__name__:<30}"
                  f"{str(v['output_shape']):<24}{v['params']:>12}{v['flops']:>16}")
    print(f"Total Flops: {total_flops}     Total Params: {total_params}")
    return int(total_flops)
