"""Weight/data file cache resolution.

Reference: python/paddle/utils/download.py (get_weights_path_from_url /
get_path_from_url with a ~/.cache download directory and md5 checks).

This environment has zero network egress, so the TPU build resolves URLs
against the local cache only: a file already placed under
``$PADDLE_TPU_HOME/weights`` (default ``~/.cache/paddle_tpu``) by an offline
sync is returned; anything else raises with instructions. Decompression of
cached .tar/.zip archives is supported like the reference.
"""
from __future__ import annotations

import hashlib
import os
import os.path as osp
import tarfile
import zipfile

__all__ = ["get_weights_path_from_url", "get_path_from_url", "WEIGHTS_HOME"]

WEIGHTS_HOME = osp.expanduser(
    os.environ.get("PADDLE_TPU_HOME", "~/.cache/paddle_tpu/weights")
)


def md5file(fname: str) -> str:
    md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            md5.update(chunk)
    return md5.hexdigest()


def _md5check(fullname: str, md5sum: str | None) -> bool:
    if md5sum is None:
        return True
    return md5file(fullname) == md5sum


def _decompress(fname: str) -> str:
    dirname = osp.dirname(fname)
    if tarfile.is_tarfile(fname):
        with tarfile.open(fname) as f:
            names = f.getnames()
            f.extractall(path=dirname, filter="data")
        root = names[0].split(os.sep)[0]
        return osp.join(dirname, root)
    if zipfile.is_zipfile(fname):
        with zipfile.ZipFile(fname) as f:
            names = f.namelist()
            f.extractall(path=dirname)
        root = names[0].split(os.sep)[0]
        return osp.join(dirname, root)
    return fname


def get_path_from_url(url: str, root_dir: str | None = None,
                      md5sum: str | None = None, check_exist: bool = True,
                      decompress: bool = True) -> str:
    root_dir = root_dir or WEIGHTS_HOME
    fname = osp.split(url)[-1]
    fullname = osp.join(root_dir, fname)
    if osp.exists(fullname) and _md5check(fullname, md5sum):
        if decompress and (fullname.endswith((".tar", ".tar.gz", ".tgz", ".zip"))):
            return _decompress(fullname)
        return fullname
    raise RuntimeError(
        f"'{fname}' not found in local cache {root_dir} and this build has no "
        f"network egress. Place the file there manually (source: {url})."
    )


def get_weights_path_from_url(url: str, md5sum: str | None = None) -> str:
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
