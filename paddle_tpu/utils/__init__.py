"""paddle.utils parity.

Reference surface: python/paddle/utils/__init__.py — deprecated decorator,
dlpack interchange, unique_name, download cache, flops accounting,
install_check, layer-structure helpers, cpp_extension.
"""
from __future__ import annotations

from . import deprecated as _deprecated_mod  # noqa: F401
from .deprecated import deprecated
from . import dlpack
from . import unique_name
from . import download
from . import flops as _flops_mod
from .flops import flops, register_flops
from . import install_check
from .install_check import run_check
from .lazy_import import try_import
from .layers_utils import flatten, pack_sequence_as, map_structure

__all__ = [
    "deprecated", "dlpack", "unique_name", "download", "flops",
    "register_flops", "install_check", "run_check", "try_import",
    "flatten", "pack_sequence_as", "map_structure", "require_version",
]


def require_version(min_version: str, max_version: str | None = None) -> None:
    """Check that the installed framework version is within range.

    Reference: python/paddle/utils/__init__.py require_version.
    """
    from .. import __version__

    def _parse(v):
        parts = []
        for p in str(v).split("."):
            digits = "".join(ch for ch in p if ch.isdigit())
            parts.append(int(digits) if digits else 0)
        while len(parts) < 3:
            parts.append(0)
        return tuple(parts[:3])

    if not isinstance(min_version, str):
        raise TypeError("min_version must be a str")
    cur = _parse(__version__)
    if cur < _parse(min_version):
        raise Exception(
            f"installed version {__version__} < required min {min_version}"
        )
    if max_version is not None and cur > _parse(max_version):
        raise Exception(
            f"installed version {__version__} > allowed max {max_version}"
        )
