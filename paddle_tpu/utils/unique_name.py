"""Unique-name generator.

Reference: python/paddle/base/unique_name.py re-exported through
python/paddle/utils/unique_name.py (generate/switch/guard).
"""
from __future__ import annotations

import contextlib
from collections import defaultdict

__all__ = ["generate", "switch", "guard", "generate_with_ignorable_key"]


class UniqueNameGenerator:
    def __init__(self, prefix: str = ""):
        self.prefix = prefix
        self.ids: defaultdict[str, int] = defaultdict(int)

    def __call__(self, key: str) -> str:
        tmp = self.ids[key]
        self.ids[key] += 1
        return "_".join([self.prefix + key, str(tmp)]) if self.prefix else f"{key}_{tmp}"


generator = UniqueNameGenerator()


def generate(key: str) -> str:
    return generator(key)


def generate_with_ignorable_key(key: str) -> str:
    return generator(key)


def switch(new_generator: UniqueNameGenerator | None = None):
    global generator
    old = generator
    generator = new_generator if new_generator is not None else UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = UniqueNameGenerator(new_generator)
    elif isinstance(new_generator, bytes):
        new_generator = UniqueNameGenerator(new_generator.decode())
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
