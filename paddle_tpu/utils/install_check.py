"""Installation self-check.

Reference: python/paddle/utils/install_check.py (run_check trains a tiny
linear model on one and, when available, multiple devices and prints a
verdict). TPU form: one compiled train step single-device, then the same
step pjit-sharded over all visible devices.
"""
from __future__ import annotations

__all__ = ["run_check"]


def run_check() -> None:
    import jax
    import jax.numpy as jnp

    devices = jax.devices()
    print(f"Running verify on {len(devices)} {devices[0].platform} device(s).")

    def loss_fn(w, x, y):
        pred = x @ w
        return jnp.mean((pred - y) ** 2)

    grad_fn = jax.jit(jax.grad(loss_fn))
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (8, 4), dtype=jnp.float32)
    w = jnp.zeros((4, 1), dtype=jnp.float32)
    y = jnp.ones((8, 1), dtype=jnp.float32)
    g = grad_fn(w, x, y)
    assert g.shape == (4, 1)
    print("paddle_tpu works well on 1 device.")

    if len(devices) > 1:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(devices, ("dp",))
        sharded = jax.jit(
            jax.grad(loss_fn),
            in_shardings=(NamedSharding(mesh, P()), NamedSharding(mesh, P("dp")),
                          NamedSharding(mesh, P("dp"))),
        )
        g = sharded(w, x, y)
        assert g.shape == (4, 1)
        print(f"paddle_tpu works well on {len(devices)} devices.")
    print("paddle_tpu is installed successfully!")
