"""Optional-dependency import helper.

Reference: python/paddle/utils/lazy_import.py (try_import).
"""
from __future__ import annotations

import importlib

__all__ = ["try_import"]


def try_import(module_name: str, err_msg: str | None = None):
    try:
        return importlib.import_module(module_name)
    except ImportError as e:
        if err_msg is None:
            err_msg = (
                f"Failed importing {module_name}. This likely means that some "
                f"modules require additional dependencies that have to be "
                f"manually installed (usually with `pip install {module_name}`)."
            )
        raise ImportError(err_msg) from e
