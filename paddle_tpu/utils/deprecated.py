"""Deprecation decorator.

Reference: python/paddle/utils/deprecated.py — annotates the docstring and
emits a DeprecationWarning with since/update_to/reason on first call.
"""
from __future__ import annotations

import functools
import warnings

__all__ = ["deprecated"]


def deprecated(update_to: str = "", since: str = "", reason: str = "",
               level: int = 0):
    def decorator(func):
        msg = f'API "{func.__module__}.{func.__name__}" is deprecated'
        if since:
            msg += f" since {since}"
        if update_to:
            msg += f', and will be removed in future versions. Please use "{update_to}" instead'
        if reason:
            msg += f". Reason: {reason}"
        if level == 2:
            raise RuntimeError(msg)

        existing = func.__doc__ or ""
        func.__doc__ = f"\n\n.. warning::\n    {msg}\n\n" + existing

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            if level == 1:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        return wrapper

    return decorator
