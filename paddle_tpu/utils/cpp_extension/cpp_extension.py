"""JIT / setuptools build of native extensions.

Reference: python/paddle/utils/cpp_extension/ (CppExtension/CUDAExtension/
setup/load building custom operators against the paddle C++ headers).

TPU-native shape: custom *device* kernels are Pallas (pure Python), so this
module's job is the host-side native path — compile C/C++ sources into a
shared object with g++ and expose it via ctypes (pybind11 is not available
in this image; the framework's own runtime in csrc/ uses a C ABI the same
way). `load()` returns the loaded ctypes.CDLL; `setup()` defers to
setuptools for installable packages.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

__all__ = ["CppExtension", "CUDAExtension", "load", "setup"]


class CppExtension:
    def __init__(self, sources, *args, **kwargs):
        self.sources = list(sources)
        self.extra_compile_args = kwargs.get("extra_compile_args", [])
        self.extra_link_args = kwargs.get("extra_link_args", [])
        self.include_dirs = kwargs.get("include_dirs", [])


def CUDAExtension(*args, **kwargs):
    raise RuntimeError(
        "CUDAExtension is not supported in the TPU build: device kernels are "
        "Pallas (see paddle_tpu/ops/pallas). Use CppExtension for host-side "
        "native code."
    )


def _build_dir() -> str:
    d = os.environ.get(
        "PADDLE_TPU_EXTENSION_DIR",
        os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions"),
    )
    os.makedirs(d, exist_ok=True)
    return d


def load(name: str, sources, extra_cxx_cflags=None, extra_include_paths=None,
         build_directory: str | None = None, verbose: bool = False, **kwargs):
    """Compile `sources` into lib<name>.so and load it via ctypes.

    Rebuilds only when source content changes (content-hash cache key),
    mirroring the reference's version-checked JIT build.
    """
    sources = [os.path.abspath(s) for s in sources]
    build_directory = build_directory or _build_dir()
    h = hashlib.sha1()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    tag = h.hexdigest()[:12]
    out = os.path.join(build_directory, f"lib{name}_{tag}.so")
    if not os.path.exists(out):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-o", out]
        for inc in extra_include_paths or []:
            cmd += ["-I", inc]
        cmd += list(extra_cxx_cflags or [])
        cmd += sources
        if verbose:
            print("Compiling:", " ".join(cmd))
        subprocess.run(cmd, check=True, capture_output=not verbose)
    return ctypes.CDLL(out)


def setup(**attrs):
    """setuptools-based installable build of CppExtension modules."""
    import setuptools
    from setuptools.command.build_ext import build_ext

    ext_modules = attrs.pop("ext_modules", [])
    converted = []
    for ext in ext_modules if isinstance(ext_modules, list) else [ext_modules]:
        if isinstance(ext, CppExtension):
            converted.append(
                setuptools.Extension(
                    name=attrs.get("name", "paddle_tpu_ext"),
                    sources=ext.sources,
                    extra_compile_args=["-std=c++17"] + list(ext.extra_compile_args),
                    extra_link_args=list(ext.extra_link_args),
                    include_dirs=list(ext.include_dirs),
                    language="c++",
                )
            )
        else:
            converted.append(ext)
    attrs["ext_modules"] = converted
    attrs.setdefault("cmdclass", {})["build_ext"] = build_ext
    return setuptools.setup(**attrs)
