"""C-ABI custom-kernel registration.

Reference: paddle/phi/core/custom_kernel.h:25 and phi/capi/include/ —
out-of-tree kernels compiled against a stable C ABI join the PHI kernel
factory and dispatch like built-ins.

TPU re-design: the device compute path is XLA/Pallas, so a C kernel is a
HOST kernel. ``register_cpp_kernel`` wires a ``cpp_extension.load``-built
C function into ``core.dispatch`` as a first-class primitive:

- the forward runs through ``jax.pure_callback``, so the op works both
  eagerly and inside ``jit`` (XLA schedules a host callback — the same
  architecture the reference uses for CPU kernels inside a GPU graph);
- an optional C (or Python) VJP makes it differentiable: the primitive
  is wrapped in ``jax.custom_vjp`` so ``jax.grad``/``loss.backward()``
  both see it, and the eager tape uses the same rule.

C ABI (ptpu_c_api.h style, mirroring phi/capi's PD_Tensor accessors)::

    typedef struct {
      void*          data;   /* element buffer, dense row-major      */
      const int64_t* shape;
      int32_t        ndim;
      int32_t        dtype;  /* 0=f32 1=f64 2=i32 3=i64 4=u8 5=bool */
    } PtpuTensor;

    /* return 0 on success */
    int my_kernel(int32_t n_in, const PtpuTensor* ins, PtpuTensor* out);

The output buffer is allocated by the caller from the registered shape
rule, exactly like the reference's InferMeta-then-Kernel split.
"""
from __future__ import annotations

import ctypes
from typing import Callable, Optional, Sequence

import numpy as np

__all__ = ["PtpuTensor", "register_cpp_kernel"]


class PtpuTensor(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.c_void_p),
        ("shape", ctypes.POINTER(ctypes.c_int64)),
        ("ndim", ctypes.c_int32),
        ("dtype", ctypes.c_int32),
    ]


_DTYPE_CODES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.uint8): 4,
    np.dtype(np.bool_): 5,
}


def _as_c_tensor(arr: np.ndarray, keepalive: list) -> PtpuTensor:
    arr = np.ascontiguousarray(arr)
    shape = (ctypes.c_int64 * max(arr.ndim, 1))(*arr.shape)
    keepalive.extend((arr, shape))
    code = _DTYPE_CODES.get(arr.dtype)
    if code is None:
        raise TypeError(
            f"C custom kernels accept {sorted(str(k) for k in _DTYPE_CODES)}"
            f", got {arr.dtype} (bf16 compute belongs on the device — use "
            f"a Pallas kernel)")
    return PtpuTensor(
        data=arr.ctypes.data_as(ctypes.c_void_p), shape=shape,
        ndim=arr.ndim, dtype=code)


def _host_call(cfunc, out_spec, arrays: Sequence[np.ndarray]) -> np.ndarray:
    keep: list = []
    ins = (PtpuTensor * max(len(arrays), 1))(
        *[_as_c_tensor(np.asarray(a), keep) for a in arrays])
    out = np.zeros(out_spec.shape, np.dtype(out_spec.dtype))
    out_c = _as_c_tensor(out, keep)
    rc = cfunc(ctypes.c_int32(len(arrays)), ins, ctypes.byref(out_c))
    if rc != 0:
        raise RuntimeError(f"C custom kernel returned {rc}")
    return out


def register_cpp_kernel(name: str, lib, symbol: Optional[str] = None, *,
                        out_shape_fn: Optional[Callable] = None,
                        vjp: Optional[Callable] = None,
                        vjp_symbol: Optional[str] = None,
                        nondiff: bool = False):
    """Register the C function ``symbol`` (default: ``name``) from a
    ``cpp_extension.load``-built library as primitive ``name``.

    out_shape_fn(*avals) -> jax.ShapeDtypeStruct — the InferMeta rule
    (default: same shape/dtype as the first input).
    vjp: Python rule ``vjp(grads_out, saved, **static) -> grads`` (the
    Primitive VJP convention), or pass ``vjp_symbol`` naming a C kernel
    in the same library with the ABI ``f(n_in, ins, out)`` where ins =
    (dy, *forward_inputs) and out = dx for the first input.
    With neither, the op is marked non-differentiable.
    """
    import jax

    from ...core.dispatch import register_primitive

    cfunc = getattr(lib, symbol or name)
    cfunc.argtypes = [ctypes.c_int32, ctypes.POINTER(PtpuTensor),
                      ctypes.POINTER(PtpuTensor)]
    cfunc.restype = ctypes.c_int32

    def infer_out(*arrays):
        if out_shape_fn is not None:
            return out_shape_fn(*[
                jax.ShapeDtypeStruct(np.shape(a), a.dtype)
                for a in arrays])
        return jax.ShapeDtypeStruct(arrays[0].shape, arrays[0].dtype)

    if vjp is None and vjp_symbol is not None:
        cbwd = getattr(lib, vjp_symbol)
        cbwd.argtypes = cfunc.argtypes
        cbwd.restype = ctypes.c_int32

        def vjp(grads_out, saved, **static):  # noqa: F811
            dy = grads_out[0]
            spec = jax.ShapeDtypeStruct(saved[0].shape, saved[0].dtype)
            dx = jax.pure_callback(
                lambda *a: _host_call(cbwd, spec, a), spec, dy, *saved,
                vmap_method="sequential")
            return (dx,) + (None,) * (len(saved) - 1)

    def raw_forward(*arrays, **static):
        spec = infer_out(*arrays)
        return jax.pure_callback(
            lambda *a: _host_call(cfunc, spec, a), spec, *arrays,
            vmap_method="sequential")

    if vjp is not None:
        # jax.custom_vjp so jax.grad / traced training steps also see
        # the rule, not just the eager tape
        wrapped = jax.custom_vjp(raw_forward)

        def fwd_rule(*arrays, **static):
            out = raw_forward(*arrays, **static)
            return out, arrays

        def bwd_rule(saved, g):
            grads = vjp((g,), saved)

            def zero_for(s):
                # custom_vjp requires float0 tangents for integer
                # inputs (gather-like C kernels take index operands)
                if not jax.numpy.issubdtype(s.dtype, jax.numpy.inexact):
                    return np.zeros(s.shape, jax.dtypes.float0)
                return jax.numpy.zeros_like(s)

            return tuple(zero_for(s) if d is None else d
                         for d, s in zip(grads, saved))

        wrapped.defvjp(fwd_rule, bwd_rule)
        forward = wrapped
    else:
        forward = raw_forward
        nondiff = True

    return register_primitive(name, forward, vjp=vjp, nondiff=nondiff)
