from .cpp_extension import CppExtension, CUDAExtension, load, setup
from .custom_kernel import PtpuTensor, register_cpp_kernel

__all__ = ["CppExtension", "CUDAExtension", "load", "setup",
           "PtpuTensor", "register_cpp_kernel"]


def get_build_directory(verbose=False):
    """Reference: utils/cpp_extension/extension_utils.py
    get_build_directory — the default dir `load` builds into
    ($PADDLE_EXTENSION_DIR or ~/.cache/paddle_tpu/extensions)."""
    import os

    root = os.environ.get("PADDLE_EXTENSION_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu", "extensions")
    os.makedirs(root, exist_ok=True)
    return root


__all__ += ["get_build_directory"]
