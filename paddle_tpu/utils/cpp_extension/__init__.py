from .cpp_extension import CppExtension, CUDAExtension, load, setup

__all__ = ["CppExtension", "CUDAExtension", "load", "setup"]
