"""Nested-structure helpers.

Reference: python/paddle/utils/layers_utils.py (flatten / pack_sequence_as /
map_structure over arbitrarily nested lists/tuples/dicts). On TPU these ride
jax.tree_util so the flattening order matches what pjit/jit see.
"""
from __future__ import annotations

import jax

__all__ = ["flatten", "pack_sequence_as", "map_structure", "to_sequence"]


def flatten(nest):
    return jax.tree_util.tree_leaves(
        nest, is_leaf=lambda x: not isinstance(x, (list, tuple, dict))
    )


def pack_sequence_as(structure, flat_sequence):
    treedef = jax.tree_util.tree_structure(
        structure, is_leaf=lambda x: not isinstance(x, (list, tuple, dict))
    )
    return jax.tree_util.tree_unflatten(treedef, list(flat_sequence))


def map_structure(func, *structures):
    return jax.tree_util.tree_map(
        func, *structures,
        is_leaf=lambda x: not isinstance(x, (list, tuple, dict)),
    )


def to_sequence(nest):
    if isinstance(nest, (list, tuple)):
        return list(nest)
    return [nest]
