"""DLPack interchange.

Reference: python/paddle/utils/dlpack.py (to_dlpack/from_dlpack).

On TPU the PJRT plugin does not expose zero-copy external references, so
the interchange path stages through host memory (numpy implements the
DLPack protocol); CPU arrays interchange zero-copy where the consumer
supports it.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    if not isinstance(x, Tensor):
        raise TypeError(
            f"The type of 'x' in to_dlpack must be paddle_tpu.Tensor, but received {type(x)}."
        )
    # np.asarray of a jax array is readonly; DLPack can't signal readonly,
    # so export an owned writable copy.
    host = np.array(x._value, copy=True)
    return host.__dlpack__()


def from_dlpack(dlpack) -> Tensor:
    """Accepts a DLPack capsule or any object implementing ``__dlpack__``
    (torch/numpy/jax arrays)."""
    if hasattr(dlpack, "__dlpack__"):
        host = np.from_dlpack(dlpack)
    else:
        # raw capsule: numpy's from_dlpack consumes capsules via a shim
        host = np.from_dlpack(_CapsuleWrapper(dlpack))
    return Tensor._from_value(np.ascontiguousarray(host))


class _CapsuleWrapper:
    def __init__(self, capsule):
        self._capsule = capsule

    def __dlpack__(self, stream=None):
        return self._capsule

    def __dlpack_device__(self):
        # kDLCPU = 1; host-staged capsules are always CPU-resident
        return (1, 0)
