"""paddle.sparse.nn.functional — sparse NN ops over BCOO tensors.

Reference: python/paddle/sparse/nn/functional/ (conv.py, pooling.py,
activation.py, transformer.py over phi/kernels/sparse/). TPU stance:
XLA has no sparse-conv kernels, so convolutions densify, run the dense
MXU conv, and re-sparsify; submanifold variants mask the output to the
input's active sites — exactly the subm_conv contract at stride 1.
Activations act on stored values only (f(0) = 0 holds for this family),
preserving sparsity structure.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor


def _sp():
    # lazy: this package is imported during paddle_tpu.sparse's own init
    import paddle_tpu.sparse as sparse

    return sparse

__all__ = [
    "conv2d", "conv3d", "subm_conv2d", "subm_conv2d_igemm", "subm_conv3d",
    "subm_conv3d_igemm", "max_pool3d", "relu", "relu6", "leaky_relu",
    "softmax", "attention",
]


def _values_op(x, fn):
    sp = _sp()
    coo = sp._as_coo(x)
    import jax.experimental.sparse as jsparse

    return sp._wrap_like(x, jsparse.BCOO((fn(coo.data), coo.indices),
                                         shape=coo.shape))


def relu(x, name=None):
    return _values_op(x, jax.nn.relu)


def relu6(x, name=None):
    return _values_op(x, lambda v: jnp.clip(v, 0.0, 6.0))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _values_op(x, lambda v: jnp.where(v >= 0, v,
                                             negative_slope * v))


def softmax(x, axis=-1, name=None):
    """Softmax over the stored values of each row (reference: sparse
    softmax ignores implicit zeros — CSR row-wise semantics)."""
    sp = _sp()
    coo = sp._as_coo(x)
    if axis not in (-1, coo.ndim - 1):
        raise ValueError("sparse softmax supports the last axis only")
    import jax.experimental.sparse as jsparse
    import numpy as np

    idx = np.asarray(coo.indices)
    rows = idx[:, :-1]
    # group by row: stable segment ids over the leading indices
    row_key = np.zeros(idx.shape[0], np.int64)
    mul = 1
    for d in range(rows.shape[1] - 1, -1, -1):
        row_key += rows[:, d] * mul
        mul *= coo.shape[d]
    uniq, seg = np.unique(row_key, return_inverse=True)
    seg = jnp.asarray(seg)
    n = int(uniq.size)
    vals = coo.data
    mx = jax.ops.segment_max(vals, seg, num_segments=n)
    e = jnp.exp(vals - mx[seg])
    s = jax.ops.segment_sum(e, seg, num_segments=n)
    return sp._wrap_like(x, jsparse.BCOO((e / s[seg], coo.indices),
                                         shape=coo.shape))


def _dense_conv(x, weight, bias, stride, padding, dilation, groups,
                nd, subm, data_format):
    """Densify -> dense conv (NDHWC/NHWC layouts like the reference
    sparse convs) -> re-sparsify; subm masks to the input active sites."""
    import numpy as np

    dense = x.to_dense() if hasattr(x, "to_dense") else x
    xv = dense._value if isinstance(dense, Tensor) else dense
    wv = weight._value if isinstance(weight, Tensor) else jnp.asarray(weight)
    s = (stride,) * nd if isinstance(stride, int) else tuple(stride)
    d = (dilation,) * nd if isinstance(dilation, int) else tuple(dilation)
    if isinstance(padding, int):
        pad = [(padding, padding)] * nd
    elif padding == "SAME" or padding == "VALID":
        pad = padding
    else:
        pad = [(p, p) if isinstance(p, int) else tuple(p) for p in padding]
    dn_in = "NHWC" if nd == 2 else "NDHWC"
    dn_k = "HWIO" if nd == 2 else "DHWIO"
    out = jax.lax.conv_general_dilated(
        xv, wv, s, pad, rhs_dilation=d,
        dimension_numbers=(dn_in, dn_k, dn_in),
        feature_group_count=groups)
    if bias is not None:
        bv = bias._value if isinstance(bias, Tensor) else jnp.asarray(bias)
        out = out + bv
    if subm:
        # submanifold: outputs only at the input's active sites
        mask = (jnp.abs(xv).sum(-1, keepdims=True) > 0).astype(out.dtype)
        out = out * mask
    t = Tensor._from_value(out)
    return t.to_sparse_coo(t.ndim - 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    """Reference: sparse/nn/functional/conv.py conv2d ([N,H,W,C] layout)."""
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups,
                       2, False, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups,
                       3, False, data_format)


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups,
                       2, True, data_format)


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    return _dense_conv(x, weight, bias, stride, padding, dilation, groups,
                       3, True, data_format)


# igemm variants: algorithm choice on GPU; same math here
subm_conv2d_igemm = subm_conv2d
subm_conv3d_igemm = subm_conv3d


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Reference: sparse/nn/functional/pooling.py max_pool3d."""
    dense = x.to_dense() if hasattr(x, "to_dense") else x
    xv = dense._value if isinstance(dense, Tensor) else dense
    k = (kernel_size,) * 3 if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    s = k if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else tuple(stride))
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    dims = (1,) + k + (1,)
    strides = (1,) + s + (1,)
    pads = [(0, 0)] + [(pp, pp) for pp in p] + [(0, 0)]
    out = jax.lax.reduce_window(xv, -jnp.inf, jax.lax.max, dims, strides,
                                pads)
    out = jnp.where(jnp.isfinite(out), out, 0.0)
    t = Tensor._from_value(out.astype(xv.dtype))
    return t.to_sparse_coo(t.ndim - 1)


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-mask attention (reference: sparse/nn/functional/
    transformer.py — scores kept only at sparse_mask's nonzeros)."""
    from ....nn.functional.attention import scaled_dot_product_attention

    qd = query.to_dense() if hasattr(query, "to_dense") else query
    kd = key.to_dense() if hasattr(key, "to_dense") else key
    vd = value.to_dense() if hasattr(value, "to_dense") else value
    md = sparse_mask.to_dense() if hasattr(sparse_mask, "to_dense") \
        else sparse_mask
    import numpy as np

    mv = md._value if isinstance(md, Tensor) else jnp.asarray(md)
    add_mask = jnp.where(mv != 0, 0.0, -1e9).astype(jnp.float32)
    return scaled_dot_product_attention(
        qd, kd, vd, attn_mask=Tensor._from_value(add_mask))
