"""paddle.sparse.nn — layer wrappers over sparse.nn.functional.

Reference: python/paddle/sparse/nn/ (layer/activation.py, layer/conv.py,
layer/norm.py, layer/pooling.py).
"""
from __future__ import annotations

from . import functional  # noqa: F401
from . import functional as F
from ...nn.layer import Layer

__all__ = [
    "ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm", "SyncBatchNorm",
    "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D", "MaxPool3D",
]


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self.negative_slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class BatchNorm(Layer):
    """Channel batch-norm over the stored values (reference sparse
    BatchNorm normalizes the value tensor's channel dim)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn import BatchNorm1D

        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr)

    def forward(self, x):
        import jax.experimental.sparse as jsparse

        import paddle_tpu.sparse as _sp
        from ...core.tensor import Tensor

        coo = _sp._as_coo(x)
        if coo.data.ndim == 1:
            # fully-sparse layout: regroup so the channel dim is dense —
            # stats are per channel over stored values (reference sparse
            # BatchNorm semantics)
            dense = coo.todense()
            coo = jsparse.BCOO.fromdense(dense, n_dense=1)
        vals = Tensor._from_value(coo.data)
        out = self._bn(vals)
        return _sp._wrap_like(x, jsparse.BCOO((out._value, coo.indices),
                                              shape=coo.shape))


class SyncBatchNorm(BatchNorm):
    """Cross-replica stats ride GSPMD data layouts (see nn.SyncBatchNorm);
    per-host math is identical."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class _SparseConvBase(Layer):
    _nd = 2
    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format=None):
        super().__init__()
        nd = self._nd
        k = (kernel_size,) * nd if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        # [*k, C_in/groups, C_out] — the HWIO/DHWIO layout the dense conv
        # consumes
        self.weight = self.create_parameter(
            list(k) + [in_channels // groups, out_channels],
            attr=weight_attr)
        self.bias = (None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True))

    def forward(self, x):
        fn = {
            (2, False): F.conv2d, (3, False): F.conv3d,
            (2, True): F.subm_conv2d, (3, True): F.subm_conv3d,
        }[(self._nd, self._subm)]
        return fn(x, self.weight, self.bias, self._stride, self._padding,
                  self._dilation, self._groups)


class Conv2D(_SparseConvBase):
    _nd, _subm = 2, False


class Conv3D(_SparseConvBase):
    _nd, _subm = 3, False


class SubmConv2D(_SparseConvBase):
    _nd, _subm = 2, True


class SubmConv3D(_SparseConvBase):
    _nd, _subm = 3, True


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        self._k = kernel_size
        self._s = stride
        self._p = padding

    def forward(self, x):
        return F.max_pool3d(x, self._k, self._s, self._p)
