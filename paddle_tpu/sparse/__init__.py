"""paddle.sparse — COO/CSR sparse tensors and ops.

Reference: paddle/phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h (C++
tensor types), phi/kernels/sparse/ (kernel set), python/paddle/sparse/
(sparse_coo_tensor/sparse_csr_tensor creation, unary/binary/matmul ops,
Tensor.to_sparse_coo/to_dense methods).

TPU re-design: storage is jax.experimental.sparse BCOO/BCSR — XLA
compiles scatter/gather/dot_general programs for them, which is the TPU
analog of the reference's cuSPARSE-backed kernels. Sparse tensors are
inference/feature-engineering objects here (stop_gradient=True), matching
the reference's main sparse use (recommendation/point-cloud feature
paths); autograd flows through to_dense().
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "add", "subtract", "multiply", "divide", "matmul",
    "masked_matmul", "relu", "tanh", "sqrt", "sin", "abs", "neg", "pow",
    "cast", "coalesce", "transpose", "is_same_shape",
]


class _SparseBase:
    @property
    def shape(self):
        return list(self._mat.shape)

    @property
    def dtype(self):
        return self._mat.dtype

    @property
    def ndim(self):
        return self._mat.ndim

    def nnz(self) -> int:
        return int(self._mat.nse)

    def to_dense(self) -> Tensor:
        return Tensor._from_value(self._mat.todense())

    def numpy(self):
        return np.asarray(self._mat.todense())

    def is_sparse(self) -> bool:
        return True

    def __repr__(self):
        return (f"{type(self).__name__}(shape={self.shape}, "
                f"nnz={self.nnz()}, dtype={self.dtype})")


class SparseCooTensor(_SparseBase):
    """COO sparse tensor (reference: phi SparseCooTensor — non_zero_indices
    + non_zero_elements + dims)."""

    def __init__(self, mat: jsparse.BCOO):
        self._mat = mat
        self.stop_gradient = True

    def indices(self) -> Tensor:
        # paddle layout: [sparse_ndim, nnz]; BCOO stores [nnz, sparse_ndim]
        return Tensor._from_value(self._mat.indices.T)

    def values(self) -> Tensor:
        return Tensor._from_value(self._mat.data)

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._mat.sum_duplicates())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(self._mat.sum_duplicates()))

    def is_coalesced(self) -> bool:
        return bool(self._mat.unique_indices)

    # -- operators -------------------------------------------------------
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)


class SparseCsrTensor(_SparseBase):
    """CSR sparse tensor (reference: phi SparseCsrTensor — crows/cols/
    values)."""

    def __init__(self, mat: jsparse.BCSR):
        self._mat = mat
        self.stop_gradient = True

    def crows(self) -> Tensor:
        return Tensor._from_value(self._mat.indptr)

    def cols(self) -> Tensor:
        return Tensor._from_value(self._mat.indices)

    def values(self) -> Tensor:
        return Tensor._from_value(self._mat.data)

    def to_sparse_coo(self, sparse_dim: Optional[int] = None) -> SparseCooTensor:
        return SparseCooTensor(self._mat.to_bcoo())

    def __matmul__(self, other):
        return matmul(self, other)


# ------------------------------------------------------------- creation
def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    """Reference: paddle.sparse.sparse_coo_tensor(indices [sparse_ndim,nnz],
    values [nnz,...], shape)."""
    idx = np.asarray(
        indices._value if isinstance(indices, Tensor) else indices)
    vals = jnp.asarray(
        values._value if isinstance(values, Tensor) else values, dtype=dtype)
    idx = jnp.asarray(idx.T, jnp.int32)  # → [nnz, sparse_ndim]
    if shape is None:
        shape = tuple(int(m) + 1 for m in np.asarray(idx).max(axis=0))
        shape = shape + tuple(vals.shape[1:])
    mat = jsparse.BCOO((vals, idx), shape=tuple(int(s) for s in shape))
    return SparseCooTensor(mat)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCsrTensor:
    """Reference: paddle.sparse.sparse_csr_tensor."""
    def arr(x, dt=None):
        return jnp.asarray(
            x._value if isinstance(x, Tensor) else x, dtype=dt)

    mat = jsparse.BCSR(
        (arr(values, dtype), arr(cols, jnp.int32), arr(crows, jnp.int32)),
        shape=tuple(int(s) for s in shape),
    )
    return SparseCsrTensor(mat)


def _as_coo(x) -> jsparse.BCOO:
    if isinstance(x, SparseCooTensor):
        return x._mat
    if isinstance(x, SparseCsrTensor):
        return x._mat.to_bcoo()
    raise TypeError(f"expected a sparse tensor, got {type(x)}")


def _wrap_like(x, mat: jsparse.BCOO):
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(mat))
    return SparseCooTensor(mat)


# ------------------------------------------------------------- binary ops
def add(x, y):
    """sparse+sparse or sparse+dense (densifies). Reference:
    paddle.sparse.add."""
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        return _wrap_like(x, (_as_coo(x) + _as_coo(y)).sum_duplicates())
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor._from_value(_as_coo(x).todense() + yv)


def subtract(x, y):
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        neg_y = jsparse.BCOO(
            (-_as_coo(y).data, _as_coo(y).indices), shape=tuple(y.shape))
        return _wrap_like(x, (_as_coo(x) + neg_y).sum_duplicates())
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    return Tensor._from_value(_as_coo(x).todense() - yv)


def multiply(x, y):
    """Elementwise multiply. sparse*scalar and sparse*dense keep sparsity
    (dense is sampled at the nonzero positions)."""
    coo = _as_coo(x)
    if isinstance(y, (int, float)):
        return _wrap_like(x, jsparse.BCOO((coo.data * y, coo.indices),
                                          shape=coo.shape))
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        yd = _as_coo(y).todense()
    else:
        yd = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    sampled = yd[tuple(coo.indices[:, i] for i in range(coo.indices.shape[1]))]
    return _wrap_like(x, jsparse.BCOO((coo.data * sampled, coo.indices),
                                      shape=coo.shape))


def divide(x, y):
    coo = _as_coo(x)
    if isinstance(y, (int, float)):
        return _wrap_like(x, jsparse.BCOO((coo.data / y, coo.indices),
                                          shape=coo.shape))
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        yd = _as_coo(y).todense()
    else:
        yd = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    sampled = yd[tuple(coo.indices[:, i] for i in range(coo.indices.shape[1]))]
    return _wrap_like(x, jsparse.BCOO((coo.data / sampled, coo.indices),
                                      shape=coo.shape))


# ------------------------------------------------------------------ matmul
def matmul(x, y):
    """sparse @ dense → dense (reference: paddle.sparse.matmul; phi
    kernels sparse/cpu/matmul_kernel). XLA lowers to gather+dot."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        lhs = _as_coo(x)
        rhs = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        n = lhs.ndim
        out = jsparse.bcoo_dot_general(
            lhs, rhs, dimension_numbers=(([n - 1], [0]), ([], [])))
        return Tensor._from_value(out)
    raise TypeError("sparse.matmul expects a sparse lhs")


def masked_matmul(x: Tensor, y: Tensor, mask):
    """dense @ dense sampled at mask's sparsity (reference:
    paddle.sparse.masked_matmul — SDDMM)."""
    coo = _as_coo(mask)
    xv = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    rows = coo.indices[:, 0]
    cols = coo.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], yv[:, cols].T)
    return SparseCooTensor(
        jsparse.BCOO((vals, coo.indices), shape=coo.shape))


# ------------------------------------------------------------- unary ops
def _unary(fn):
    def op(x):
        coo = _as_coo(x)
        return _wrap_like(x, jsparse.BCOO((fn(coo.data), coo.indices),
                                          shape=coo.shape))
    return op


relu = _unary(jax.nn.relu)
tanh = _unary(jnp.tanh)
sqrt = _unary(jnp.sqrt)
sin = _unary(jnp.sin)
abs = _unary(jnp.abs)
neg = _unary(jnp.negative)


def pow(x, factor):
    coo = _as_coo(x)
    return _wrap_like(x, jsparse.BCOO((coo.data ** factor, coo.indices),
                                      shape=coo.shape))


def cast(x, index_dtype=None, value_dtype=None):
    coo = _as_coo(x)
    data = coo.data if value_dtype is None else coo.data.astype(value_dtype)
    idx = coo.indices if index_dtype is None \
        else coo.indices.astype(index_dtype)
    return _wrap_like(x, jsparse.BCOO((data, idx), shape=coo.shape))


def coalesce(x: SparseCooTensor) -> SparseCooTensor:
    return x.coalesce()


def transpose(x, perm: Sequence[int]):
    coo = _as_coo(x)
    return _wrap_like(
        x, jsparse.bcoo_transpose(coo, permutation=tuple(perm)))


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


# ---------------------------------------------------------------------------
# Dense→sparse conversion methods on Tensor (the reference patches these
# onto its Tensor too: python/paddle/sparse binds to_sparse_coo/to_sparse_csr)
# ---------------------------------------------------------------------------
def _to_sparse_coo(self: Tensor, sparse_dim: Optional[int] = None):
    mat = jsparse.BCOO.fromdense(self._value)
    return SparseCooTensor(mat)


def _to_sparse_csr(self: Tensor):
    return SparseCsrTensor(jsparse.BCSR.fromdense(self._value))


Tensor.to_sparse_coo = _to_sparse_coo
Tensor.to_sparse_csr = _to_sparse_csr

# remaining zero-preserving unary surface (reference: sparse/unary.py)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
square = _unary(jnp.square)
log1p = _unary(jnp.log1p)
expm1 = _unary(jnp.expm1)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)


def mv(x, vec, name=None):
    """Sparse matrix x dense vector (reference: sparse/matmul.py mv)."""
    from ..core.tensor import Tensor
    from ..ops._helpers import ensure_tensor

    coo = _as_coo(x)
    v = ensure_tensor(vec)._value
    return Tensor._from_value((coo @ v))


def mask_as(x, mask, name=None):
    """Dense x filtered by a sparse mask's pattern
    (reference: sparse/unary.py mask_as)."""
    from ..ops._helpers import ensure_tensor

    coo = _as_coo(mask)
    xv = ensure_tensor(x)._value
    rows = tuple(coo.indices[:, i] for i in range(coo.indices.shape[1]))
    vals = xv[rows]
    return _wrap_like(mask, jsparse.BCOO((vals, coo.indices),
                                         shape=coo.shape))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Reference: sparse pca_lowrank over a sparse input — densify (the
    randomized iteration is dense anyway) and run the dense kernel."""
    from ..core.tensor import Tensor
    from ..ops.extras import pca_lowrank as _dense

    coo = _as_coo(x)
    return _dense(Tensor._from_value(coo.todense()), q=q, center=center,
                  niter=niter)


__all__ += [
    "tan", "asin", "atan", "sinh", "asinh", "atanh", "square", "log1p",
    "expm1", "deg2rad", "rad2deg", "mv", "mask_as", "pca_lowrank",
]


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) with sparse x (reference: sparse/matmul.py
    addmm)."""
    from ..core.tensor import Tensor
    from ..ops._helpers import ensure_tensor

    coo = _as_coo(x)
    yv = ensure_tensor(y)._value
    iv = ensure_tensor(input)._value
    return Tensor._from_value(beta * iv + alpha * (coo @ yv))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """Sparse sum (reference: sparse/unary.py sum) — returns dense."""
    from ..core.tensor import Tensor

    coo = _as_coo(x)
    dense = coo.todense()
    out = dense.sum() if axis is None else dense.sum(
        axis=axis, keepdims=keepdim)
    if dtype is not None:
        from ..core.dtype import convert_dtype

        out = out.astype(convert_dtype(dtype))
    return Tensor._from_value(out)


def reshape(x, shape, name=None):
    """Sparse reshape (reference: sparse/unary.py reshape)."""
    coo = _as_coo(x)
    dense = coo.todense().reshape(tuple(shape))
    return _wrap_like(x, jsparse.BCOO.fromdense(dense))


def isnan(x, name=None):
    return _unary(jnp.isnan)(x)


def slice(x, axes, starts, ends, name=None):
    """Sparse slice (reference: sparse/unary.py slice) — dense roundtrip."""
    coo = _as_coo(x)
    dense = coo.todense()
    import builtins

    sl = [builtins.slice(None)] * dense.ndim
    for ax, st, en in zip(axes, starts, ends):
        sl[ax] = builtins.slice(int(st), int(en))
    return _wrap_like(x, jsparse.BCOO.fromdense(dense[tuple(sl)]))


__all__ += ["addmm", "sum", "reshape", "isnan", "slice"]


from . import nn  # noqa: E402,F401
