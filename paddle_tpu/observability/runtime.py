"""Step-level training telemetry: wall time, throughput, MFU, HBM gauges.

The runtime counterpart of the compile-time metrics PR 2 shipped: where
dispatch/Executor/PassManager telemetry answers "where do recompiles
go?", this module answers "how fast is the training loop actually
running and how close to the roofline is it?" per step:

- ``step_region()`` / :class:`StepTimer` bracket one optimizer step and
  record ``train.step_seconds``, ``train.items_per_second`` and — when a
  per-step FLOP count is known — ``train.mfu`` (model FLOPs utilization
  against the chip's peak), emitting a ``train.step`` event that rides
  both the export ring and the flight recorder;
- :func:`sample_device_memory` reads ``device/memory.py`` stats into
  ``device.hbm_bytes_in_use`` / ``device.hbm_watermark_bytes`` gauges,
  with a live-array scan as the safe CPU fallback (CPU PJRT reports no
  allocator stats);
- :func:`measure_step_flops` computes the FLOP count from XLA's compiled
  cost analysis (``utils/flops.xla_flops`` — the post-fusion count the
  hardware executes), so MFU is cost-analysis-driven, not hand-counted.

Everything is behind the ``observability.state.on`` gate: a disabled
process pays two attribute loads per region and allocates nothing.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

from . import _gate, flight
from .events import emit
from .metrics import registry

PEAK_FLOPS_ENV = "PADDLE_TPU_PEAK_FLOPS"


class FakeClock:
    """Deterministic injectable clock for timing-sensitive tests.

    Serves BOTH clock protocols in the codebase: calling it (or
    ``.time()``) returns the current fake time — the callable protocol
    ``ServeEngine(clock=...)``, ``StepTimer(clock=...)`` and
    ``step_region(clock=...)`` take — and ``.sleep(dt)`` advances it,
    the object protocol ``serve.load.run_load(clock=...)`` takes.

    ``tick`` advances the clock by a fixed amount on every read, so a
    code path that reads the clock twice always measures a positive,
    exactly reproducible duration — the deflaking device for the
    load-generator and step-telemetry tests that used to assert on real
    ``time.sleep`` under CI load."""

    __slots__ = ("now", "tick")

    def __init__(self, start: float = 0.0, tick: float = 0.0):
        self.now = float(start)
        self.tick = float(tick)

    def time(self) -> float:
        t = self.now
        self.now += self.tick
        return t

    __call__ = time

    def sleep(self, dt: float):
        self.now += max(float(dt), 0.0)

    def advance(self, dt: float):
        self.now += float(dt)

M_STEP_SECONDS = registry.histogram(
    "train.step_seconds",
    "wall seconds per training step bracketed by obs.step_region()")
M_STEPS = registry.counter(
    "train.steps", "training steps completed, by region name")
M_ITEMS_PER_SEC = registry.gauge(
    "train.items_per_second",
    "throughput of the last step (tokens- or samples-per-second — the "
    "unit label says which), by region name")
M_MFU = registry.gauge(
    "train.mfu",
    "model FLOPs utilization of the last step (0-1): step FLOPs / wall "
    "seconds / peak chip FLOPs, by region name")
M_HBM_IN_USE = registry.gauge(
    "device.hbm_bytes_in_use",
    "device memory currently allocated, by device index (CPU fallback: "
    "sum of live jax array bytes)")
M_HBM_WATERMARK = registry.gauge(
    "device.hbm_watermark_bytes",
    "high-water mark of device memory, by device index (allocator "
    "peak_bytes_in_use where the platform reports it, else the max "
    "in-use value this process has sampled)")
M_HBM_LIMIT = registry.gauge(
    "device.hbm_bytes_limit",
    "device memory capacity, by device index (0 when the platform "
    "reports no limit)")

# host-side watermark per device label, for platforms whose allocator
# reports no peak (CPU PJRT): max bytes_in_use ever sampled here.
_seen_watermark: Dict[str, int] = {}


def _clear_watermarks():
    _seen_watermark.clear()


def default_peak_flops() -> float:
    """Peak chip FLOPs/s for MFU: ``PADDLE_TPU_PEAK_FLOPS`` env override,
    else the v5e bf16 peak on TPU and a 1 TF/s nominal figure on CPU
    (same convention as bench.py)."""
    env = os.environ.get(PEAK_FLOPS_ENV)
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        import jax

        platforms = {d.platform for d in jax.devices()}
        if "tpu" in platforms:
            return 197e12
        if platforms & {"gpu", "cuda", "rocm"}:
            return 312e12  # A100 bf16 — the ROADMAP's comparison chip
    except Exception:
        pass
    return 1e12


def measure_step_flops(fn, *args, **kwargs) -> int:
    """FLOPs of one ``fn(*args)`` step from XLA's compiled cost analysis
    (post-fusion, what the hardware executes). Returns 0 when the
    backend reports no cost analysis rather than raising."""
    from ..utils.flops import xla_flops

    try:
        return int(xla_flops(fn, *args, **kwargs))
    except Exception:
        return 0


def sample_device_memory(device_id: Optional[int] = None) -> Dict[str, int]:
    """Read device memory stats into the ``device.*`` gauges.

    Uses the PJRT allocator stats where the platform reports them
    (``device/memory.py:memory_stats``); on CPU — whose PJRT client
    reports None — falls back to summing live jax array bytes, so tests
    and CPU rigs still see a meaningful curve. Never raises; returns
    ``{"bytes_in_use", "watermark_bytes", "bytes_limit"}``."""
    from ..device import memory as dev_mem

    stats = dev_mem.memory_stats(device_id)
    in_use = int(stats.get("bytes_in_use", 0))
    peak = int(stats.get("peak_bytes_in_use", 0))
    limit = int(stats.get("bytes_limit", 0))
    if "bytes_in_use" not in stats:
        # platform reports no allocator stats (CPU PJRT): process-wide
        # live-array scan — a host-level approximation, so on a forced
        # multi-device CPU mesh every device label sees the same total.
        # A real allocator's genuine 0 reading is left untouched.
        in_use = dev_mem.live_array_bytes()
    label = str(device_id or 0)
    watermark = max(peak, in_use, _seen_watermark.get(label, 0))
    _seen_watermark[label] = watermark
    if _gate.state.on:
        M_HBM_IN_USE.set(in_use, device=label)
        M_HBM_WATERMARK.set(watermark, device=label)
        M_HBM_LIMIT.set(limit, device=label)
    return {"bytes_in_use": in_use, "watermark_bytes": watermark,
            "bytes_limit": limit}


class _StepRegion:
    """One bracketed step: a profiler host span + the train.* metrics.

    On a clean exit it records step wall time, throughput and MFU; on an
    exception it emits a ``train.step_failed`` event and writes the
    flight-recorder dump (reason ``step_exception``) before re-raising.
    """

    __slots__ = ("name", "step", "items", "unit", "flops", "peak_flops",
                 "sample_memory", "fields", "_rec", "_t0", "seconds",
                 "mfu", "items_per_second", "_clock")

    def __init__(self, name: str, step: Optional[int], items: Optional[int],
                 unit: str, flops: Optional[int], peak_flops: Optional[float],
                 sample_memory: bool, fields: Dict[str, Any],
                 clock=None):
        self.name = name
        self.step = step
        self.items = items
        self.unit = unit
        self.flops = flops
        self.peak_flops = peak_flops
        self.sample_memory = sample_memory
        self.fields = fields
        self._rec = None
        self.seconds = 0.0
        self.mfu: Optional[float] = None
        self.items_per_second: Optional[float] = None
        self._clock = clock if clock is not None else time.perf_counter

    def __enter__(self):
        from ..profiler.utils import RecordEvent

        self._rec = RecordEvent(f"{self.name}.step")
        self._rec.begin()
        self._t0 = self._clock()
        return self

    def abandon(self):
        """Close the profiler span without recording any metrics — for a
        region superseded before its ``end()`` ran (e.g. a fit loop that
        died between batch-begin and batch-end), so the host-tracer span
        stack stays balanced."""
        if self._rec is not None:
            self._rec.end()
            self._rec = None

    def __exit__(self, exc_type, exc, tb):
        self.seconds = max(self._clock() - self._t0, 1e-12)
        if self._rec is not None:
            self._rec.end()
            self._rec = None
        if not _gate.state.on:
            return False
        if exc is not None:
            emit("train.step_failed", name=self.name, step=self.step,
                 seconds=self.seconds, error=f"{exc_type.__name__}: {exc}")
            flight.recorder.dump("step_exception", exc)
            return False
        M_STEP_SECONDS.observe(self.seconds, name=self.name)
        M_STEPS.inc(name=self.name)
        ev: Dict[str, Any] = {"name": self.name, "seconds": self.seconds}
        if self.step is not None:
            ev["step"] = self.step
        if self.items:
            self.items_per_second = self.items / self.seconds
            M_ITEMS_PER_SEC.set(self.items_per_second, name=self.name,
                                unit=self.unit)
            ev["items"] = self.items
            ev[f"{self.unit}_per_second"] = round(self.items_per_second, 2)
        if self.flops:
            peak = self.peak_flops or default_peak_flops()
            self.mfu = self.flops / self.seconds / peak
            M_MFU.set(round(self.mfu, 5), name=self.name)
            ev["mfu"] = round(self.mfu, 5)
        ev.update(self.fields)
        emit("train.step", **ev)
        if self.sample_memory:
            sample_device_memory()
        from . import health
        health.maybe_on_step(self._clock())
        return False


class _DisabledRegion:
    """Shared no-op returned by :func:`step_region` while observability is
    off — the disabled hot path allocates nothing and opens no span.
    Mirrors the _StepRegion surface callers may poke at."""

    seconds = 0.0
    mfu = None
    items_per_second = None
    items = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def abandon(self):
        pass


_DISABLED_REGION = _DisabledRegion()


def step_region(name: str = "train", *, step: Optional[int] = None,
                items: Optional[int] = None, unit: str = "items",
                flops: Optional[int] = None,
                peak_flops: Optional[float] = None,
                sample_memory: bool = False, clock=None, **fields):
    """Context manager bracketing ONE training step.

    ``items`` is the tokens/samples consumed this step (drives
    ``train.items_per_second``); ``flops`` the per-step FLOP count
    (drives ``train.mfu`` against ``peak_flops``, defaulting to the
    chip's peak). Extra keyword fields ride the ``train.step`` event.

    Usage::

        for step, batch in enumerate(loader):
            with obs.step_region("train", step=step, items=bs * seq,
                                 unit="tokens", flops=step_flops):
                loss = train_step(batch)
    """
    if not _gate.state.on:
        return _DISABLED_REGION
    return _StepRegion(name, step, items, unit, flops, peak_flops,
                       sample_memory, fields, clock=clock)


class StepTimer:
    """Loop-lifetime helper over :func:`step_region`: counts steps,
    remembers the per-step FLOP/item constants, samples device memory
    every ``sample_memory_every`` steps, and supports the split
    ``begin()``/``end()`` form callback-style loops need (hapi's
    ``MetricsCallback`` drives it from on_train_batch_begin/end).
    """

    def __init__(self, name: str = "train", *,
                 flops_per_step: Optional[int] = None,
                 items_per_step: Optional[int] = None, unit: str = "items",
                 peak_flops: Optional[float] = None,
                 sample_memory_every: int = 16, clock=None):
        self.name = name
        self.flops_per_step = flops_per_step
        self.items_per_step = items_per_step
        self.unit = unit
        self.peak_flops = peak_flops
        self.clock = clock        # injectable (FakeClock) for determinism
        self.sample_memory_every = max(0, int(sample_memory_every))
        self.count = 0
        self.last: Optional[_StepRegion] = None
        self._open: Optional[_StepRegion] = None

    def measure_flops(self, fn, *args, **kwargs) -> int:
        """Fix ``flops_per_step`` from XLA cost analysis of ``fn``."""
        self.flops_per_step = measure_step_flops(fn, *args, **kwargs)
        return self.flops_per_step

    def region(self, items: Optional[int] = None, **fields) -> _StepRegion:
        sample = (self.sample_memory_every > 0
                  and self.count % self.sample_memory_every == 0)
        r = step_region(
            self.name, step=self.count,
            items=self.items_per_step if items is None else items,
            unit=self.unit, flops=self.flops_per_step,
            peak_flops=self.peak_flops, sample_memory=sample,
            clock=self.clock, **fields)
        self.count += 1
        self.last = r
        return r

    # -- split form for callback-driven loops ------------------------------
    def begin(self, **fields):
        if self._open is not None:
            self.abandon()
        self._open = self.region(**fields)
        self._open.__enter__()

    def abandon(self):
        """Discard an open region without recording it (balances the
        profiler span stack when end() will never arrive)."""
        r, self._open = self._open, None
        if r is not None:
            r.abandon()

    def end(self, items: Optional[int] = None, failed: bool = False):
        r, self._open = self._open, None
        if r is None:
            return
        if items is not None:
            r.items = items
        if failed:
            # synthesize an exception-shaped exit without a live traceback
            r.__exit__(RuntimeError, RuntimeError("step failed"), None)
        else:
            r.__exit__(None, None, None)
