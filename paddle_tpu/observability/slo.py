"""Declarative SLO guardrails for the serving plane.

Production serving is run against objectives — "p99 TTFT under 200 ms",
"never below 500 tokens/sec", "preemption storms are an incident" — not
against raw histograms. This module evaluates declarative rolling-window
rules at engine step boundaries and turns a breach into every artifact
an operator needs at once:

- the ``trace.slo_breaches{rule}`` counter (one increment per breach
  *episode*: a rule latches while out of bounds and can fire again only
  after recovering);
- a ``trace.slo_breach`` structured event on the export + flight rings;
- a PTL401 diagnostic accumulated on :attr:`SloMonitor.report`;
- a flight-recorder dump with reason ``slo_breach`` — carrying the tail
  exemplars from ``observability/tracing.py``, so the post-mortem file
  already contains the span trees of the worst requests that defined
  the breached percentile.

Rule kinds (all evaluated over a trailing ``window_seconds``):

====================  ====================================================
``ttft_p99``          p99 of observed TTFTs (seconds); breach when above
                      ``threshold`` (``bound="max"``)
``tokens_per_sec``    generated tokens / window span; breach when below
                      ``threshold`` (``bound="min"``)
``pool_exhaustion_rate``  preemptions per engine step; breach when above
                      ``threshold``
====================  ====================================================

Configuration: pass ``SloRule`` objects (or plain dicts) to
``ServeEngine(slo=[...])``, or set ``PADDLE_TPU_SLO`` to inline JSON
(``[{"name": "ttft", "kind": "ttft_p99", "threshold": 0.2}]``) or to the
path of a JSON rules file.
"""
from __future__ import annotations

import collections
import json
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from . import flight
from .events import emit
from .metrics import registry

__all__ = ["SloRule", "SloMonitor", "parse_rules", "rules_from_env",
           "SLO_ENV", "SLO_CODES", "RULE_KINDS"]

SLO_ENV = "PADDLE_TPU_SLO"

#: diagnostic codes this module emits (documented in
#: static/analysis/diagnostics.py:CODES; audited by tools/lint_registry.py)
SLO_CODES = ("PTL401",)

RULE_KINDS = ("ttft_p99", "tokens_per_sec", "pool_exhaustion_rate")

M_SLO_BREACHES = registry.counter(
    "trace.slo_breaches",
    "SLO rule breach episodes (a rule fires once per excursion out of "
    "bounds, re-arming on recovery), by rule")


@dataclass
class SloRule:
    """One declarative objective over a rolling window."""

    name: str                      # the rule= label breaches carry
    kind: str                      # one of RULE_KINDS
    threshold: float
    bound: str = ""                # "max" | "min"; default per kind
    window_seconds: float = 5.0
    min_samples: int = 3           # ttft_p99 only: don't judge 2 points

    def __post_init__(self):
        if self.kind not in RULE_KINDS:
            raise ValueError(
                f"SLO rule {self.name!r}: unknown kind {self.kind!r} "
                f"(expected one of {RULE_KINDS})")
        if not self.bound:
            self.bound = "min" if self.kind == "tokens_per_sec" else "max"
        if self.bound not in ("min", "max"):
            raise ValueError(
                f"SLO rule {self.name!r}: bound must be 'min' or 'max', "
                f"got {self.bound!r}")

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": self.kind,
                "threshold": self.threshold, "bound": self.bound,
                "window_seconds": self.window_seconds,
                "min_samples": self.min_samples}


def parse_rules(spec) -> List[SloRule]:
    """Rules from a list of ``SloRule``/dicts, an inline JSON string, or
    a path to a JSON file holding the list."""
    if spec is None:
        return []
    if isinstance(spec, str):
        s = spec.strip()
        if not s:
            return []
        if not s.startswith("["):
            with open(s) as f:
                s = f.read()
        spec = json.loads(s)
    if isinstance(spec, dict):
        spec = [spec]
    rules = []
    for r in spec:
        rules.append(r if isinstance(r, SloRule) else SloRule(**r))
    return rules


def rules_from_env() -> List[SloRule]:
    return parse_rules(os.environ.get(SLO_ENV))


class SloMonitor:
    """Evaluates the rules at every engine step boundary.

    The engine feeds it per-step deltas (``on_step``) and raw TTFT
    observations (``observe_ttft``); everything else — windowing,
    latching, the breach artifacts — happens here. ``exemplars`` is the
    tracer's :class:`~.tracing.TailExemplars` (or None): its current
    worst span trees ride the ``slo_breach`` flight dump."""

    def __init__(self, rules, *, engine: str = "default", clock=None,
                 exemplars=None):
        import time as _time

        self.rules = parse_rules(rules)
        self.engine = str(engine)
        self._clock = clock if clock is not None else _time.perf_counter
        self.exemplars = exemplars
        self._ttfts: collections.deque = collections.deque()    # (t, v)
        self._tokens: collections.deque = collections.deque()   # (t, n)
        self._steps: collections.deque = collections.deque()    # (t, pre)
        self._latched: set = set()
        self.breaches: List[Dict[str, Any]] = []
        from ..static.analysis.diagnostics import DiagnosticReport

        self.report = DiagnosticReport()

    # -- feeding -----------------------------------------------------------
    def observe_ttft(self, seconds: float, now: Optional[float] = None):
        self._ttfts.append(
            (self._clock() if now is None else now, float(seconds)))

    def on_step(self, *, tokens: int = 0, preemptions: int = 0,
                now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Record one engine step's deltas and evaluate every rule.
        Returns the breaches that FIRED this step (newly latched)."""
        now = self._clock() if now is None else now
        self._steps.append((now, int(preemptions)))
        if tokens:
            self._tokens.append((now, int(tokens)))
        self._prune(now)
        return self._evaluate(now)

    def _prune(self, now: float):
        horizon = max(r.window_seconds for r in self.rules) \
            if self.rules else 5.0
        for ring in (self._ttfts, self._tokens, self._steps):
            while ring and ring[0][0] < now - horizon:
                ring.popleft()

    # -- evaluation --------------------------------------------------------
    def current_value(self, rule: SloRule,
                      now: Optional[float] = None) -> Optional[float]:
        """The rule's windowed value right now (None = not enough data
        to judge)."""
        now = self._clock() if now is None else now
        lo = now - rule.window_seconds
        if rule.kind == "ttft_p99":
            vals = sorted(v for t, v in self._ttfts if t >= lo)
            if len(vals) < max(1, rule.min_samples):
                return None
            idx = (len(vals) - 1) * 0.99
            i, frac = int(idx), idx - int(idx)
            hi = min(i + 1, len(vals) - 1)
            return vals[i] * (1 - frac) + vals[hi] * frac
        if rule.kind == "tokens_per_sec":
            pts = [(t, n) for t, n in self._tokens if t >= lo]
            if not pts:
                return None
            span = max(now - max(pts[0][0], lo), 1e-9)
            return sum(n for _t, n in pts) / span
        if rule.kind == "pool_exhaustion_rate":
            steps = [(t, p) for t, p in self._steps if t >= lo]
            if not steps:
                return None
            return sum(p for _t, p in steps) / len(steps)
        return None

    def _evaluate(self, now: float) -> List[Dict[str, Any]]:
        from ..static.analysis.diagnostics import Severity

        fired = []
        for rule in self.rules:
            val = self.current_value(rule, now)
            if val is None:
                continue
            breached = (val > rule.threshold if rule.bound == "max"
                        else val < rule.threshold)
            if not breached:
                self._latched.discard(rule.name)
                continue
            if rule.name in self._latched:
                continue               # still the same excursion
            self._latched.add(rule.name)
            M_SLO_BREACHES.inc(engine=self.engine, rule=rule.name)
            # key is "rule_kind", not "kind": the rec doubles as the
            # **fields of emit(), whose first parameter is the EVENT kind
            rec = {"rule": rule.name, "rule_kind": rule.kind,
                   "value": round(float(val), 6),
                   "threshold": rule.threshold, "bound": rule.bound,
                   "engine": self.engine, "at": round(now, 6)}
            self.breaches.append(rec)
            fired.append(rec)
            emit("trace.slo_breach", **rec)
            self.report.add(
                "PTL401", Severity.WARNING,
                f"SLO {rule.name!r} breached: {rule.kind} = {val:.6g} "
                f"{'>' if rule.bound == 'max' else '<'} "
                f"threshold {rule.threshold:g} "
                f"(window {rule.window_seconds:g}s, engine "
                f"{self.engine})",
                hint="the slo_breach flight dump carries the tail "
                     "exemplars — the per-phase breakdown of the worst "
                     "requests names the culprit phase",
                suggestion=rec)
            context = dict(rec)
            if self.exemplars is not None:
                context["exemplars"] = self.exemplars.to_dict()
            flight.recorder.dump(flight.REASON_SLO_BREACH,
                                 context=context)
        return fired
