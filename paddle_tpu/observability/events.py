"""Structured span events riding the profiler's host-tracer timeline.

Two complementary records per interesting runtime moment:

- a structured :class:`Event` (kind + JSON-serializable fields + unix
  timestamp) appended to a bounded ring buffer, exported by
  ``observability.dump()``;
- a ``profiler.RecordEvent`` host span, so the same moment lands in the
  Chrome-trace timeline (and, under an active device capture, as a
  ``jax.profiler.TraceAnnotation`` next to the XLA xplane lanes) —
  one timeline for host spans, device ops and observability events.
"""
from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional

from . import _gate, flight
from .metrics import Histogram

#: ring-buffer capacity; read once from core.flags at first use so the
#: flag can be set before any event is emitted.
_MAX_EVENTS_FLAG = "observability_max_events"

_events: Optional[collections.deque] = None


def _buffer() -> collections.deque:
    global _events
    if _events is None:
        from ..core import flags

        try:
            maxlen = int(flags.get_flag(_MAX_EVENTS_FLAG))
        except KeyError:
            maxlen = 4096
        _events = collections.deque(maxlen=max(1, maxlen))
    return _events


class Event:
    __slots__ = ("ts", "kind", "fields")

    def __init__(self, kind: str, fields: Dict[str, Any]):
        self.ts = time.time()
        self.kind = kind
        self.fields = fields

    def to_dict(self) -> Dict[str, Any]:
        return {"ts": self.ts, "kind": self.kind, **self.fields}


def emit(kind: str, **fields):
    """Record a structured event (no-op while observability is off).

    The event lands in two rings: the large export buffer read by
    ``observability.dump()`` and the smaller flight-recorder ring that
    survives into crash dumps (see ``observability.flight``)."""
    if not _gate.state.on:
        return
    ev = Event(kind, fields)
    _buffer().append(ev)
    flight.recorder.record(kind, fields, ts=ev.ts)


def events(kind: Optional[str] = None) -> List[Event]:
    evs = list(_buffer())
    if kind is not None:
        evs = [e for e in evs if e.kind == kind]
    return evs


def clear():
    _buffer().clear()


def ring_len() -> int:
    """Events currently buffered (0 when the ring was never created) —
    probed by observability/timeseries.py as a host-side leak series."""
    return len(_events) if _events is not None else 0


class span:
    """Context manager bracketing a named runtime moment.

    Always opens a ``profiler.RecordEvent`` (so the moment shows up in
    any active host/device trace); when observability is on it also
    feeds ``histogram`` with the elapsed seconds and emits an ``event``
    record carrying ``fields`` plus the measured duration.
    """

    __slots__ = ("name", "_hist", "_hist_labels", "_event", "_fields",
                 "_rec", "_t0", "seconds")

    def __init__(self, name: str, *, histogram: Optional[Histogram] = None,
                 hist_labels: Optional[Dict[str, Any]] = None,
                 event: Optional[str] = None, **fields):
        self.name = name
        self._hist = histogram
        self._hist_labels = hist_labels or {}
        self._event = event
        self._fields = fields
        self._rec = None
        self.seconds = 0.0

    def __enter__(self):
        from ..profiler.utils import RecordEvent

        self._rec = RecordEvent(self.name)
        self._rec.begin()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._t0
        if self._rec is not None:
            self._rec.end()
            self._rec = None
        if _gate.state.on:
            if self._hist is not None:
                self._hist.observe(self.seconds, **self._hist_labels)
            if self._event is not None:
                emit(self._event, seconds=self.seconds, **self._fields)
        return False
