"""paddle_tpu.observability — runtime metrics + structured span events.

The runtime counterpart of the PR-1 static diagnostics layer: where
``static.analysis`` tells you what is *wrong* with a program,
observability tells you where *time and recompiles go* at runtime. Three
hot layers are instrumented with it out of the box:

- ``core/dispatch.py`` — per-primitive call counts (eager vs traced vs
  capture), ``_jitted_forward`` executable-cache hits/misses, and
  retrace causes (new static-args vs new input avals);
- ``static/program.py`` Executor — compile events carrying the program
  fingerprint, feed signature and compile wall time, replay counts,
  cache invalidations and recompiles saved by fingerprint keying;
- ``distributed/passes`` PassManager — per-pass wall time, op-count
  delta, verifier runs and diagnostic counts.

Usage::

    import paddle_tpu.observability as obs
    obs.enable()                  # or FLAGS_observability=1 in the env
    ...run workload...
    print(obs.summary())          # human table
    obs.dump("metrics.json")      # JSON; render with tools/metrics_report.py

Gating: recording at the instrumentation sites is OFF by default and
costs two attribute loads per dispatch when disabled. It turns on via
``enable()``, the ``FLAGS_observability`` env/flag (core/flags.py), or
automatically when ``PADDLE_TPU_METRICS_DUMP=<path>`` is set — that env
var also registers an atexit hook writing the dump to ``<path>``.
Metric objects themselves always record when called directly; the gate
belongs to the hot-path instrumentation, not the registry.

Spans reuse ``profiler.RecordEvent``/host-tracer machinery, so compile
and pass events land in the same Chrome-trace timeline as user spans
and XLA device ops.

Claiming metric names: every name is ``subsystem.noun_verb``; claim your
subsystem prefix in ``observability.metrics.CLAIMED_SUBSYSTEMS`` (the
``PTLxxx``-code convention applied to metrics). ``tools/lint_registry.py``
audits the registry once per test session.
"""
from __future__ import annotations

import atexit
import os

from ._gate import state
from .metrics import (CLAIMED_SUBSYSTEMS, Counter, Gauge, Histogram,
                      MetricsRegistry, NAME_RE, registry)
from .events import Event, emit, events, span
from .report import (dump, dump_dict, render_flight, render_health,
                     render_report, render_trend_table, sparkline,
                     summary)
from . import flight
from .flight import FlightRecorder
from . import fleet
from .fleet import FleetAggregator, FleetReporter
from .runtime import (FakeClock, StepTimer, default_peak_flops,
                      measure_step_flops, sample_device_memory,
                      step_region)
from . import slo
from .slo import SloMonitor, SloRule
from . import timeseries
from .timeseries import SeriesRecorder, merge_timeseries
from . import health
from .health import HealthMonitor, HealthRule
from . import tracing
from .tracing import (RequestTrace, ServeTracer, Span, TailExemplars,
                      check_tracing_overhead, validate_trace)
from . import chrome
from . import opprof
from .opprof import (OpCalibration, OpProfile, OpProfiler, OpSpan,
                     attribute_profile, calibrate_op_costs,
                     check_opprof_overhead, lint_op_profile,
                     load_op_calibration, render_op_profile,
                     resolve_op_calibration, save_op_calibration)

__all__ = [
    "state", "enabled", "enable", "disable", "reset",
    "registry", "counter", "gauge", "histogram",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "Event", "emit", "events", "span",
    "dump", "dump_dict", "render_report", "render_flight", "summary",
    "render_health", "render_trend_table", "sparkline",
    "CLAIMED_SUBSYSTEMS", "NAME_RE",
    "flight", "FlightRecorder", "fleet", "FleetAggregator",
    "FleetReporter", "StepTimer", "step_region", "FakeClock",
    "sample_device_memory", "measure_step_flops", "default_peak_flops",
    "slo", "SloMonitor", "SloRule",
    "timeseries", "SeriesRecorder", "merge_timeseries",
    "health", "HealthMonitor", "HealthRule",
    "tracing", "Span", "RequestTrace", "ServeTracer", "TailExemplars",
    "check_tracing_overhead", "validate_trace",
    "chrome", "opprof", "OpSpan", "OpProfile", "OpProfiler",
    "OpCalibration", "attribute_profile", "calibrate_op_costs",
    "save_op_calibration", "load_op_calibration",
    "resolve_op_calibration", "lint_op_profile", "check_opprof_overhead",
    "render_op_profile",
]

counter = registry.counter
gauge = registry.gauge
histogram = registry.histogram


def enabled() -> bool:
    return state.on


def enable():
    """Turn on metric/event recording at the instrumentation sites."""
    state.on = True
    # arm the crash-dump hook too (idempotent): it still no-ops at fire
    # time unless PADDLE_TPU_FLIGHT_DIR is set, but a process that
    # enables observability after import must not lose the headline
    # unhandled-exception dump
    flight.install_excepthook()


def disable():
    state.on = False


_reset_hooks = []


def add_reset_hook(fn):
    """Register a callable run by :func:`reset` — instrumented modules
    use it to clear private bookkeeping (e.g. dispatch's seen-key set)."""
    _reset_hooks.append(fn)


def reset():
    """Zero all metric series, drop buffered events (both rings), run
    reset hooks."""
    registry.reset()
    from .events import clear as _clear_events
    from .runtime import _clear_watermarks

    _clear_events()
    flight.recorder.clear()
    _clear_watermarks()
    health._reset_active()
    for fn in _reset_hooks:
        fn()


def _init_from_env():
    from ..core import flags

    try:
        if flags.get_flag("observability"):
            state.on = True
    except KeyError:
        pass
    if os.environ.get("PADDLE_TPU_METRICS_DUMP"):
        state.on = True
        atexit.register(dump)
    if os.environ.get(flight.FLIGHT_DIR_ENV):
        # a configured crash-dump dir implies recording (same convention
        # as PADDLE_TPU_METRICS_DUMP) and arms the excepthook
        state.on = True
        flight.install_excepthook()
    if health.monitor_from_env() is not None:
        # PADDLE_TPU_HEALTH implies recording: detectors read the
        # registry, which only fills while the gate is on
        state.on = True


_init_from_env()
