"""Flight recorder: a crash-surviving trail of recent runtime events.

MegaScale-style in-job post-mortem: every structured observability event
(steps, compiles, collectives, watchdog scans) also lands in a small
bounded ring here, and on an unhandled exception or a comm-watchdog
timeout the ring — plus the exception, a metrics snapshot and device
memory gauges — is serialized as one JSON file under the directory named
by ``PADDLE_TPU_FLIGHT_DIR``. When a multi-chip job dies, the dump from
each host answers "what were the last N things this process did?"
without any profiler having been attached.

Gating follows the rest of the layer: nothing is recorded while
``observability.state.on`` is False, and setting ``PADDLE_TPU_FLIGHT_DIR``
turns the gate on at import (mirroring ``PADDLE_TPU_METRICS_DUMP``).
Dump files are named ``flight-<pid>-<seq>.json`` so concurrent hosts
sharing one directory never collide.
"""
from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from . import _gate

FLIGHT_DIR_ENV = "PADDLE_TPU_FLIGHT_DIR"
FLIGHT_DUMP_KIND = "flight_dump"
FLIGHT_VERSION = 1

#: well-known dump reasons. Free-form strings are accepted, but the
#: elastic-training reasons are named so the launcher, the renderer
#: (observability.report.render_flight / tools/metrics_report.py) and
#: tests agree on the spelling:
#: - ``peer_death``: a surviving worker detected a dead peer via the
#:   elastic heartbeat and is about to exit for the coordinated restart;
#: - ``rejoin``: a worker came back at a bumped generation and resumed
#:   from checkpoint (dumped right after the restore so the trail shows
#:   what recovery cost).
#: - ``straggler``: the fleet aggregator flagged this rank as a
#:   persistent straggler and requested a post-mortem via the store
#:   flag (observability/fleet.py FleetAggregator).
#: - ``slo_breach``: a serving SLO rule left its bound
#:   (observability/slo.py SloMonitor); the dump context carries the
#:   rule, the offending value and the tail-exemplar span trees.
#: - ``health_alert``: a continuous-health detector fired
#:   (observability/health.py HealthMonitor); the dump context carries
#:   the rule, its PTL6xx code, and the offending series window so the
#:   post-mortem shows the drift/leak trajectory, not just the trip.
REASON_PEER_DEATH = "peer_death"
REASON_REJOIN = "rejoin"
REASON_STRAGGLER = "straggler"
REASON_SLO_BREACH = "slo_breach"
REASON_HEALTH_ALERT = "health_alert"

#: ring capacity; read once from core.flags at first record so the flag
#: can be set before any event lands (same pattern as events._buffer).
_CAPACITY_FLAG = "observability_flight_events"


class FlightRecorder:
    """Bounded ring of recent structured events + the dump machinery."""

    def __init__(self):
        self._ring: Optional[collections.deque] = None
        self._dump_seq = 0
        # a watchdog thread and the main-thread excepthook can dump at
        # the same moment; serialize so neither post-mortem is lost
        self._dump_lock = threading.Lock()

    # -- recording --------------------------------------------------------
    def _buffer(self) -> collections.deque:
        if self._ring is None:
            from ..core import flags

            try:
                maxlen = int(flags.get_flag(_CAPACITY_FLAG))
            except KeyError:
                maxlen = 512
            self._ring = collections.deque(maxlen=max(1, maxlen))
        return self._ring

    def record(self, kind: str, fields: Dict[str, Any],
               ts: Optional[float] = None):
        """Append one event (no-op while observability is off)."""
        if not _gate.state.on:
            return
        self._buffer().append(
            {"ts": time.time() if ts is None else ts, "kind": kind,
             **fields})

    def snapshot(self) -> List[Dict[str, Any]]:
        return list(self._buffer())

    def clear(self):
        if self._ring is not None:
            self._ring.clear()

    # -- dumping ----------------------------------------------------------
    def dump_dir(self) -> Optional[str]:
        return os.environ.get(FLIGHT_DIR_ENV) or None

    def dump_dict(self, reason: str, exc: Optional[BaseException] = None,
                  context: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
        from .metrics import registry

        d: Dict[str, Any] = {
            "kind": FLIGHT_DUMP_KIND,
            "version": FLIGHT_VERSION,
            "reason": reason,
            "generated_unix": time.time(),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "events": self.snapshot(),
            "metrics": registry.to_dict(),
        }
        if context:
            # who/where fields the dumping site knows but the recorder
            # doesn't (worker rank, elastic generation, dead peer, step)
            d["context"] = dict(context)
        if exc is not None:
            d["exception"] = {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__),
            }
        try:
            from .runtime import sample_device_memory

            d["device_memory"] = sample_device_memory()
        except Exception:
            pass
        return d

    def dump(self, reason: str, exc: Optional[BaseException] = None,
             path: Optional[str] = None,
             context: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write the post-mortem JSON; returns the path, or None when no
        target directory is configured. Must never raise — it runs from
        excepthooks and watchdog threads."""
        try:
            with self._dump_lock:
                if path is None:
                    d = self.dump_dir()
                    if not d:
                        return None
                    os.makedirs(d, exist_ok=True)
                    self._dump_seq += 1
                    path = os.path.join(
                        d, f"flight-{os.getpid()}-{self._dump_seq}.json")
                doc = self.dump_dict(reason, exc, context=context)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(doc, f, indent=1, default=str)
                os.replace(tmp, path)
                return path
        except Exception:
            return None


#: process-global recorder every instrumented site records into.
recorder = FlightRecorder()

_prev_excepthook = None


def _flight_excepthook(exc_type, exc, tb):
    if _gate.state.on and recorder.dump_dir():
        e = exc if isinstance(exc, BaseException) else exc_type(exc)
        path = recorder.dump("unhandled_exception", e)
        if path:
            print(f"paddle_tpu flight recorder: wrote {path}",
                  file=sys.stderr)
    if _prev_excepthook is not None:
        _prev_excepthook(exc_type, exc, tb)


def install_excepthook():
    """Chain a sys.excepthook that writes the flight dump on an unhandled
    exception (idempotent)."""
    global _prev_excepthook
    if sys.excepthook is _flight_excepthook:
        return
    _prev_excepthook = sys.excepthook
    sys.excepthook = _flight_excepthook
