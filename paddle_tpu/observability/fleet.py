"""Fleet telemetry plane: cross-rank metric aggregation over the elastic store.

PR 2/5 observability is strictly per-process: each rank owns its metrics
registry, event ring and flight recorder, and a multi-process incident
leaves N disconnected dumps. This module is the MegaScale-style fleet
view on top of them, anchored at the LAUNCHER (whose node-0 controller
already hosts the elastic rendezvous ``Store`` and outlives any worker):

- **shipping** — each worker's :class:`FleetReporter` periodically (and
  at exit) publishes a compact :func:`snapshot_dict` of its registry and
  recent events to ``fleet/<job>/snap/<rank>``, tagged with rank,
  generation and a clock-offset estimate from a store-ping handshake at
  rendezvous (:meth:`FleetReporter.handshake`). Shipping must never take
  down training: every store op is bounded-retry/except and failures
  only increment ``fleet.ship_failures``.
- **aggregation** — the launcher-side :class:`FleetAggregator` merges
  snapshots into one fleet view (:func:`merge_metrics`: counters summed
  across ranks, gauges kept per-rank under a ``rank`` label, histograms
  merged bucket-wise) exposed as ``fleet.*`` metrics and one JSON dump,
  plus a merged Chrome-trace timeline (:func:`write_merged_trace`) where
  each rank is a process lane with clock-aligned spans.
- **straggler detection** — the aggregator watches the per-rank
  ``train.step_seconds`` spread between polls; a rank whose recent mean
  exceeds ``straggler_ratio`` x the median of its peers for
  ``straggler_polls`` consecutive polls is flagged: a structured
  ``fleet.straggler`` event is recorded, ``fleet.stragglers_detected``
  increments, and a store flag (``fleet/<job>/flight_request/<rank>``)
  asks the offending worker to write a PR 5 flight dump (reason
  ``straggler``) — so the drill shows *who* was slow before the loss
  curve shows *that* something was.

  Caveat for tightly-coupled SPMD: a per-step collective equalizes wall
  step times across ranks (the straggler slows everyone), so the spread
  only attributes blame when per-rank *local* work dominates the
  bracketed region — structure ``obs.step_region()`` around host-side
  work (input pipeline, per-rank compute) for attribution, exactly the
  reason MegaScale times per-phase, not per-step.

``tools/metrics_report.py --fleet <dir>`` renders a directory of
per-rank metric dumps + flight dumps + the aggregated fleet dump as one
incident (:func:`load_incident_dir` / :func:`render_incident`).
"""
from __future__ import annotations

import glob
import json
import os
import re
import socket
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from . import flight
from .events import events as _list_events
from .metrics import registry
from .timeseries import merge_timeseries

__all__ = [
    "FLEET_ENV", "FLEET_INTERVAL_ENV", "FLEET_POLL_ENV",
    "SNAPSHOT_KIND", "FLEET_DUMP_KIND",
    "snapshot_dict", "merge_metrics", "merged_trace_events",
    "write_merged_trace", "merge_chrome_trace_files", "rank_dump_path",
    "FleetReporter", "FleetAggregator", "active_reporter", "maybe_ship",
    "load_incident_dir", "render_incident",
]

#: set to "1" in every worker by the launcher when --fleet_dir is given:
#: run_elastic builds a FleetReporter on the elastic store.
FLEET_ENV = "PADDLE_TPU_FLEET"
#: worker snapshot publish period, seconds (default 1.0).
FLEET_INTERVAL_ENV = "PADDLE_TPU_FLEET_INTERVAL"
#: aggregator poll period, seconds (default 0.5).
FLEET_POLL_ENV = "PADDLE_TPU_FLEET_POLL"
#: straggler threshold: recent mean > ratio x peer median (default 2.0).
STRAGGLER_RATIO_ENV = "PADDLE_TPU_FLEET_STRAGGLER_RATIO"
#: consecutive over-threshold polls before a straggler fires (default 2).
STRAGGLER_POLLS_ENV = "PADDLE_TPU_FLEET_STRAGGLER_POLLS"
#: clock handshake wait for the aggregator's pong, seconds (default 3).
HANDSHAKE_TIMEOUT_ENV = "PADDLE_TPU_FLEET_HANDSHAKE_TIMEOUT"

SNAPSHOT_KIND = "fleet_snapshot"
FLEET_DUMP_KIND = "fleet_dump"
FLEET_VERSION = 1

# -- the fleet. subsystem (claimed in metrics.CLAIMED_SUBSYSTEMS).
# Label discipline (audited by tools/lint_registry.py): per-rank series
# carry rank=, failure counters carry reason=, fleet-level gauges carry
# job= — a fleet gauge with NO labels cannot be attributed and is a lint
# error.
M_SHIP_FAILURES = registry.counter(
    "fleet.ship_failures",
    "worker snapshot publishes that failed after bounded retries, by "
    "exception class (shipping never raises into the train loop)")
M_SNAPSHOTS_SHIPPED = registry.counter(
    "fleet.snapshots_shipped",
    "telemetry snapshots this worker published to the fleet store, "
    "by rank")
M_CLOCK_OFFSET = registry.gauge(
    "fleet.clock_offset_seconds",
    "this rank's clock minus the aggregator's clock, estimated by the "
    "store-ping handshake at rendezvous, by rank")
M_RANKS_REPORTING = registry.gauge(
    "fleet.ranks_reporting",
    "ranks whose snapshot the aggregator has seen (< world size means a "
    "missing/late rank — the aggregator never blocks on one), by job")
M_SNAPSHOTS_RECEIVED = registry.counter(
    "fleet.snapshots_received",
    "fresh worker snapshots the aggregator ingested, by rank")
M_STEP_SKEW = registry.gauge(
    "fleet.step_skew_seconds",
    "spread of per-rank recent mean train.step_seconds (slowest minus "
    "fastest) over the last aggregator poll window, by job")
M_SLOWEST_RANK = registry.gauge(
    "fleet.slowest_rank",
    "rank with the largest recent mean step wall time, by job")
M_RANK_STEP_SECONDS = registry.gauge(
    "fleet.rank_step_seconds",
    "recent mean train.step_seconds of one rank (delta between the "
    "aggregator's last two polls of its snapshot), by rank")
M_STRAGGLERS = registry.counter(
    "fleet.stragglers_detected",
    "persistent stragglers the aggregator flagged (flight dump "
    "requested from the offending worker via the store flag), by rank")


def _key(job_id: str, *parts: str) -> str:
    return "/".join(("fleet", job_id) + parts)


def _as_float(v, default: float = 0.0) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


def rank_dump_path(path: str, rank: int) -> str:
    """Per-rank metrics dump path: ``metrics.json`` -> ``metrics.rank<N>.json``.

    The launcher rewrites ``PADDLE_TPU_METRICS_DUMP`` through this for
    every worker so N ranks sharing one inherited path never clobber
    each other's atexit dump."""
    root, ext = os.path.splitext(path)
    if ext.lower() == ".json":
        return f"{root}.rank{rank}{ext}"
    return f"{path}.rank{rank}"


#: filename shape the per-rank rewrite produces; --fleet mode globs it.
RANK_DUMP_RE = re.compile(r"\.rank(\d+)\.json$")


# -- snapshots -----------------------------------------------------------

def snapshot_dict(rank: int, world: int, *, generation: int = 0,
                  seq: int = 0, clock_offset: Optional[float] = None,
                  reg=None, events: Optional[List[Dict[str, Any]]] = None,
                  max_events: int = 256,
                  final: bool = False) -> Dict[str, Any]:
    """One worker's shippable telemetry snapshot: the (whole) metrics
    registry plus the last ``max_events`` structured events, tagged with
    identity and the handshake clock offset."""
    if reg is None:
        reg = registry
    if events is None:
        events = [e.to_dict() for e in _list_events()[-max_events:]]
    else:
        events = list(events)[-max_events:]
    from . import health
    mon = health.active_monitor()
    timeseries = mon.recorder.to_dict() if mon is not None else None
    return {
        "kind": SNAPSHOT_KIND,
        "version": FLEET_VERSION,
        "rank": int(rank),
        "world": int(world),
        "generation": int(generation),
        "seq": int(seq),
        "pid": os.getpid(),
        "host": socket.gethostname(),
        "published_unix": time.time(),
        "clock_offset": clock_offset,
        "final": bool(final),
        "metrics": reg.to_dict(),
        "events": events,
        "timeseries": timeseries,
    }


# -- cross-rank merge semantics ------------------------------------------

def _series_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _merge_series(out: Dict[str, Any], kind: str, series: List[Dict],
                  rank: Optional[int]) -> None:
    """Fold one metric's series list into the accumulator ``out``
    (``_series`` keyed by canonical labels). ``rank`` labels gauges;
    None means the series is already fleet-level (aggregator-own)."""
    for s in series:
        labels = dict(s.get("labels", {}))
        if kind == "gauge":
            if rank is not None:
                labels["rank"] = str(rank)
            out[_series_key(labels)] = {"labels": labels,
                                        "value": s.get("value")}
        elif kind == "counter":
            key = _series_key(labels)
            cur = out.get(key)
            if cur is None:
                out[key] = {"labels": labels, "value": s.get("value", 0)}
            else:
                cur["value"] = cur["value"] + s.get("value", 0)
        elif kind == "histogram":
            key = _series_key(labels)
            cur = out.get(key)
            cnt = s.get("count", 0)
            if cur is None:
                out[key] = {
                    "labels": labels, "count": cnt,
                    "sum": s.get("sum", 0.0),
                    "min": s.get("min", 0.0), "max": s.get("max", 0.0),
                    "bounds": list(s.get("bounds", [])),
                    "bucket_counts": list(s.get("bucket_counts", [])),
                }
                continue
            if cnt:
                cur["min"] = (min(cur["min"], s.get("min", 0.0))
                              if cur["count"] else s.get("min", 0.0))
                cur["max"] = max(cur["max"], s.get("max", 0.0))
            cur["count"] += cnt
            cur["sum"] += s.get("sum", 0.0)
            if cur["bounds"] == list(s.get("bounds", [])) \
                    and len(cur["bucket_counts"]) \
                    == len(s.get("bucket_counts", [])):
                cur["bucket_counts"] = [
                    a + b for a, b in zip(cur["bucket_counts"],
                                          s.get("bucket_counts", []))]
            else:
                # incompatible bucket layouts: keep count/sum/min/max,
                # drop the per-bucket detail rather than fabricate it
                cur["bounds"], cur["bucket_counts"] = [], []


def merge_metrics(snapshots: Dict[int, Dict[str, Any]],
                  own: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Merge per-rank metric dicts into one fleet view.

    ``snapshots`` maps rank -> any dict with a ``metrics`` mapping (a
    fleet snapshot or an ``obs.dump()`` document). Semantics: counters
    are SUMMED across ranks by identical label set, gauges are kept
    per-rank under an added ``rank`` label, histograms are merged
    (count/sum/min/max always; bucket counts element-wise when the
    bucket layouts agree). ``own`` (the aggregator's local registry
    dump) is folded in as fleet-level series without rank labeling.
    Returns the same shape ``registry.to_dict()`` produces, so
    ``report.render_report({"metrics": merged})`` renders it."""
    acc: Dict[str, Dict[str, Any]] = {}

    def fold(mets: Dict[str, Any], rank: Optional[int]):
        for name, m in mets.items():
            kind = m.get("kind")
            slot = acc.setdefault(name, {"kind": kind,
                                         "doc": m.get("doc", ""),
                                         "_series": {}})
            if slot["kind"] != kind:
                continue  # cross-rank kind conflict: first kind wins
            _merge_series(slot["_series"], kind, m.get("series", []), rank)

    for rank in sorted(snapshots):
        fold(snapshots[rank].get("metrics", {}), rank)
    if own:
        fold(own, None)

    merged: Dict[str, Any] = {}
    for name in sorted(acc):
        slot = acc[name]
        series = [slot["_series"][k] for k in sorted(slot["_series"])]
        if not series:
            continue
        merged[name] = {"kind": slot["kind"], "doc": slot["doc"],
                        "series": series}
    return merged


# -- merged Chrome-trace timeline ----------------------------------------

def merged_trace_events(snapshots: Dict[int, Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
    """Chrome-trace events over every rank's shipped event ring: one
    process lane per rank (pid = rank), spans for events that carry a
    ``seconds`` duration (``train.step``, compiles, passes), instants
    otherwise — timestamps shifted by each rank's handshake clock offset
    so lanes line up on the aggregator's clock."""
    traces: List[Dict[str, Any]] = []
    for rank in sorted(snapshots):
        snap = snapshots[rank]
        off = _as_float(snap.get("clock_offset"), 0.0)
        traces.append({"ph": "M", "name": "process_name", "pid": rank,
                       "tid": 0,
                       "args": {"name": f"rank {rank} "
                                        f"(pid {snap.get('pid', '?')} on "
                                        f"{snap.get('host', '?')})"}})
        traces.append({"ph": "M", "name": "process_sort_index",
                       "pid": rank, "tid": 0,
                       "args": {"sort_index": rank}})
        for e in snap.get("events", []):
            ts = _as_float(e.get("ts")) - off
            name = str(e.get("kind", "event"))
            args = {k: v for k, v in e.items() if k != "ts"}
            dur = e.get("seconds")
            if isinstance(dur, (int, float)) and dur > 0:
                # the event timestamp marks the END of the measured
                # region (span/step_region record on exit)
                traces.append({"name": name, "ph": "X", "cat": "fleet",
                               "ts": (ts - dur) * 1e6, "dur": dur * 1e6,
                               "pid": rank, "tid": 0, "args": args})
            else:
                traces.append({"name": name, "ph": "i", "s": "t",
                               "cat": "fleet", "ts": ts * 1e6,
                               "pid": rank, "tid": 0, "args": args})
    return traces


def _write_json_atomic(path: str, doc: Dict[str, Any]) -> str:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, default=str)
    os.replace(tmp, path)
    return path


def write_merged_trace(snapshots: Dict[int, Dict[str, Any]],
                       path: str) -> str:
    """Write the merged fleet timeline as one chrome-trace JSON."""
    return _write_json_atomic(
        path, {"traceEvents": merged_trace_events(snapshots),
               "displayTimeUnit": "ms"})


def merge_chrome_trace_files(paths_by_rank: Dict[int, str],
                             offsets: Optional[Dict[int, float]] = None,
                             path: Optional[str] = None) -> Dict[str, Any]:
    """Merge per-rank ``profiler.export_chrome_tracing`` files into one
    timeline: each rank's events move to pid = rank (a process lane) and
    shift by that rank's clock offset (seconds).

    Only meaningful when the input traces share a wall-clock timebase —
    the host tracer's ``perf_counter`` spans from different processes do
    NOT; the snapshot-based :func:`write_merged_trace` is the primary
    cross-rank timeline and this is the escape hatch for wall-clock
    trace sources."""
    offsets = offsets or {}
    merged: List[Dict[str, Any]] = []
    for rank in sorted(paths_by_rank):
        with open(paths_by_rank[rank]) as f:
            doc = json.load(f)
        off_us = _as_float(offsets.get(rank)) * 1e6
        merged.append({"ph": "M", "name": "process_name", "pid": rank,
                       "tid": 0, "args": {"name": f"rank {rank}"}})
        for ev in doc.get("traceEvents", []):
            ev = dict(ev)
            ev["pid"] = rank
            if "ts" in ev:
                ev["ts"] = _as_float(ev["ts"]) - off_us
            merged.append(ev)
    out = {"traceEvents": merged, "displayTimeUnit": "ms"}
    if path:
        _write_json_atomic(path, out)
    return out


# -- worker side: the reporter -------------------------------------------

_active: Optional["FleetReporter"] = None


def active_reporter() -> Optional["FleetReporter"]:
    """The process's running FleetReporter (None when fleet telemetry is
    off) — hapi's MetricsCallback ships through it at step boundaries."""
    return _active


def maybe_ship(min_interval_s: Optional[float] = None):
    """Rate-limited publish through the active reporter; a no-op without
    one and never raises (safe on any step boundary)."""
    r = _active
    if r is not None:
        r.maybe_ship(min_interval_s)


class FleetReporter:
    """Ships this worker's telemetry snapshots over the elastic store.

    A daemon thread publishes every ``interval_s`` and polls the
    aggregator's flight-request flag; ``maybe_ship`` lets step
    boundaries (hapi ``MetricsCallback``) publish opportunistically
    between tick marks. Every store operation is wrapped: a dead or
    wedged store costs ``fleet.ship_failures`` increments, never an
    exception in the training process.
    """

    def __init__(self, store, rank: int, world: int, *,
                 generation: int = 0, job_id: str = "default",
                 interval_s: float = 1.0, max_events: int = 256,
                 max_retries: int = 2):
        self.store = store
        self.rank = int(rank)
        self.world = int(world)
        self.generation = int(generation)
        self.job_id = job_id
        self.interval_s = max(0.05, float(interval_s))
        self.max_events = max_events
        self.max_retries = max(1, int(max_retries))
        self.clock_offset: Optional[float] = None
        self._seq = 0
        self._last_pub = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        self._lock = threading.Lock()

    def _k(self, *parts: str) -> str:
        return _key(self.job_id, *parts)

    # -- clock handshake -------------------------------------------------
    def handshake(self, timeout_s: Optional[float] = None,
                  poll_s: float = 0.05) -> Optional[float]:
        """Store-ping clock sync: write a ping carrying the local send
        time; the aggregator's poll loop answers with its receive time;
        ``offset = local_midpoint - aggregator_time`` (positive = this
        rank's clock runs ahead). Returns None — and snapshots stay
        unaligned (offset 0) — when nobody answers in time, e.g. a run
        without a launcher-side aggregator."""
        if timeout_s is None:
            timeout_s = _as_float(
                os.environ.get(HANDSHAKE_TIMEOUT_ENV), 3.0)
        self._seq += 1
        token = f"{os.getpid()}-{self._seq}"
        t0 = time.time()
        try:
            self.store.set(self._k("ping", str(self.rank)),
                           f"{token} {t0}")
        except Exception as e:
            M_SHIP_FAILURES.inc(reason=type(e).__name__)
            return None
        deadline = t0 + timeout_s
        while time.time() < deadline:
            try:
                raw = self.store.get(self._k("pong", str(self.rank)),
                                     timeout_s=0).decode()
                got, agg_t = raw.split()
                if got == token:
                    t1 = time.time()
                    offset = (t0 + (t1 - t0) / 2) - float(agg_t)
                    self.clock_offset = offset
                    M_CLOCK_OFFSET.set(round(offset, 6),
                                       rank=str(self.rank))
                    return offset
            except Exception:
                pass
            time.sleep(poll_s)
        return None

    # -- publishing ------------------------------------------------------
    def publish(self, final: bool = False) -> bool:
        """Serialize and ship one snapshot. Bounded retry; returns False
        (and counts ``fleet.ship_failures``) instead of ever raising."""
        with self._lock:
            self._seq += 1
            try:
                payload = json.dumps(snapshot_dict(
                    self.rank, self.world, generation=self.generation,
                    seq=self._seq, clock_offset=self.clock_offset,
                    max_events=self.max_events, final=final),
                    default=str)
            except Exception as e:
                M_SHIP_FAILURES.inc(reason=type(e).__name__)
                return False
            err = "unknown"
            for _ in range(self.max_retries):
                try:
                    self.store.set(self._k("snap", str(self.rank)),
                                   payload)
                    self._last_pub = time.time()
                    M_SNAPSHOTS_SHIPPED.inc(rank=str(self.rank))
                    return True
                except Exception as e:
                    err = type(e).__name__
            M_SHIP_FAILURES.inc(reason=err)
            return False

    def maybe_ship(self, min_interval_s: Optional[float] = None):
        """Publish if at least ``min_interval_s`` (default: the periodic
        interval) passed since the last successful publish."""
        iv = self.interval_s if min_interval_s is None else min_interval_s
        if time.time() - self._last_pub >= iv:
            self.publish()
            self.check_flight_request()

    def check_flight_request(self):
        """Honor an aggregator-raised flight flag: dump the PR 5 flight
        ring with the flagged reason, then clear the flag (one dump per
        request)."""
        try:
            raw = self.store.get(self._k("flight_request",
                                         str(self.rank)),
                                 timeout_s=0).decode()
        except Exception:
            return
        if not raw:
            return
        try:
            self.store.set(self._k("flight_request", str(self.rank)), "")
        except Exception as e:
            M_SHIP_FAILURES.inc(reason=type(e).__name__)
        reason = raw.split()[0]
        path = flight.recorder.dump(
            reason, context={"rank": self.rank,
                             "generation": self.generation,
                             "requested_by": "fleet_aggregator",
                             "request": raw})
        if path:
            print(f"paddle_tpu fleet: rank {self.rank} wrote requested "
                  f"flight dump {path} ({raw})", file=sys.stderr,
                  flush=True)

    # -- lifecycle -------------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.publish()
            self.check_flight_request()

    def start(self):
        """Start periodic shipping and become the process's active
        reporter."""
        global _active
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="ptpu-fleet-reporter",
                daemon=True)
            self._thread.start()
        _active = self

    def close(self):
        """Stop the thread and publish the final snapshot (idempotent)."""
        global _active
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        self.publish(final=True)
        if _active is self:
            _active = None


# -- launcher side: the aggregator ---------------------------------------

def _median(vals: List[float]) -> float:
    vals = sorted(vals)
    n = len(vals)
    if not n:
        return 0.0
    mid = n // 2
    return vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2


def _step_totals(mets: Dict[str, Any]) -> Tuple[int, float]:
    """(count, sum) of train.step_seconds across all its label sets."""
    cnt, tot = 0, 0.0
    for s in mets.get("train.step_seconds", {}).get("series", []):
        cnt += s.get("count", 0)
        tot += s.get("sum", 0.0)
    return cnt, tot


class FleetAggregator:
    """Launcher-anchored fleet view over the elastic store.

    ``poll()`` (driven by a daemon thread between ``start``/``stop``, or
    called directly in tests) reads every rank's latest snapshot
    *without blocking on missing ones* (a late rank just keeps
    ``fleet.ranks_reporting`` below the world size), answers clock
    pings, updates the skew gauges, and runs straggler detection on the
    per-rank step-time deltas. ``stop()``/``finalize()`` write the
    aggregated ``fleet_metrics.json`` and the merged
    ``fleet_trace.json`` under ``out_dir``.
    """

    def __init__(self, store, world: int, *, job_id: str = "default",
                 out_dir: Optional[str] = None,
                 poll_interval_s: Optional[float] = None,
                 straggler_ratio: Optional[float] = None,
                 straggler_polls: Optional[int] = None,
                 min_step_seconds: float = 0.001):
        self.store = store
        self.world = int(world)
        self.job_id = job_id
        self.out_dir = out_dir
        self.poll_interval_s = poll_interval_s if poll_interval_s \
            else _as_float(os.environ.get(FLEET_POLL_ENV), 0.5)
        self.straggler_ratio = straggler_ratio if straggler_ratio \
            else _as_float(os.environ.get(STRAGGLER_RATIO_ENV), 2.0)
        self.straggler_polls = int(straggler_polls if straggler_polls
                                   else int(os.environ.get(
                                       STRAGGLER_POLLS_ENV, "2") or 2))
        self.min_step_seconds = min_step_seconds
        self.snapshots: Dict[int, Dict[str, Any]] = {}
        self.events: List[Dict[str, Any]] = []
        self._prev_step_totals: Dict[int, Tuple[int, float]] = {}
        self._recent_mean: Dict[int, float] = {}
        self._consec: Dict[int, int] = {}
        self._flagged: set = set()
        self._answered_pings: Dict[int, str] = {}
        self._polls = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _k(self, *parts: str) -> str:
        return _key(self.job_id, *parts)

    # -- one poll tick ---------------------------------------------------
    def _answer_pings(self):
        for rank in range(self.world):
            try:
                raw = self.store.get(self._k("ping", str(rank)),
                                     timeout_s=0).decode()
                token = raw.split()[0]
            except Exception:
                continue
            if self._answered_pings.get(rank) == token:
                continue
            try:
                self.store.set(self._k("pong", str(rank)),
                               f"{token} {time.time()}")
                self._answered_pings[rank] = token
            except Exception:
                pass

    def poll(self) -> Dict[int, Dict[str, Any]]:
        """Ingest every rank's current snapshot; never blocks on a
        missing or late rank."""
        self._answer_pings()
        for rank in range(self.world):
            try:
                raw = self.store.get(self._k("snap", str(rank)),
                                     timeout_s=0)
                snap = json.loads(raw)
            except Exception:
                continue
            prev = self.snapshots.get(rank)
            if prev is None or (snap.get("seq"), snap.get("generation")) \
                    != (prev.get("seq"), prev.get("generation")):
                M_SNAPSHOTS_RECEIVED.inc(rank=str(rank))
            self.snapshots[rank] = snap
        M_RANKS_REPORTING.set(len(self.snapshots), job=self.job_id)
        self._polls += 1
        self._update_skew()
        return dict(self.snapshots)

    def ranks_reporting(self) -> List[int]:
        return sorted(self.snapshots)

    # -- skew + straggler detection --------------------------------------
    def _update_skew(self):
        for rank, snap in self.snapshots.items():
            cnt, tot = _step_totals(snap.get("metrics", {}))
            pcnt, ptot = self._prev_step_totals.get(rank, (0, 0.0))
            if cnt > pcnt:
                mean = (tot - ptot) / (cnt - pcnt)
                self._recent_mean[rank] = mean
                M_RANK_STEP_SECONDS.set(round(mean, 6), rank=str(rank))
            self._prev_step_totals[rank] = (cnt, tot)
        means = self._recent_mean
        if len(means) < 2:
            return
        slowest = max(means, key=means.get)
        skew = means[slowest] - min(means.values())
        M_STEP_SKEW.set(round(skew, 6), job=self.job_id)
        M_SLOWEST_RANK.set(slowest, job=self.job_id)
        self._detect_stragglers(means)

    def _detect_stragglers(self, means: Dict[int, float]):
        for rank, mean in means.items():
            peers = [m for r, m in means.items() if r != rank]
            med = _median(peers)
            over = (med >= self.min_step_seconds
                    and mean > self.straggler_ratio * med)
            if not over:
                self._consec[rank] = 0
                self._flagged.discard(rank)
                continue
            self._consec[rank] = self._consec.get(rank, 0) + 1
            if self._consec[rank] >= self.straggler_polls \
                    and rank not in self._flagged:
                self._flagged.add(rank)
                self._fire_straggler(rank, mean, med)

    def _fire_straggler(self, rank: int, mean: float, med: float):
        ratio = mean / med if med else float("inf")
        M_STRAGGLERS.inc(rank=str(rank))
        self.events.append({
            "ts": time.time(), "kind": "fleet.straggler", "rank": rank,
            "mean_step_seconds": round(mean, 6),
            "peer_median_seconds": round(med, 6),
            "ratio": round(ratio, 3), "polls": self._consec[rank],
        })
        print(f"paddle_tpu fleet: straggler detected — rank {rank} "
              f"recent step mean {mean * 1e3:.1f}ms is {ratio:.1f}x the "
              f"peer median {med * 1e3:.1f}ms "
              f"({self._consec[rank]} consecutive polls); requesting a "
              f"flight dump from it", file=sys.stderr, flush=True)
        try:
            self.store.set(
                self._k("flight_request", str(rank)),
                f"{flight.REASON_STRAGGLER} ratio={ratio:.2f} "
                f"mean_step_seconds={mean:.4f}")
        except Exception:
            pass

    # -- outputs ---------------------------------------------------------
    def merged_metrics(self) -> Dict[str, Any]:
        own = {name: m for name, m in registry.to_dict().items()
               if name.startswith("fleet.") and m.get("series")}
        return merge_metrics(self.snapshots, own=own)

    def dump_dict(self) -> Dict[str, Any]:
        means = self._recent_mean
        return {
            "kind": FLEET_DUMP_KIND,
            "version": FLEET_VERSION,
            "generated_unix": time.time(),
            "job_id": self.job_id,
            "world": self.world,
            "polls": self._polls,
            "ranks_reporting": self.ranks_reporting(),
            "clock_offsets": {str(r): s.get("clock_offset")
                              for r, s in self.snapshots.items()},
            "snapshot_meta": {
                str(r): {k: s.get(k) for k in
                         ("seq", "pid", "host", "generation",
                          "published_unix", "final")}
                for r, s in self.snapshots.items()},
            "recent_step_seconds": {str(r): round(v, 6)
                                    for r, v in means.items()},
            "step_skew_seconds": (round(max(means.values())
                                        - min(means.values()), 6)
                                  if len(means) >= 2 else None),
            "slowest_rank": (max(means, key=means.get)
                             if means else None),
            "stragglers": sorted(self._flagged),
            "metrics": self.merged_metrics(),
            "events": list(self.events),
            "timeseries": merge_timeseries(list(self.snapshots.values())),
        }

    def finalize(self) -> Dict[str, str]:
        """One last poll, then write the aggregated dump + merged trace
        under ``out_dir`` (no-op paths when out_dir is unset)."""
        try:
            self.poll()
        except Exception:
            pass
        paths: Dict[str, str] = {}
        if self.out_dir:
            try:
                os.makedirs(self.out_dir, exist_ok=True)
                paths["metrics"] = _write_json_atomic(
                    os.path.join(self.out_dir, "fleet_metrics.json"),
                    self.dump_dict())
                paths["trace"] = write_merged_trace(
                    self.snapshots,
                    os.path.join(self.out_dir, "fleet_trace.json"))
            except Exception as e:
                print(f"paddle_tpu fleet: failed writing fleet outputs "
                      f"under {self.out_dir!r}: {e!r}", file=sys.stderr)
        return paths

    # -- lifecycle -------------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll()
            except Exception:
                pass

    def start(self):
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="ptpu-fleet-aggregator",
                daemon=True)
            self._thread.start()

    def stop(self) -> Dict[str, str]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        return self.finalize()


# -- incident-directory rendering (tools/metrics_report.py --fleet) ------

def load_incident_dir(dirname: str) -> Dict[str, Any]:
    """Collect one fleet incident from a directory: per-rank metric
    dumps (``*.rank<N>.json``, the launcher's rewrite shape), flight
    dumps (``flight-*.json``) and the aggregated fleet dump (any JSON
    whose ``kind`` is ``fleet_dump``)."""
    rank_dumps: Dict[int, Dict[str, Any]] = {}
    fleet_doc: Optional[Dict[str, Any]] = None
    flights: List[Tuple[str, Dict[str, Any]]] = []
    for path in sorted(glob.glob(os.path.join(dirname, "*.json"))):
        base = os.path.basename(path)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(doc, dict):
            continue
        kind = doc.get("kind")
        m = RANK_DUMP_RE.search(base)
        if kind == FLEET_DUMP_KIND:
            if fleet_doc is None or doc.get("generated_unix", 0) \
                    > fleet_doc.get("generated_unix", 0):
                fleet_doc = doc
        elif kind == flight.FLIGHT_DUMP_KIND:
            flights.append((path, doc))
        elif m and "metrics" in doc:
            rank_dumps[int(m.group(1))] = doc
    flights.sort(key=lambda pd: pd[1].get("generated_unix", 0))
    return {"dir": dirname, "rank_dumps": rank_dumps,
            "fleet": fleet_doc, "flights": flights}


def render_incident(inc: Dict[str, Any], max_events: int = 40,
                    top: Optional[int] = None) -> str:
    """One human report over a whole multi-process incident: skew
    summary, per-rank gauge table (rank-labeled merged metrics),
    clock-aligned cross-rank event interleaving, and the flight-dump
    index."""
    from .report import render_report

    lines: List[str] = [f"FLEET INCIDENT — {inc['dir']}"]
    ranks = sorted(inc["rank_dumps"])
    fdoc = inc.get("fleet") or {}
    head = (f"rank metric dumps: {ranks if ranks else 'none'}   "
            f"flight dumps: {len(inc['flights'])}")
    if fdoc:
        head += (f"   world={fdoc.get('world')} "
                 f"reporting={fdoc.get('ranks_reporting')}")
    lines.append(head)
    offsets = {int(r): _as_float(v) for r, v in
               (fdoc.get("clock_offsets") or {}).items()}

    # -- skew summary ----------------------------------------------------
    rows = []
    for r in ranks:
        cnt, tot = _step_totals(inc["rank_dumps"][r].get("metrics", {}))
        mx = max((s.get("max", 0.0) for s in
                  inc["rank_dumps"][r].get("metrics", {})
                  .get("train.step_seconds", {}).get("series", [])),
                 default=0.0)
        rows.append((r, cnt, tot / cnt if cnt else 0.0, mx,
                     offsets.get(r)))
    if rows:
        lines += ["", "Per-rank step summary",
                  f"{'rank':>4}{'steps':>8}{'avg_ms':>10}{'max_ms':>10}"
                  f"{'clock_offset_ms':>17}"]
        for r, cnt, avg, mx, off in rows:
            lines.append(
                f"{r:>4}{cnt:>8}{avg * 1e3:>10.2f}{mx * 1e3:>10.2f}"
                + (f"{off * 1e3:>17.3f}" if off is not None
                   else f"{'-':>17}"))
    if fdoc:
        skew = fdoc.get("step_skew_seconds")
        if skew is not None:
            lines.append(f"step skew {skew * 1e3:.2f}ms, slowest rank "
                         f"{fdoc.get('slowest_rank')}, recent means "
                         + " ".join(
                             f"r{r}={v * 1e3:.1f}ms" for r, v in sorted(
                                 (fdoc.get("recent_step_seconds")
                                  or {}).items())))
        for e in fdoc.get("events", []):
            if e.get("kind") == "fleet.straggler":
                lines.append(
                    f"STRAGGLER rank {e.get('rank')}: recent step mean "
                    f"{_as_float(e.get('mean_step_seconds')) * 1e3:.1f}ms"
                    f" = {e.get('ratio')}x peer median over "
                    f"{e.get('polls')} polls")

    # -- merged per-rank metric view ------------------------------------
    # the per-rank atexit dumps are the COMPLETE final registries (the
    # aggregator's snapshots may trail them); merge those and fold in
    # the launcher-side fleet.* series from the aggregated dump
    if inc["rank_dumps"]:
        own = {name: m for name, m in (fdoc.get("metrics") or {}).items()
               if name.startswith("fleet.")}
        merged = merge_metrics(
            {r: d for r, d in inc["rank_dumps"].items()}, own=own)
    else:
        merged = fdoc.get("metrics") or {}
    if merged:
        lines += ["", render_report({"metrics": merged}, max_events=0,
                                    top=top)]

    # -- clock-aligned cross-rank interleaving ---------------------------
    evs: List[Tuple[float, str, Dict[str, Any]]] = []
    for r in ranks:
        off = offsets.get(r, 0.0)
        for e in inc["rank_dumps"][r].get("events", []):
            evs.append((_as_float(e.get("ts")) - off, f"r{r}", e))
    covered = set(ranks)
    for _, fd in inc["flights"]:
        # a rank that died without an atexit metrics dump still left its
        # flight ring — use it so the interleaving covers every rank
        ctx = fd.get("context") or {}
        r = ctx.get("rank")
        if r in covered:
            continue
        off = offsets.get(r, 0.0) if isinstance(r, int) else 0.0
        for e in fd.get("events", []):
            evs.append((_as_float(e.get("ts")) - off,
                        f"r{r if r is not None else '?'}*", e))
    if evs and max_events > 0:
        evs.sort(key=lambda t: t[0])
        shown = evs[-max_events:]
        lines += ["", f"Cross-rank events (clock-aligned, last "
                      f"{len(shown)} of {len(evs)}; * = from a flight "
                      f"dump)", "-" * 78]
        for ts, tag, e in shown:
            fields = " ".join(f"{k}={v}" for k, v in e.items()
                              if k not in ("ts", "kind"))
            lines.append(
                f"{time.strftime('%H:%M:%S', time.localtime(ts))} "
                f"[{tag:>4}] {e.get('kind', '?')}: {fields}")

    # -- flight dump index ----------------------------------------------
    if inc["flights"]:
        lines += ["", "Flight dumps (render each with "
                      "tools/metrics_report.py <file>):"]
        for path, fd in inc["flights"]:
            ctx = fd.get("context") or {}
            ctx_s = " ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
            lines.append(f"  {os.path.basename(path)}  "
                         f"reason={fd.get('reason')}  "
                         f"pid={fd.get('pid')}  {ctx_s}")
    return "\n".join(lines)
