"""Metric time-series: the time dimension of the telemetry plane.

Every other observability layer is point-in-time — one metrics dump at
exit, one snapshot per fleet ship, one rolling SLO window for serving.
This module keeps a bounded *history*: a :class:`SeriesRecorder` samples
a tracked set of registry metrics at step boundaries (driven by the
``obs.step_region`` / ``ServeEngine.step`` hooks via
``observability/health.py``) and stores ``(t, value)`` points in
per-series ring buffers, so detectors can ask "how has ``train.
step_seconds`` moved since step 2k?" instead of "what is it now?".

Sampling semantics by metric kind (reference: the monitor daemons that
tail the reference framework's profiler statistic tables over time):

- **counters** are recorded as *deltas* between consecutive samples
  (the first sample only sets the baseline — a job restarted mid-run
  must not register its lifetime total as one giant spike);
- **gauges** are recorded as *levels* (multi-labelset gauges collapse
  to the max across series, the conservative choice for watermarks and
  occupancies);
- **histograms** are recorded as *per-window* statistics from
  bucket-count deltas: the window mean under the metric's own name and
  an interpolated window quantile under ``<name>.p90``.

Memory is bounded by ``FLAGS_observability_ts_points`` points per
series no matter how long the job runs; the clock is injectable
(``obs.FakeClock`` works) so every detector test is deterministic.
Recorded histories ship inside fleet snapshots (``fleet.snapshot_dict``
includes ``to_dict()``) so the aggregator can build fleet-wide series
with per-rank lanes.
"""
from __future__ import annotations

import collections
import math
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import flight
from .events import ring_len as _events_ring_len
from .metrics import Counter, Gauge, Histogram, registry

M_POINTS = registry.counter(
    "ts.points_recorded",
    "time-series points recorded by SeriesRecorder, labeled by series")

#: capacity flag; read lazily at recorder construction so tests can
#: set the flag first (same pattern as events/flight ring buffers).
_CAPACITY_FLAG = "observability_ts_points"

#: registry metrics sampled by default. Unregistered names are skipped
#: silently — tracking is declarative, the subsystems stay decoupled.
DEFAULT_TRACKED = (
    "train.step_seconds",          # histogram -> window mean + .p90
    "train.items_per_second",      # gauge
    "serve.tokens_per_sec",        # gauge
    "serve.pool_occupancy",        # gauge
    "serve.queue_depth",           # gauge
    "serve.tokens_generated",      # counter -> per-window delta
    "device.hbm_watermark_bytes",  # gauge
    "elastic.steps_lost",          # counter -> per-window delta
    "fleet.ship_failures",         # counter -> per-window delta
)

#: quantile recorded for tracked histograms (as ``<name>.p90``).
HIST_QUANTILE = 0.90


def _default_capacity() -> int:
    from ..core import flags

    try:
        return max(2, int(flags.get_flag(_CAPACITY_FLAG)))
    except KeyError:
        return 512


def _resolve_clock(clock) -> Callable[[], float]:
    if clock is None:
        return time.time
    if callable(clock):
        return clock
    return clock.time  # clock object (FakeClock satisfies both)


def _bucket_quantile(bounds: Sequence[float], deltas: Sequence[int],
                     q: float) -> Optional[float]:
    """Interpolated quantile from per-window bucket-count deltas."""
    total = sum(deltas)
    if total <= 0:
        return None
    rank = q * total
    seen = 0
    lo = 0.0
    for i, n in enumerate(deltas):
        hi = bounds[i] if i < len(bounds) else bounds[-1]
        if n and seen + n >= rank:
            if i >= len(bounds):      # overflow bucket: clamp to last bound
                return float(bounds[-1])
            frac = (rank - seen) / n
            return lo + (hi - lo) * frac
        seen += n
        lo = hi
    return float(bounds[-1])


class SeriesRecorder:
    """Ring-buffered ``(t, value)`` history for a tracked metric set.

    ``record()`` appends a raw level point; ``sample()`` walks the
    tracked registry metrics applying the per-kind semantics above,
    plus two host-side ring-length probes (``host.events_ring_len`` /
    ``host.flight_ring_len``) so a Python-side buffer that stops
    honoring its bound shows up as a leak like any other series.
    """

    def __init__(self, capacity: Optional[int] = None, clock=None,
                 tracked: Optional[Sequence[str]] = None):
        self.capacity = int(capacity) if capacity else _default_capacity()
        self._clock = _resolve_clock(clock)
        self.tracked = tuple(tracked if tracked is not None
                             else DEFAULT_TRACKED)
        self._series: Dict[str, collections.deque] = {}
        self._prev_counter: Dict[str, int] = {}
        self._prev_hist: Dict[str, Tuple[int, float, Tuple[int, ...]]] = {}
        self.samples = 0

    # -- raw points -------------------------------------------------------
    def record(self, name: str, value: float,
               t: Optional[float] = None) -> None:
        dq = self._series.get(name)
        if dq is None:
            dq = self._series[name] = collections.deque(
                maxlen=self.capacity)
        dq.append((self._clock() if t is None else float(t), value))
        M_POINTS.inc(series=name)

    # -- per-kind sampling ------------------------------------------------
    def _sample_counter(self, name: str, m: Counter, now: float) -> None:
        total = m.total()
        prev = self._prev_counter.get(name)
        self._prev_counter[name] = total
        if prev is None:
            return  # baseline only: lifetime total is not a window delta
        self.record(name, total - prev, t=now)

    def _sample_gauge(self, name: str, m: Gauge, now: float) -> None:
        values = [v for v in m._series.values()
                  if isinstance(v, (int, float)) and math.isfinite(v)]
        if not values:
            return
        self.record(name, max(values), t=now)

    def _sample_histogram(self, name: str, m: Histogram,
                          now: float) -> None:
        count, total = 0, 0.0
        buckets = [0] * (len(m.bounds) + 1)
        for s in m._series.values():
            count += s.count
            total += s.sum
            for i, n in enumerate(s.bucket_counts):
                buckets[i] += n
        prev = self._prev_hist.get(name)
        self._prev_hist[name] = (count, total, tuple(buckets))
        if prev is None:
            return
        pcount, psum, pbuckets = prev
        dcount = count - pcount
        if dcount <= 0:
            return  # no observations this window: record nothing
        self.record(name, (total - psum) / dcount, t=now)
        deltas = [b - pb for b, pb in zip(buckets, pbuckets)]
        quant = _bucket_quantile(m.bounds, deltas, HIST_QUANTILE)
        if quant is not None:
            self.record(f"{name}.p90", quant, t=now)

    def sample(self, now: Optional[float] = None) -> None:
        """Take one sample of every tracked series (one step boundary)."""
        t = self._clock() if now is None else float(now)
        self.samples += 1
        for name in self.tracked:
            m = registry.get(name)
            if isinstance(m, Counter):
                self._sample_counter(name, m, t)
            elif isinstance(m, Histogram):
                self._sample_histogram(name, m, t)
            elif isinstance(m, Gauge):
                self._sample_gauge(name, m, t)
        self.record("host.events_ring_len", _events_ring_len(), t=t)
        self.record("host.flight_ring_len",
                    len(flight.recorder._ring)
                    if flight.recorder._ring is not None else 0, t=t)

    # -- access -----------------------------------------------------------
    def window(self, name: str) -> List[Tuple[float, float]]:
        return list(self._series.get(name, ()))

    def values(self, name: str) -> List[float]:
        return [v for _t, v in self._series.get(name, ())]

    def names(self) -> List[str]:
        return sorted(self._series)

    def points_total(self) -> int:
        return sum(len(dq) for dq in self._series.values())

    def clear(self) -> None:
        self._series.clear()
        self._prev_counter.clear()
        self._prev_hist.clear()
        self.samples = 0

    # -- serialization (shipped inside fleet snapshots) -------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "capacity": self.capacity,
            "samples": self.samples,
            "series": {name: [[t, v] for t, v in dq]
                       for name, dq in sorted(self._series.items())},
        }


def merge_timeseries(snapshots: Sequence[Dict[str, Any]],
                     own: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """Fold shipped per-rank histories into fleet-wide per-rank lanes.

    Returns ``{series_name: {"lanes": {rank: [[t, v], ...]}}}`` — ranks
    stay separate (a leak on rank 3 must not be averaged away by seven
    healthy peers); cross-rank reduction is the *reader's* choice.
    """
    merged: Dict[str, Dict[str, Any]] = {}

    def _fold(rank, ts_doc):
        if not isinstance(ts_doc, dict):
            return
        for name, points in (ts_doc.get("series") or {}).items():
            lane = merged.setdefault(name, {"lanes": {}})
            lane["lanes"][str(rank)] = points

    for snap in snapshots:
        _fold(snap.get("rank", "?"), snap.get("timeseries"))
    if own is not None:
        _fold(own.get("rank", "own"), own.get("timeseries"))
    return merged
