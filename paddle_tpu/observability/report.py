"""Metrics dump (JSON) + human summary rendering.

``dump()`` serializes the whole registry plus the event ring buffer into
one JSON document; ``render_report()`` turns that document (live or
re-loaded from disk — ``tools/metrics_report.py``) into the human table,
so the dump round-trips by construction.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from . import _gate
from .events import events as _list_events
from .metrics import registry

DUMP_ENV = "PADDLE_TPU_METRICS_DUMP"
DUMP_VERSION = 1


def dump_dict() -> Dict[str, Any]:
    return {
        "version": DUMP_VERSION,
        "generated_unix": time.time(),
        "enabled": _gate.state.on,
        "metrics": registry.to_dict(),
        "events": [e.to_dict() for e in _list_events()],
    }


def dump(path: Optional[str] = None) -> Dict[str, Any]:
    """Serialize all metrics + events; write JSON to ``path`` (or the
    ``PADDLE_TPU_METRICS_DUMP`` env path) when one is given. Always
    returns the dump dict."""
    d = dump_dict()
    path = path or os.environ.get(DUMP_ENV)
    if path:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(d, f, indent=1, default=str)
        os.replace(tmp, path)
    return d


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_secs(s: float) -> str:
    return f"{s * 1e3:.3f}ms" if s < 1.0 else f"{s:.3f}s"


def render_report(d: Dict[str, Any], max_events: int = 20) -> str:
    """Human table over a dump dict (live or loaded from a JSON file)."""
    metrics = d.get("metrics", {}) if isinstance(d, dict) else None
    if not isinstance(metrics, dict):
        raise ValueError("not a metrics dump: no 'metrics' mapping")
    counters, gauges, hists = [], [], []
    for name in sorted(metrics):
        m = metrics[name]
        kind = m.get("kind")
        for s in m.get("series", []):
            row_name = name + _fmt_labels(s.get("labels", {}))
            if kind == "counter":
                counters.append((row_name, s["value"]))
            elif kind == "gauge":
                gauges.append((row_name, s["value"]))
            elif kind == "histogram":
                cnt = s.get("count", 0)
                avg = s.get("sum", 0.0) / cnt if cnt else 0.0
                hists.append((row_name, cnt, s.get("sum", 0.0), avg,
                              s.get("max", 0.0)))
    lines: List[str] = []
    width = 64
    if counters:
        lines += ["Counters", "-" * (width + 14)]
        lines += [f"{n[:width]:<{width}}{v:>14}" for n, v in counters]
    if gauges:
        lines += ["", "Gauges", "-" * (width + 14)]
        lines += [f"{n[:width]:<{width}}{str(v):>14}" for n, v in gauges]
    if hists:
        header = (f"{'Histogram':<{width}}{'Count':>8}{'Total':>12}"
                  f"{'Avg':>12}{'Max':>12}")
        lines += ["", header, "-" * len(header)]
        lines += [f"{n[:width]:<{width}}{c:>8}{_fmt_secs(t):>12}"
                  f"{_fmt_secs(a):>12}{_fmt_secs(mx):>12}"
                  for n, c, t, a, mx in hists]
    evs = d.get("events", [])
    if evs:
        lines += ["", f"Events (last {min(max_events, len(evs))} of "
                      f"{len(evs)})", "-" * (width + 14)]
        for e in evs[-max_events:]:
            e = dict(e)
            ts, kind = e.pop("ts", 0.0), e.pop("kind", "?")
            fields = " ".join(f"{k}={v}" for k, v in e.items())
            lines.append(f"{time.strftime('%H:%M:%S', time.localtime(ts))} "
                         f"{kind}: {fields}")
    if not lines:
        lines = ["(no metrics recorded)"]
    return "\n".join(lines)


def summary(max_events: int = 20) -> str:
    """Human-readable table over the live registry."""
    return render_report(dump_dict(), max_events=max_events)
