"""Metrics dump (JSON) + human summary rendering.

``dump()`` serializes the whole registry plus the event ring buffer into
one JSON document; ``render_report()`` turns that document (live or
re-loaded from disk — ``tools/metrics_report.py``) into the human table,
so the dump round-trips by construction. ``render_flight()`` does the
same for flight-recorder crash dumps (``observability.flight``).

Rows are grouped by subsystem (the ``<subsystem>.`` metric-name prefix),
value columns are unit-aware (``*_seconds`` renders ms/s, ``*_bytes``
renders KiB/MiB/GiB, everything else raw), and ``top=N`` keeps only the
N largest series per metric — the shape a human scans when a dump has
hundreds of labeled series.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from . import _gate
from .events import events as _list_events
from .metrics import registry

DUMP_ENV = "PADDLE_TPU_METRICS_DUMP"
DUMP_VERSION = 1


def dump_dict() -> Dict[str, Any]:
    d = {
        "version": DUMP_VERSION,
        "generated_unix": time.time(),
        "enabled": _gate.state.on,
        "metrics": registry.to_dict(),
        "events": [e.to_dict() for e in _list_events()],
    }
    from . import health as _health

    mon = _health.active_monitor()
    if mon is not None:
        # only when health monitoring is on — an unmonitored process
        # dumps byte-identical documents (solo equivalence)
        d["timeseries"] = mon.recorder.to_dict()
        d["health_alerts"] = list(mon.alerts)
    return d


def dump(path: Optional[str] = None) -> Dict[str, Any]:
    """Serialize all metrics + events; write JSON to ``path`` (or the
    ``PADDLE_TPU_METRICS_DUMP`` env path) when one is given. Always
    returns the dump dict."""
    d = dump_dict()
    path = path or os.environ.get(DUMP_ENV)
    if path:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(d, f, indent=1, default=str)
        os.replace(tmp, path)
    return d


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt_secs(s: float) -> str:
    return f"{s * 1e3:.3f}ms" if s < 1.0 else f"{s:.3f}s"


def _fmt_bytes(b: float) -> str:
    b = float(b)
    for unit, div in (("GiB", 2**30), ("MiB", 2**20), ("KiB", 2**10)):
        if abs(b) >= div:
            return f"{b / div:.2f}{unit}"
    return f"{b:.0f}B"


def _fmt_raw(v) -> str:
    if isinstance(v, float):
        return f"{v:.4g}"
    return str(v)


def _value_formatter(name: str):
    """Unit from the metric-name suffix (the `noun_verb` convention makes
    `_seconds` / `_bytes` the unit authority)."""
    leaf = name.rsplit(".", 1)[-1]
    if leaf.endswith("seconds"):
        return _fmt_secs
    if leaf.endswith("bytes"):
        return _fmt_bytes
    return _fmt_raw


_WIDTH = 64


def _trim(rows: List, top: Optional[int]) -> Tuple[List, int]:
    """Keep the ``top`` largest rows (rows pre-sorted desc); returns
    (kept, dropped_count)."""
    if top is None or top <= 0 or len(rows) <= top:
        return rows, 0
    return rows[:top], len(rows) - top


def render_report(d: Dict[str, Any], max_events: int = 20,
                  top: Optional[int] = None) -> str:
    """Human table over a dump dict (live or loaded from a JSON file),
    grouped by metric subsystem. ``top`` keeps only the N largest series
    per metric (by count/value)."""
    metrics = d.get("metrics", {}) if isinstance(d, dict) else None
    if not isinstance(metrics, dict):
        raise ValueError("not a metrics dump: no 'metrics' mapping")

    # subsystem -> list of (kind, row...) preserving per-metric blocks
    groups: Dict[str, Dict[str, List]] = {}
    for name in sorted(metrics):
        m = metrics[name]
        kind = m.get("kind")
        sub = name.split(".", 1)[0]
        g = groups.setdefault(sub, {"counter": [], "gauge": [],
                                    "histogram": []})
        fmt = _value_formatter(name)
        series = m.get("series", [])
        if kind == "counter":
            rows = sorted(
                ((name + _fmt_labels(s.get("labels", {})),
                  s.get("value", 0)) for s in series),
                key=lambda r: -_as_num(r[1]))
            rows, dropped = _trim(rows, top)
            g["counter"] += [(n, fmt(v)) for n, v in rows]
            if dropped:
                g["counter"].append((f"  ... {dropped} more series", ""))
        elif kind == "gauge":
            rows = sorted(
                ((name + _fmt_labels(s.get("labels", {})),
                  s.get("value")) for s in series),
                key=lambda r: -_as_num(r[1]))
            rows, dropped = _trim(rows, top)
            g["gauge"] += [(n, fmt(v) if v is not None else "-")
                           for n, v in rows]
            if dropped:
                g["gauge"].append((f"  ... {dropped} more series", ""))
        elif kind == "histogram":
            rows = []
            for s in series:
                cnt = s.get("count", 0)
                total = s.get("sum", 0.0) or 0.0
                avg = total / cnt if cnt else 0.0
                rows.append((name + _fmt_labels(s.get("labels", {})),
                             cnt, total, avg, s.get("max", 0.0) or 0.0))
            rows.sort(key=lambda r: -_as_num(r[1]))
            rows, dropped = _trim(rows, top)
            g["histogram"] += [
                (n, str(c), fmt(t), fmt(a), fmt(mx))
                for n, c, t, a, mx in rows]
            if dropped:
                g["histogram"].append(
                    (f"  ... {dropped} more series", "", "", "", ""))

    lines: List[str] = []
    for sub in sorted(groups):
        g = groups[sub]
        if not (g["counter"] or g["gauge"] or g["histogram"]):
            continue
        if lines:
            lines.append("")
        lines.append(f"=== {sub} ===")
        if sub == "opt":
            opt_lines = render_opt_table(metrics)
            if opt_lines:
                lines += opt_lines + [""]
        if sub == "cost":
            cost_lines = render_cost_table(metrics)
            if cost_lines:
                lines += cost_lines + [""]
            comm_lines = render_comm_table(metrics)
            if comm_lines:
                lines += comm_lines + [""]
        if g["counter"]:
            lines += ["Counters", "-" * (_WIDTH + 14)]
            lines += [f"{n[:_WIDTH]:<{_WIDTH}}{v:>14}"
                      for n, v in g["counter"]]
        if g["gauge"]:
            if g["counter"]:
                lines.append("")
            lines += ["Gauges", "-" * (_WIDTH + 14)]
            lines += [f"{n[:_WIDTH]:<{_WIDTH}}{v:>14}"
                      for n, v in g["gauge"]]
        if g["histogram"]:
            if g["counter"] or g["gauge"]:
                lines.append("")
            header = (f"{'Histogram':<{_WIDTH}}{'Count':>8}{'Total':>12}"
                      f"{'Avg':>12}{'Max':>12}")
            lines += [header, "-" * len(header)]
            lines += [f"{n[:_WIDTH]:<{_WIDTH}}{c:>8}{t:>12}{a:>12}{mx:>12}"
                      for n, c, t, a, mx in g["histogram"]]
    lines_events = _render_events(d.get("events", []), max_events)
    if lines_events:
        if lines:
            lines.append("")
        lines += lines_events
    if not lines:
        lines = ["(no metrics recorded)"]
    return "\n".join(lines)


def _as_num(v) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


def render_opt_table(metrics: Dict[str, Any]) -> List[str]:
    """Per-code fixed/remaining table for the lint->rewrite driver
    (``opt.findings_fixed`` / ``opt.findings_remaining``), rendered next
    to the per-pass timing view inside the ``opt`` subsystem section —
    the at-a-glance answer to "what did optimize_program actually fix,
    and what is still outstanding"."""
    def by_code(name):
        out = {}
        for s in (metrics.get(name) or {}).get("series", []):
            code = (s.get("labels") or {}).get("code")
            if code is not None:
                out[code] = s.get("value", 0)
        return out

    fixed = by_code("opt.findings_fixed")
    remaining = by_code("opt.findings_remaining")
    if not fixed and not remaining:
        return []
    header = f"{'code':<10}{'fixed':>10}{'remaining':>12}"
    lines = ["lint -> rewrite, findings by code", header,
             "-" * len(header)]
    for code in sorted(set(fixed) | set(remaining)):
        rem = remaining.get(code)
        lines.append(f"{code:<10}{fixed.get(code, 0):>10}"
                     f"{'-' if rem is None else rem:>12}")
    return lines


def render_cost_table(metrics: Dict[str, Any]) -> List[str]:
    """Predicted-vs-measured FLOPs/HBM table for the static cost model
    (``cost.predicted_*`` vs ``cost.measured_*`` gauges, by program
    name), rendered inside the ``cost`` subsystem section next to the
    ``opt`` per-code view — the at-a-glance answer to "is the cost
    model still telling the truth" (PTL302 fires when it is not)."""
    def by_name(metric_name):
        out = {}
        for s in (metrics.get(metric_name) or {}).get("series", []):
            name = (s.get("labels") or {}).get("name")
            if name is not None:
                out[name] = s.get("value")
        return out

    pred_f = by_name("cost.predicted_flops")
    meas_f = by_name("cost.measured_flops")
    err = by_name("cost.model_flops_error_pct")
    pred_m = by_name("cost.predicted_peak_hbm_bytes")
    meas_m = by_name("cost.measured_peak_hbm_bytes")
    pred_s = by_name("cost.predicted_step_seconds")
    meas_s = by_name("cost.measured_step_seconds")
    err_s = by_name("cost.model_step_error_pct")
    names = sorted(set(pred_f) | set(pred_m))
    step_names = sorted(set(pred_s) | set(meas_s))
    if not names and not step_names:
        return []

    def fmt(v, f=_fmt_raw):
        return "-" if v is None else f(v)

    lines: List[str] = []
    if names:
        header = (f"{'program':<16}{'pred flops':>14}{'xla flops':>14}"
                  f"{'err%':>8}{'pred peak':>12}{'measured':>12}")
        lines += ["cost model, predicted vs measured", header,
                  "-" * len(header)]
        for n in names:
            lines.append(
                f"{n[:16]:<16}{fmt(pred_f.get(n)):>14}"
                f"{fmt(meas_f.get(n)):>14}{fmt(err.get(n)):>8}"
                f"{fmt(pred_m.get(n), _fmt_bytes):>12}"
                f"{fmt(meas_m.get(n), _fmt_bytes):>12}")
    if step_names:
        header2 = (f"{'program':<16}{'pred step':>14}{'measured':>14}"
                   f"{'err%':>8}")
        if lines:
            lines.append("")
        lines += ["step-time model, predicted vs measured "
                  "(PTL304 guards the drift)", header2,
                  "-" * len(header2)]
        for n in step_names:
            lines.append(
                f"{n[:16]:<16}{fmt(pred_s.get(n), _fmt_secs):>14}"
                f"{fmt(meas_s.get(n), _fmt_secs):>14}"
                f"{fmt(err_s.get(n)):>8}")
    return lines


def render_comm_table(metrics: Dict[str, Any]) -> List[str]:
    """Per-collective predicted comm-cost table
    (``cost.comm_predicted_bytes``/``_seconds``, by program name +
    collective kind) rendered next to the cost table — the analytical
    decomposition of the step-time model's comm term, so "why is this
    placement predicted slower" reads straight off the dump."""
    rows: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for metric, col in (("cost.comm_predicted_bytes", "bytes"),
                        ("cost.comm_predicted_seconds", "seconds")):
        for s in (metrics.get(metric) or {}).get("series", []):
            labels = s.get("labels") or {}
            name, kind = labels.get("name"), labels.get("kind")
            if name is None or kind is None:
                continue
            rows.setdefault((name, kind), {})[col] = s.get("value")
    if not rows:
        return []
    header = (f"{'program':<16}{'collective':<16}{'wire bytes':>14}"
              f"{'seconds':>12}")
    lines = ["predicted comm cost, by collective kind", header,
             "-" * len(header)]
    for (name, kind) in sorted(rows, key=lambda k: (
            k[0], k[1] == "all", k[1])):  # per-kind rows, then the roll-up
        r = rows[(name, kind)]
        b, sec = r.get("bytes"), r.get("seconds")
        lines.append(
            f"{name[:16]:<16}{kind:<16}"
            f"{'-' if b is None else _fmt_bytes(b):>14}"
            f"{'-' if sec is None else _fmt_secs(float(sec)):>12}")
    return lines


#: eight-level block ramp for unicode sparklines.
SPARK_CHARS = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float], width: int = 32) -> str:
    """Render a numeric series as a unicode sparkline (empty string for
    an empty series). Series longer than ``width`` are mean-downsampled
    so the whole window fits one glance."""
    vals = [_as_num(v) for v in values]
    if not vals:
        return ""
    if len(vals) > width:
        n = len(vals)
        vals = [sum(vals[i * n // width:(i + 1) * n // width])
                / max(1, (i + 1) * n // width - i * n // width)
                for i in range(width)]
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return SPARK_CHARS[0] * len(vals)
    scale = (len(SPARK_CHARS) - 1) / (hi - lo)
    return "".join(SPARK_CHARS[int((v - lo) * scale)] for v in vals)


def render_trend_table(series: Dict[str, List],
                       title: str = "Time-series") -> List[str]:
    """Trend table over ``{name: [[t, v], ...]}`` windows: point count,
    first/last values, total change, and the sparkline."""
    if not series:
        return []
    header = (f"{title:<40}{'Pts':>6}{'First':>12}{'Last':>12}"
              f"{'Change':>9}  Trend")
    lines = [header, "-" * len(header)]
    for name in sorted(series):
        points = series[name] or []
        vals = [p[1] for p in points if isinstance(p, (list, tuple))
                and len(p) == 2]
        if not vals:
            continue
        # quantile series ("train.step_seconds.p90") format like their
        # parent metric
        fmt = _value_formatter(name.rsplit(".p", 1)[0]
                               if name.rpartition(".p")[2].isdigit()
                               else name)
        first, last = _as_num(vals[0]), _as_num(vals[-1])
        change = (f"{100.0 * (last - first) / abs(first):+.1f}%"
                  if first else "-")
        lines.append(f"{name[:40]:<40}{len(vals):>6}"
                     f"{fmt(first):>12}{fmt(last):>12}{change:>9}  "
                     f"{sparkline(vals)}")
    return lines


def render_health(d: Dict[str, Any]) -> str:
    """Health view of a dump: the recorded time-series as trend tables
    plus any alerts. Accepts a metrics dump carrying ``timeseries``
    (``PADDLE_TPU_HEALTH`` runs), a ``health_alert`` flight dump (the
    offending window rides the context), or a fleet dump whose
    ``timeseries`` holds per-rank lanes."""
    from .flight import FLIGHT_DUMP_KIND

    lines: List[str] = []
    if isinstance(d, dict) and d.get("kind") == FLIGHT_DUMP_KIND:
        ctx = d.get("context") or {}
        lines.append(f"HEALTH ALERT — rule {ctx.get('rule', '?')!r} "
                     f"({ctx.get('code', '?')}) on series "
                     f"{ctx.get('series', '?')!r}")
        detail = " ".join(
            f"{k}={v}" for k, v in sorted(ctx.items())
            if k not in ("window", "rule", "code", "series"))
        if detail:
            lines.append(detail)
        window = ctx.get("window") or []
        if window:
            lines += [""] + render_trend_table(
                {str(ctx.get("series", "series")): window},
                title="Offending window")
        return "\n".join(lines) if lines else "(no health context)"

    ts = (d or {}).get("timeseries") or {}
    series = ts.get("series")
    if series is not None:                       # per-process dump
        lines += render_trend_table(series)
    else:                                        # fleet dump: rank lanes
        lanes_by_name = {
            f"{name} [rank {rank}]": points
            for name, doc in ts.items()
            for rank, points in sorted((doc.get("lanes") or {}).items())
        }
        lines += render_trend_table(lanes_by_name,
                                    title="Time-series (per-rank lanes)")
    alerts = (d or {}).get("health_alerts") or []
    if alerts:
        if lines:
            lines.append("")
        lines.append(f"Alerts ({len(alerts)})")
        lines.append("-" * (_WIDTH + 14))
        for a in alerts:
            lines.append(
                f"{a.get('code', '?')} {a.get('rule', '?')} on "
                f"{a.get('series', '?')}: "
                + " ".join(f"{k}={v}" for k, v in sorted(a.items())
                           if k not in ("code", "rule", "series")))
    ham = (d or {}).get("metrics", {}).get("health.alerts", {})
    rows = ham.get("series") or []
    if rows:
        if lines:
            lines.append("")
        lines.append("health.alerts")
        lines.append("-" * (_WIDTH + 14))
        for s in rows:
            lines.append(f"{_fmt_labels(s.get('labels', {})):<{_WIDTH}}"
                         f"{s.get('value', 0):>14}")
    if not lines:
        return ("(no time-series recorded — set PADDLE_TPU_HEALTH=1 "
                "or install a HealthMonitor)")
    return "\n".join(lines)


def _render_events(evs: List[Dict[str, Any]], max_events: int) -> List[str]:
    if not evs or max_events <= 0:
        return []
    lines = [f"Events (last {min(max_events, len(evs))} of {len(evs)})",
             "-" * (_WIDTH + 14)]
    for e in evs[-max_events:]:
        e = dict(e)
        ts, kind = e.pop("ts", 0.0), e.pop("kind", "?")
        fields = " ".join(f"{k}={v}" for k, v in e.items())
        lines.append(f"{time.strftime('%H:%M:%S', time.localtime(_as_num(ts)))} "
                     f"{kind}: {fields}")
    return lines


def render_flight(d: Dict[str, Any], max_events: int = 50,
                  top: Optional[int] = None) -> str:
    """Human rendering of a flight-recorder crash dump
    (``observability.flight.FlightRecorder.dump``): the post-mortem
    header (reason, pid, exception), the last-N event trail, then the
    metrics snapshot through the normal grouped renderer."""
    from .flight import FLIGHT_DUMP_KIND

    if not isinstance(d, dict) or d.get("kind") != FLIGHT_DUMP_KIND:
        raise ValueError("not a flight-recorder dump: kind != "
                         f"{FLIGHT_DUMP_KIND!r}")
    reason = d.get("reason", "?")
    lines = [f"FLIGHT RECORDER DUMP — reason: {reason}",
             f"pid {d.get('pid', '?')}  generated "
             + time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(_as_num(d.get("generated_unix",
                                                          0))))]
    ctx = d.get("context")
    if ctx:
        # the exemplars payload (slo_breach dumps) is a span-tree bundle
        # and the window payload (health_alert dumps) is a point list,
        # not scalars — both render as their own blocks below the header
        lines.append("context: " + "  ".join(
            f"{k}={v}" for k, v in sorted(ctx.items())
            if k not in ("exemplars", "window")))
    # elastic-training post-mortems get a one-line interpretation so an
    # operator triaging a directory of per-worker dumps reads the story
    # without knowing the reason vocabulary
    if reason == "peer_death":
        lines.append(
            "(a peer worker's elastic heartbeat went stale; this worker "
            "dumped and exited for the coordinated restart)")
    elif reason == "rejoin":
        lines.append(
            "(this worker re-rendezvoused at a new generation and "
            "resumed from the latest checkpoint)")
    elif reason == "straggler":
        lines.append(
            "(the fleet aggregator flagged this rank as a persistent "
            "straggler — its recent step times exceeded the peer median "
            "threshold — and requested this post-mortem via the store "
            "flag)")
    elif reason == "slo_breach":
        lines.append(
            "(a serving SLO rule latched out of bounds — the context "
            "names the rule/value/threshold, and the tail exemplars "
            "below carry the span trees of the worst requests behind "
            "the breached percentile)")
        ex = (ctx or {}).get("exemplars")
        if ex:
            from .tracing import TailExemplars

            t = TailExemplars(ex.get("n", 4),
                              engine=(ctx or {}).get("engine", "?"))
            t.worst_ttft = list(ex.get("worst_ttft") or [])
            t.worst_latency = list(ex.get("worst_latency") or [])
            lines += ["", t.render()]
    elif reason == "health_alert":
        lines.append(
            "(a continuous-health detector latched — the context names "
            "the rule/series/code and the offending series window below "
            "shows the drift/leak trajectory that tripped it)")
        window = (ctx or {}).get("window")
        if window:
            lines += [""] + render_trend_table(
                {str((ctx or {}).get("series", "series")): window},
                title="Offending window")
    mem = d.get("device_memory")
    if mem:
        lines.append(
            "device memory: "
            f"in_use={_fmt_bytes(mem.get('bytes_in_use', 0))} "
            f"watermark={_fmt_bytes(mem.get('watermark_bytes', 0))} "
            f"limit={_fmt_bytes(mem.get('bytes_limit', 0))}")
    exc = d.get("exception")
    if exc:
        lines += ["", f"exception: {exc.get('type')}: {exc.get('message')}"]
        tb = exc.get("traceback") or []
        lines += [ln.rstrip("\n") for ln in tb]
    lines.append("")
    ev_lines = _render_events(d.get("events", []), max_events)
    lines += ev_lines if ev_lines else ["(empty event ring)"]
    if d.get("metrics"):
        lines += ["", render_report({"metrics": d["metrics"]},
                                    max_events=0, top=top)]
    return "\n".join(lines)


def summary(max_events: int = 20, top: Optional[int] = None) -> str:
    """Human-readable table over the live registry."""
    return render_report(dump_dict(), max_events=max_events, top=top)
