"""Per-op execution profiler: measured-time attribution for Program replay.

The static-analysis plane predicts per-op cost (PR 15's FLOPs/bytes
model, PR 16's comm model and predicted step time); until now the
observability plane only *measured* at whole-step granularity
(``train.step_seconds``, ``train.mfu``) — so PTL302/PTL304 drift alarms
could say "the model is off" but never WHICH op is off. This module is
the measurement half of that loop: an env-gated
(``PADDLE_TPU_OPPROF``) op-level profiling mode that replays a captured
``Program`` op by op, bracketing every instruction with an
injectable-clock timer and blocking on device results
(``jax.block_until_ready``) so timings are honest under async dispatch.

Span discipline: consecutive op spans SHARE boundaries (one clock read
per boundary, the ``tracing.RequestTrace`` transition rule), and the
feed-bind / fetch-gather phases get pseudo-spans of their own — so the
spans tile ``[step_start, step_end]`` exactly by construction and
attribution is loss-free. A profile whose spans do NOT tile its step
(a truncated dump, an outer step measurement, a profiler bug) is
exactly what PTL502 exists to catch.

Three consumers close the predicted-vs-measured loop:

- **Attribution** (:func:`attribute_profile`): joins the measured
  timeline against ``static/analysis/cost.py`` per-op FLOPs/bytes to
  produce, per op, achieved FLOP/s and bytes/s, roofline position
  against :func:`~paddle_tpu.observability.runtime.default_peak_flops`,
  and the measured/predicted drift ratio the PTL501 hot-op lint reads.
- **Calibration** (:func:`calibrate_op_costs`): per-op-class correction
  factors (measured seconds / predicted seconds per prim, plus a
  whole-program FLOPs factor against XLA's compiled count), persisted
  to JSON (:func:`save_op_calibration`) and consumed by
  ``cost.program_cost`` via the ``PADDLE_TPU_OP_CALIBRATION`` env (the
  ``PADDLE_TPU_COMM_PARAMS`` convention) — so PTL302/PTL304 drift
  tightens from measurement instead of hand-tuning.
- **Chrome-trace export**: the per-op timeline rides the shared
  ``observability.chrome`` exporter, so it is
  ``fleet.merge_chrome_trace_files``-compatible (multi-rank training
  steps render per-rank op lanes next to PR 17's serve lanes), with
  ``RecordEvent`` spans from the legacy ``paddle_tpu/profiler`` package
  mirrored into the same timeline: each profiled op is bracketed in a
  ``RecordEvent`` (so an active legacy host tracer sees the ops), and
  collected host spans can be handed back to
  :meth:`OpProfiler.chrome_trace_events` as an extra lane.

Cost control: an op-by-op replay with per-op blocking is far slower
than the fused jit step, so the Executor hook SAMPLES. With
``PADDLE_TPU_OPPROF_STRIDE=N`` every Nth run is profiled; by default
(budget pacing) the profiler waits after each profiled step until
enough unprofiled wall time has passed that the amortized overhead
stays under ``PADDLE_TPU_OPPROF_BUDGET_PCT`` (default 5%).
:func:`check_opprof_overhead` is the guard on that promise — the
PTL402 analog, filing **PTL503** when the measured steps/sec budget is
exceeded (``bench.py --opprof`` runs it).

Diagnostics this module emits: PTL501 (hot-op drift), PTL502
(attribution shortfall), PTL503 (profiling overhead exceeded) — see
:data:`OPPROF_CODES`, audited by ``tools/lint_registry.py``.
"""
from __future__ import annotations

import collections
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import chrome
from .metrics import registry
from .runtime import default_peak_flops

__all__ = [
    "OpSpan", "OpProfile", "OpProfiler", "OpCalibration",
    "attribute_profile", "calibrate_op_costs", "save_op_calibration",
    "load_op_calibration", "resolve_op_calibration", "lint_op_profile",
    "check_opprof_overhead", "render_op_profile",
    "opprof_enabled_from_env", "active_session", "session",
    "reset_session", "OPPROF_ENV", "OPPROF_STRIDE_ENV",
    "OPPROF_BUDGET_ENV", "OP_CALIBRATION_ENV", "OPPROF_CODES",
]

OPPROF_ENV = "PADDLE_TPU_OPPROF"
OPPROF_STRIDE_ENV = "PADDLE_TPU_OPPROF_STRIDE"
OPPROF_BUDGET_ENV = "PADDLE_TPU_OPPROF_BUDGET_PCT"
#: inline JSON or a file path, the PADDLE_TPU_COMM_PARAMS convention
OP_CALIBRATION_ENV = "PADDLE_TPU_OP_CALIBRATION"

#: diagnostic codes this module emits (documented in
#: static/analysis/diagnostics.py:CODES; audited by tools/lint_registry.py)
OPPROF_CODES = ("PTL501", "PTL502", "PTL503")

#: default amortized-overhead budget (percent of steps/sec) the pacer
#: targets and PTL503 enforces
DEFAULT_BUDGET_PCT = 5.0

#: the __gradients__ pseudo-op (static/analysis/verify.GRAD_OP) — the
#: one instruction the profiled interpreter replays via jax.grad of the
#: forward sub-replay, timed as a single named span
_GRAD_OP = "__gradients__"

#: pseudo-span names for the non-op phases that complete the step tiling
_FEED_SPAN = "__feed__"
_FETCH_SPAN = "__fetch__"

# --- opprof. metric subsystem (prefix claimed in CLAIMED_SUBSYSTEMS) ---
M_STEPS_PROFILED = registry.counter(
    "opprof.steps_profiled",
    "Program replays executed under the op-by-op profiled interpreter, "
    "by profile name")
M_STEPS_SKIPPED = registry.counter(
    "opprof.steps_skipped",
    "Executor runs the opprof pacer let ride the fused jit path while "
    "profiling was enabled (stride/budget sampling), by profile name")
M_OP_SECONDS = registry.histogram(
    "opprof.op_seconds",
    "measured wall seconds per profiled step attributed to one "
    "primitive class, by profile name and prim — the per-op truth the "
    "cost-model calibration fits against")
M_STEP_SECONDS = registry.histogram(
    "opprof.step_seconds",
    "wall seconds of one profiled (eager, per-op-blocking) step, by "
    "profile name — NOT comparable to train.step_seconds of the fused "
    "jit step; the pacer amortizes the difference")
M_ATTRIBUTED = registry.gauge(
    "opprof.attributed_pct",
    "percent of the last profiled step's wall time covered by named op "
    "spans, by profile name (PTL502 fires when it falls below the "
    "attribution floor)")
M_OVERHEAD = registry.gauge(
    "opprof.overhead_pct",
    "steps/sec cost of profiling: 100*(off-on)/off at the pacer's "
    "sampling rate, by profile name (PTL503 above tolerance — the "
    "PTL402 analog for the training plane)")
M_DRIFT = registry.gauge(
    "opprof.drift_ratio",
    "measured/predicted seconds per primitive class from the last "
    "attributed profile, by profile name and prim (the per-op "
    "decomposition of PTL302/PTL304 whole-program drift)")


def opprof_enabled_from_env() -> bool:
    """True when ``PADDLE_TPU_OPPROF`` opts Executor.run into op-level
    profiling."""
    return os.environ.get(OPPROF_ENV, "").strip().lower() not in (
        "", "0", "false", "no", "off")


# ---------------------------------------------------------------------------
# profile data model
# ---------------------------------------------------------------------------

@dataclass
class OpSpan:
    """One timed instruction (or pseudo-phase) of a profiled replay.

    ``index`` is the instruction index in ``Program._insts`` (None for
    the ``__feed__``/``__fetch__`` pseudo-phases). Consecutive spans
    share boundaries — ``end`` of op N is ``start`` of op N+1."""

    index: Optional[int]
    prim: str
    start: float
    end: float

    @property
    def seconds(self) -> float:
        return max(self.end - self.start, 0.0)

    def to_dict(self) -> Dict[str, Any]:
        return {"index": self.index, "prim": self.prim,
                "start": round(self.start, 9), "end": round(self.end, 9),
                "seconds": round(self.seconds, 9)}


@dataclass
class OpProfile:
    """The measured timeline of ONE profiled step."""

    name: str
    step_start: float
    step_end: float
    spans: List[OpSpan] = field(default_factory=list)
    fingerprint: Optional[str] = None
    #: attribution join output (attribute_profile): one row per op span
    rows: Optional[List[Dict[str, Any]]] = None
    #: the cost model's whole-step prediction, copied at join time
    predicted_step_seconds: Optional[float] = None

    @property
    def step_seconds(self) -> float:
        return max(self.step_end - self.step_start, 0.0)

    @property
    def attributed_seconds(self) -> float:
        return sum(s.seconds for s in self.spans)

    @property
    def attributed_pct(self) -> float:
        step = self.step_seconds
        if step <= 0:
            return 100.0
        return 100.0 * min(self.attributed_seconds / step, 1.0)

    def seconds_by_prim(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for s in self.spans:
            out[s.prim] = out.get(s.prim, 0.0) + s.seconds
        return out

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "fingerprint": self.fingerprint,
            "step_start": round(self.step_start, 9),
            "step_end": round(self.step_end, 9),
            "step_seconds": round(self.step_seconds, 9),
            "attributed_pct": round(self.attributed_pct, 3),
            "spans": [s.to_dict() for s in self.spans],
        }
        if self.rows is not None:
            d["rows"] = [dict(r) for r in self.rows]
        if self.predicted_step_seconds is not None:
            d["predicted_step_seconds"] = round(
                self.predicted_step_seconds, 9)
        return d


# ---------------------------------------------------------------------------
# the profiler
# ---------------------------------------------------------------------------

class _Pacer:
    """Decides which Executor runs pay for a profiled (eager) step.

    Stride mode (``stride=N``): every Nth run. Budget mode (default):
    after a profiled step costing ``C`` wall seconds, skip until the
    wall time since then satisfies ``idle * budget/(100-budget) >= C``
    — i.e. the amortized overhead of the NEXT profile stays within the
    budget. The first run always profiles."""

    __slots__ = ("stride", "budget_frac", "runs", "last_cost", "last_end")

    def __init__(self, stride: Optional[int], budget_pct: float):
        self.stride = stride
        budget_pct = min(max(float(budget_pct), 0.1), 99.0)
        self.budget_frac = budget_pct / (100.0 - budget_pct)
        self.runs = 0
        self.last_cost: Optional[float] = None
        self.last_end = 0.0

    def should_profile(self, now: float) -> bool:
        self.runs += 1
        if self.stride:
            return (self.runs - 1) % self.stride == 0
        if self.last_cost is None:
            return True
        return (now - self.last_end) * self.budget_frac >= self.last_cost

    def profiled(self, cost_seconds: float, end: float):
        self.last_cost = max(cost_seconds, 0.0)
        self.last_end = end


def _env_stride() -> Optional[int]:
    raw = os.environ.get(OPPROF_STRIDE_ENV, "").strip().lower()
    if not raw or raw == "auto":
        return None
    try:
        return max(int(raw), 1)
    except ValueError:
        return None


def _env_budget_pct() -> float:
    try:
        return float(os.environ.get(OPPROF_BUDGET_ENV, ""))
    except ValueError:
        return DEFAULT_BUDGET_PCT


class OpProfiler:
    """Op-level execution profiler for captured ``Program`` replays.

    ``clock`` is injectable (``FakeClock``) for deterministic tests;
    the default is ``time.perf_counter`` — the same clock the legacy
    host tracer's ``perf_counter_ns`` ticks on, so mirrored
    ``RecordEvent`` spans line up in the merged chrome timeline.
    Retention is bounded: the last ``max_profiles`` profiles ride a
    ring; everything else is exported (metrics, dumps) as it happens.
    """

    def __init__(self, *, name: str = "program", clock=None,
                 stride: Optional[int] = None,
                 budget_pct: Optional[float] = None,
                 max_profiles: int = 16, attribute: bool = True):
        self.name = str(name)
        self.clock = clock if clock is not None else time.perf_counter
        stride = _env_stride() if stride is None else max(int(stride), 1)
        budget = _env_budget_pct() if budget_pct is None else budget_pct
        self.pacer = _Pacer(stride, budget)
        self.attribute = attribute
        self.profiles: collections.deque = collections.deque(
            maxlen=max(1, int(max_profiles)))
        self.last: Optional[OpProfile] = None
        self.steps_profiled = 0
        self._cost_cache: Dict[Any, Any] = {}

    # -- profiled interpreter ---------------------------------------------
    def run_program(self, program, feed_names, feed_arrays, fetch_vids,
                    *, name: Optional[str] = None):
        """Eager op-by-op replay of ``program`` mirroring
        ``Executor._compile``'s jit closure, with every instruction
        timed (shared-boundary spans) and blocked on
        (``jax.block_until_ready``) so async dispatch cannot smear one
        op's time into the next. Returns ``(fetch_values, OpProfile)``.

        Each op is also bracketed in a legacy ``profiler.RecordEvent``
        — free when the host tracer is disabled, and when a
        ``profiler.Profiler`` window is recording, the op spans land in
        ITS chrome export too (the mirror the reference host tracer
        keeps between its tracer layers)."""
        import jax

        from ..core import dispatch
        from ..profiler.host_tracer import TracerEventType
        from ..profiler.utils import RecordEvent
        from ..static.program import _ReplaySnapshot, _replay_gradients

        name = name or self.name
        snap = program if isinstance(program, _ReplaySnapshot) \
            else _ReplaySnapshot(program)
        clock = self.clock
        spans: List[OpSpan] = []
        rec_step = RecordEvent("opprof.step",
                               TracerEventType.ProfileStep)
        rec_step.begin()
        try:
            t = step_start = clock()
            env: Dict[int, Any] = dict(snap._consts)
            for n, a in zip(feed_names, feed_arrays):
                env[snap._feed_names[n]] = a
            t2 = clock()
            spans.append(OpSpan(None, _FEED_SPAN, t, t2))
            t = t2
            for idx, (prim_name, in_vids, static_items, out_vids) in \
                    enumerate(snap._insts):
                rec = RecordEvent(prim_name, TracerEventType.Operator)
                rec.begin()
                try:
                    if prim_name == _GRAD_OP:
                        grads = _replay_gradients(
                            snap, idx, in_vids[0], in_vids[1:], env)
                        jax.block_until_ready(grads)
                        for v, g in zip(out_vids, grads):
                            env[v] = g
                    else:
                        prim = dispatch.PRIMITIVES[prim_name]
                        outs = prim.forward(*[env[v] for v in in_vids],
                                            **dict(static_items))
                        outs = outs if isinstance(outs, tuple) \
                            else (outs,)
                        jax.block_until_ready(outs)
                        for v, o in zip(out_vids, outs):
                            env[v] = o
                finally:
                    rec.end()
                t2 = clock()
                spans.append(OpSpan(idx, prim_name, t, t2))
                t = t2
            fetch = [env[v] for v in fetch_vids]
            jax.block_until_ready(fetch)
            t2 = clock()
            spans.append(OpSpan(None, _FETCH_SPAN, t, t2))
            step_end = t2
        finally:
            rec_step.end()

        profile = OpProfile(
            name=name, step_start=step_start, step_end=step_end,
            spans=spans,
            fingerprint=program.fingerprint()
            if hasattr(program, "fingerprint") else None)
        M_STEPS_PROFILED.inc(name=name)
        M_STEP_SECONDS.observe(profile.step_seconds, name=name)
        for prim, sec in profile.seconds_by_prim().items():
            M_OP_SECONDS.observe(sec, name=name, prim=prim)
        M_ATTRIBUTED.set(round(profile.attributed_pct, 2), name=name)
        if self.attribute:
            self._attribute(program, fetch_vids, profile)
        self.profiles.append(profile)
        self.last = profile
        self.steps_profiled += 1
        return fetch, profile

    def _attribute(self, program, fetch_vids, profile: OpProfile):
        """Join the measured timeline against the static cost model —
        best-effort: a program the cost model cannot walk still gets a
        valid (rows-less) profile."""
        try:
            cost = self._program_cost(program, fetch_vids)
        except Exception:
            return
        if cost is not None:
            attribute_profile(profile, cost)

    def _program_cost(self, program, fetch_vids):
        from ..static.analysis.cost import program_cost

        if not hasattr(program, "fingerprint"):
            return None
        key = (program.fingerprint(), tuple(fetch_vids))
        cost = self._cost_cache.get(key)
        if cost is None:
            cost = program_cost(program, fetch_vids or None)
            self._cost_cache[key] = cost
            while len(self._cost_cache) > 8:
                self._cost_cache.pop(next(iter(self._cost_cache)))
        return cost

    # -- Executor.run sampling hook ---------------------------------------
    def maybe_profiled_run(self, program, feed_names, feed_arrays,
                           fetch_vids, *, name: Optional[str] = None):
        """The Executor.run entry point: profile this run (returning the
        fetch values) or return None — caller falls through to the
        fused jit path. Pacing (stride or overhead budget) decides."""
        if not self.pacer.should_profile(self.clock()):
            M_STEPS_SKIPPED.inc(name=name or self.name)
            return None
        t0 = self.clock()
        outs, _profile = self.run_program(program, feed_names,
                                          feed_arrays, fetch_vids,
                                          name=name)
        t1 = self.clock()
        # the pacer amortizes the FULL profiled-run cost, attribution
        # join included — that is the wall time the jit path did not get
        self.pacer.profiled(t1 - t0, t1)
        return outs

    # -- exports -----------------------------------------------------------
    def chrome_trace_events(self, pid: int = 0, host_events=None
                            ) -> List[Dict[str, Any]]:
        """Chrome ``traceEvents`` through the shared
        ``observability.chrome`` exporter: tid 0 carries the per-op
        spans of every retained profile, tid 1 (when ``host_events`` —
        legacy ``profiler`` HostEvent roots — are handed in) mirrors
        the ``RecordEvent`` span tree into the same timeline.
        ``fleet.merge_chrome_trace_files`` re-maps pid per rank."""
        evs = [chrome.process_name_event(pid, f"opprof:{self.name}"),
               chrome.thread_name_event(pid, 0, "program ops")]
        for step_i, profile in enumerate(self.profiles):
            for s in profile.spans:
                args: Dict[str, Any] = {"step": step_i}
                if s.index is not None:
                    args["op"] = s.index
                evs.append(chrome.complete_event(
                    s.prim, s.start, s.end, cat="opprof", pid=pid,
                    tid=0, args=args))
        if host_events:
            from ..profiler.host_tracer import flatten_events

            evs.append(chrome.thread_name_event(
                pid, 1, "host spans (profiler.RecordEvent)"))
            for ev in flatten_events(list(host_events)):
                evs.append(chrome.complete_event(
                    ev.name, ev.start_ns / 1e9, ev.end_ns / 1e9,
                    cat=ev.type, pid=pid, tid=1,
                    args={"thread": ev.thread_id}))
        return evs

    def chrome_trace_dict(self, pid: int = 0, host_events=None
                          ) -> Dict[str, Any]:
        return chrome.trace_dict(
            self.chrome_trace_events(pid, host_events=host_events))

    def write_chrome_trace(self, path: str, pid: int = 0,
                           host_events=None) -> str:
        return chrome.write_chrome_trace(
            path, self.chrome_trace_dict(pid, host_events=host_events))

    def dump_dict(self) -> Dict[str, Any]:
        return {
            "kind": "opprof",
            "version": 1,
            "name": self.name,
            "steps_profiled": self.steps_profiled,
            "profiles": [p.to_dict() for p in self.profiles],
        }

    def dump(self, path: str) -> str:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.dump_dict(), f, indent=1, default=str)
        os.replace(tmp, path)
        return path


# ---------------------------------------------------------------------------
# process session (the Executor.run gate)
# ---------------------------------------------------------------------------

_session: Optional[OpProfiler] = None


def session(**kwargs) -> OpProfiler:
    """Get-or-create the process-wide profiler the Executor hook uses.
    Keyword args only apply on creation."""
    global _session
    if _session is None:
        _session = OpProfiler(**kwargs)
    return _session


def active_session() -> Optional[OpProfiler]:
    """The installed session, else a fresh one when ``PADDLE_TPU_OPPROF``
    is set, else None — the one check Executor.run pays per run."""
    if _session is not None:
        return _session
    if opprof_enabled_from_env():
        return session(name="executor")
    return None


def reset_session():
    """Drop the process profiler (tests; also re-reads env on next use)."""
    global _session
    _session = None


# ---------------------------------------------------------------------------
# attribution: join measured spans with the static cost model
# ---------------------------------------------------------------------------

def attribute_profile(profile: OpProfile, cost, *,
                      peak_flops: Optional[float] = None
                      ) -> List[Dict[str, Any]]:
    """Join ``profile``'s measured spans against a
    ``cost.ProgramCost`` (aligned by instruction index) to produce, per
    op: achieved FLOP/s and bytes/s, roofline position against
    ``default_peak_flops``, and the measured/predicted drift ratio.
    Fills ``profile.rows``/``profile.predicted_step_seconds`` and
    publishes the per-prim ``opprof.drift_ratio`` gauges."""
    peak = peak_flops if peak_flops else default_peak_flops()
    step = profile.step_seconds
    by_op = list(getattr(cost, "by_op", ()) or ())
    sec_by_op = list(getattr(cost, "seconds_by_op", ()) or ())
    rows: List[Dict[str, Any]] = []
    meas_by_prim: Dict[str, float] = {}
    pred_by_prim: Dict[str, float] = {}
    for s in profile.spans:
        if s.index is None:
            continue
        c = by_op[s.index] if s.index < len(by_op) else None
        flops = int(getattr(c, "flops", 0) or 0)
        nbytes = int(getattr(c, "bytes_total", 0) or 0)
        pred = float(sec_by_op[s.index]) \
            if s.index < len(sec_by_op) else 0.0
        meas = s.seconds
        achieved_flops = flops / meas if meas > 0 else 0.0
        rows.append({
            "index": s.index,
            "prim": s.prim,
            "measured_seconds": round(meas, 9),
            "predicted_seconds": round(pred, 9),
            "flops": flops,
            "bytes": nbytes,
            "achieved_flops_per_sec": round(achieved_flops, 3),
            "achieved_bytes_per_sec": round(
                nbytes / meas if meas > 0 else 0.0, 3),
            "roofline_pct": round(100.0 * achieved_flops / peak, 8),
            "drift_ratio": round(meas / pred, 6) if pred > 0 else None,
            "share_pct": round(100.0 * meas / step, 3)
            if step > 0 else 0.0,
        })
        meas_by_prim[s.prim] = meas_by_prim.get(s.prim, 0.0) + meas
        pred_by_prim[s.prim] = pred_by_prim.get(s.prim, 0.0) + pred
    profile.rows = rows
    pred_step = getattr(cost, "predicted_step_seconds", None)
    if pred_step:
        profile.predicted_step_seconds = float(pred_step)
    for prim, meas in meas_by_prim.items():
        pred = pred_by_prim.get(prim, 0.0)
        if pred > 0:
            M_DRIFT.set(round(meas / pred, 4), name=profile.name,
                        prim=prim)
    return rows


# ---------------------------------------------------------------------------
# calibration: correction factors program_cost consumes
# ---------------------------------------------------------------------------

@dataclass
class OpCalibration:
    """Per-op-class correction factors fitted from a measured profile.

    ``factors`` maps a prim name to ``measured_seconds /
    predicted_seconds`` over the profile's ops of that class — applied
    multiplicatively to the cost model's per-op time base.
    ``flops_factor`` is the whole-program ``measured_flops /
    predicted_flops`` ratio against XLA's compiled cost analysis (1.0
    when no measured count was supplied). Unknown keys in a loaded dict
    are ignored (forward compatibility, the CommModelParams rule)."""

    factors: Dict[str, float] = field(default_factory=dict)
    flops_factor: float = 1.0
    source: Dict[str, Any] = field(default_factory=dict)

    def factor(self, prim: str, default: float = 1.0) -> float:
        return float(self.factors.get(prim, default))

    def is_identity(self) -> bool:
        return not self.factors and self.flops_factor == 1.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": "op_calibration",
            "version": 1,
            "flops_factor": round(float(self.flops_factor), 9),
            "factors": {k: round(float(v), 9)
                        for k, v in sorted(self.factors.items())},
            "source": dict(self.source),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "OpCalibration":
        factors = {str(k): float(v)
                   for k, v in (d.get("factors") or {}).items()
                   if float(v) > 0}
        try:
            flops_factor = float(d.get("flops_factor", 1.0))
        except (TypeError, ValueError):
            flops_factor = 1.0
        if not flops_factor > 0:
            flops_factor = 1.0
        return cls(factors=factors, flops_factor=flops_factor,
                   source=dict(d.get("source") or {}))


def calibrate_op_costs(profile: OpProfile, cost, *,
                       measured_flops: Optional[int] = None
                       ) -> OpCalibration:
    """Fit per-op-class correction factors from one measured profile.

    Per prim class: ``factor = sum(measured seconds) / sum(predicted
    base seconds)`` over the profiled ops of that class (classes the
    model predicts zero time for keep the identity factor). With
    ``measured_flops`` (XLA's compiled count for the same replay,
    ``cost.measure_program_flops``) the whole-program FLOPs ratio is
    fitted too, so the calibrated ``program_cost`` tightens PTL302 as
    well as PTL304."""
    sec_by_op = list(getattr(cost, "seconds_by_op", ()) or ())
    meas_by_prim: Dict[str, float] = {}
    pred_by_prim: Dict[str, float] = {}
    for s in profile.spans:
        if s.index is None:
            continue
        pred = float(sec_by_op[s.index]) \
            if s.index < len(sec_by_op) else 0.0
        meas_by_prim[s.prim] = meas_by_prim.get(s.prim, 0.0) + s.seconds
        pred_by_prim[s.prim] = pred_by_prim.get(s.prim, 0.0) + pred
    factors = {
        prim: meas / pred_by_prim[prim]
        for prim, meas in meas_by_prim.items()
        if pred_by_prim.get(prim, 0.0) > 0 and meas > 0
    }
    flops_factor = 1.0
    model_flops = int(getattr(cost, "flops", 0) or 0)
    if measured_flops and model_flops > 0:
        flops_factor = float(measured_flops) / model_flops
    return OpCalibration(
        factors=factors, flops_factor=flops_factor,
        source={"name": profile.name,
                "fingerprint": profile.fingerprint,
                "step_seconds": round(profile.step_seconds, 9),
                "ops": sum(1 for s in profile.spans
                           if s.index is not None)})


def save_op_calibration(cal: OpCalibration, path: str) -> str:
    """Persist a calibration to JSON (atomic; the file
    ``PADDLE_TPU_OP_CALIBRATION`` points at)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(cal.to_dict(), f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_op_calibration(path: str) -> OpCalibration:
    with open(path) as f:
        return OpCalibration.from_dict(json.load(f))


def resolve_op_calibration(value=None) -> Optional[OpCalibration]:
    """Resolve a ``program_cost(op_calibration=...)`` argument: an
    :class:`OpCalibration` passes through, a dict/JSON-string/path is
    parsed, and None consults ``PADDLE_TPU_OP_CALIBRATION`` (inline
    JSON if it starts with ``{``, else a file path — the
    ``PADDLE_TPU_COMM_PARAMS`` convention). Returns None (identity —
    the exact uncalibrated behavior) when nothing usable is found;
    never raises on a malformed source."""
    if isinstance(value, OpCalibration):
        return value
    if isinstance(value, dict):
        try:
            return OpCalibration.from_dict(value)
        except Exception:
            return None
    raw = value if isinstance(value, str) \
        else os.environ.get(OP_CALIBRATION_ENV, "")
    raw = raw.strip()
    if not raw:
        return None
    try:
        if raw.startswith("{"):
            return OpCalibration.from_dict(json.loads(raw))
        return load_op_calibration(raw)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# lints (PTL501/PTL502) + overhead guard (PTL503)
# ---------------------------------------------------------------------------

def _profile_doc(profile) -> Dict[str, Any]:
    return profile.to_dict() if isinstance(profile, OpProfile) \
        else dict(profile)


def lint_op_profile(profile, *, drift_tolerance_pct: float = 200.0,
                    hot_share_pct: float = 10.0,
                    attribution_floor_pct: float = 95.0):
    """Lint one profile (an :class:`OpProfile` or its ``to_dict()``/
    JSON form):

    - **PTL501** hot-op drift: an op holding at least ``hot_share_pct``
      of the step whose measured time diverges from the cost model's
      prediction by more than ``drift_tolerance_pct`` — the per-op
      decomposition of a PTL302/PTL304 whole-program alarm, naming the
      op to fix (suggestion payload carries prim/measured/predicted).
    - **PTL502** attribution shortfall: the spans fail to tile the step
      (named-span coverage below ``attribution_floor_pct``) — the
      profile cannot be trusted to attribute the step it claims to
      measure."""
    from ..static.analysis.diagnostics import (DiagnosticReport,
                                               Severity)

    doc = _profile_doc(profile)
    report = DiagnosticReport()
    name = doc.get("name", "program")
    step = float(doc.get("step_seconds") or 0.0)
    attributed = doc.get("attributed_pct")
    if attributed is not None and attributed < attribution_floor_pct:
        unattributed_ms = step * (100.0 - attributed) / 100.0 * 1e3
        report.add(
            "PTL502", Severity.WARNING,
            f"profile {name!r}: op spans cover only {attributed:.1f}% "
            f"of the {step * 1e3:.2f} ms step "
            f"({unattributed_ms:.2f} ms unattributed, floor "
            f"{attribution_floor_pct:.0f}%)",
            hint="the profiled interpreter tiles the step by "
                 "construction (shared span boundaries) — a shortfall "
                 "means a truncated dump, an outer step measurement, "
                 "or a profiler bug; do not calibrate from this "
                 "profile",
            suggestion={"attributed_pct": attributed,
                        "floor_pct": attribution_floor_pct})
    for row in doc.get("rows") or ():
        pred = float(row.get("predicted_seconds") or 0.0)
        share = float(row.get("share_pct") or 0.0)
        if pred <= 0 or share < hot_share_pct:
            continue
        meas = float(row.get("measured_seconds") or 0.0)
        err_pct = abs(meas - pred) / pred * 100.0
        if err_pct > drift_tolerance_pct:
            report.add(
                "PTL501", Severity.WARNING,
                f"hot op drift in {name!r}: {row.get('prim')} "
                f"({share:.1f}% of step) measured "
                f"{meas * 1e3:.3f} ms vs predicted "
                f"{pred * 1e3:.3f} ms ({err_pct:.0f}% > "
                f"{drift_tolerance_pct:.0f}% tolerance)",
                op_index=row.get("index"),
                hint="this op class, not the whole model, is what "
                     "drifted — fix its cost-registry entry or refit "
                     "with calibrate_op_costs (the factor lands on "
                     "exactly this prim)",
                suggestion={"prim": row.get("prim"),
                            "measured_seconds": meas,
                            "predicted_seconds": pred,
                            "drift_ratio": row.get("drift_ratio")})
    return report


def check_opprof_overhead(steps_per_sec_on: float,
                          steps_per_sec_off: float, *,
                          tolerance_pct: float = DEFAULT_BUDGET_PCT,
                          name: str = "program"):
    """The profiling-cost guard (PTL402's training-plane analog):
    steps/sec with op profiling enabled — at the pacer's sampling rate
    — must stay within ``tolerance_pct`` of profiling off. Publishes
    ``opprof.overhead_pct`` and files **PTL503** when the budget is
    exceeded (``bench.py --opprof`` runs this; a profiler that taxes
    the training loop is a profiler nobody leaves enabled)."""
    from ..static.analysis.diagnostics import (DiagnosticReport,
                                               Severity)

    report = DiagnosticReport()
    if steps_per_sec_off <= 0:
        return report
    overhead = 100.0 * (steps_per_sec_off - steps_per_sec_on) \
        / steps_per_sec_off
    M_OVERHEAD.set(round(overhead, 3), name=name)
    if overhead > tolerance_pct:
        report.add(
            "PTL503", Severity.WARNING,
            f"op-profiling overhead {overhead:.2f}% exceeds the "
            f"{tolerance_pct:.1f}% budget ({steps_per_sec_on:.3f} "
            f"steps/s profiled vs {steps_per_sec_off:.3f} unprofiled)",
            hint="the eager per-op-blocking replay is inherently "
                 "slower than the fused jit step — the pacer exists "
                 "to amortize it; raise PADDLE_TPU_OPPROF_STRIDE (or "
                 "lower PADDLE_TPU_OPPROF_BUDGET_PCT) so fewer steps "
                 "pay the eager price",
            suggestion={"overhead_pct": round(overhead, 3),
                        "tolerance_pct": tolerance_pct})
    return report


# ---------------------------------------------------------------------------
# rendering (tools/metrics_report.py --opprof)
# ---------------------------------------------------------------------------

def render_op_profile(doc: Dict[str, Any], *, top: int = 10) -> str:
    """Human report for one ``opprof`` dump (``OpProfiler.dump_dict()``
    JSON): header, then the top-K ops table of the LAST retained
    profile — measured ms, predicted ms, drift, roofline %, and the
    cumulative step share that says how much of the step the table
    explains."""
    if doc.get("kind") != "opprof":
        raise ValueError(f"not an opprof dump (kind={doc.get('kind')!r})")
    profiles = doc.get("profiles") or []
    lines = [f"op profile (name={doc.get('name')}): "
             f"{doc.get('steps_profiled', len(profiles))} step(s) "
             f"profiled, {len(profiles)} retained"]
    if not profiles:
        return "\n".join(lines + ["no profiled steps retained"])
    p = profiles[-1]
    pred = p.get("predicted_step_seconds")
    lines.append(
        f"last step: {float(p.get('step_seconds') or 0) * 1e3:.3f} ms "
        f"measured"
        + (f" vs {float(pred) * 1e3:.3f} ms predicted" if pred else "")
        + f", {p.get('attributed_pct')}% attributed "
        f"({len(p.get('spans') or [])} span(s))")
    rows = p.get("rows")
    if not rows:
        # un-joined profile (no cost model): aggregate spans by prim
        agg: Dict[str, float] = {}
        for s in p.get("spans") or ():
            agg[s["prim"]] = agg.get(s["prim"], 0.0) + s["seconds"]
        step = float(p.get("step_seconds") or 0.0)
        rows = [{"prim": prim, "index": None, "measured_seconds": sec,
                 "predicted_seconds": 0.0, "drift_ratio": None,
                 "roofline_pct": 0.0,
                 "share_pct": 100.0 * sec / step if step > 0 else 0.0}
                for prim, sec in agg.items()]
    rows = sorted(rows, key=lambda r: -float(r["measured_seconds"]))
    table = [("op", "prim", "meas ms", "pred ms", "drift", "roofline",
              "share", "cum")]
    cum = 0.0
    for r in rows[:max(top, 1)]:
        cum += float(r.get("share_pct") or 0.0)
        drift = r.get("drift_ratio")
        table.append((
            "-" if r.get("index") is None else f"#{r['index']}",
            str(r.get("prim")),
            f"{float(r['measured_seconds']) * 1e3:.3f}",
            f"{float(r.get('predicted_seconds') or 0) * 1e3:.3f}",
            "-" if drift is None else f"{float(drift):.2f}x",
            f"{float(r.get('roofline_pct') or 0):.2f}%",
            f"{float(r.get('share_pct') or 0):.1f}%",
            f"{cum:.1f}%"))
    widths = [max(len(t[i]) for t in table) for i in range(len(table[0]))]
    lines.append("")
    lines.extend(
        "  ".join(col.ljust(w) if i <= 1 else col.rjust(w)
                  for i, (col, w) in enumerate(zip(t, widths)))
        for t in table)
    if len(rows) > top:
        lines.append(f"  ... {len(rows) - top} more op(s)")
    return "\n".join(lines)
