"""Shared Chrome-trace (about:tracing / Perfetto) JSON emission.

One exporter for every timeline the repo produces — the serve tracer
(``tracing.ServeTracer``), the op-level execution profiler
(``opprof.OpProfiler``) and the legacy ``paddle_tpu/profiler`` host
spans all speak the same dialect, so
``observability.fleet.merge_chrome_trace_files`` can interleave them
per rank without per-producer special cases. The conventions this
module pins down (and the per-producer code must NOT re-invent):

- durations are "X" (complete) events with ``ts``/``dur`` in
  MICROSECONDS — producers hold seconds, the conversion lives here;
- ``pid`` is the process lane (re-mapped to the rank at fleet-merge
  time), ``tid`` the within-process lane (decode slot, op stream, ...);
- lanes are named by "M" metadata events (``process_name`` /
  ``thread_name``) so the viewer shows "serve:default / slot 3"
  instead of bare integers;
- files are the ``{"traceEvents": [...], "displayTimeUnit": "ms"}``
  envelope, written atomically (tmp + ``os.replace``) so a merge racing
  a writer never reads a torn file.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, Optional, Union

__all__ = [
    "complete_event", "process_name_event", "thread_name_event",
    "trace_dict", "write_chrome_trace",
]


def complete_event(name: str, start_seconds: float, end_seconds: float,
                   *, pid: int = 0, tid: int = 0, cat: str = "",
                   args: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """One "X" (complete) event: a named span on lane ``(pid, tid)``.

    Takes SECONDS on the producer's clock; the µs conversion the chrome
    format wants happens here and nowhere else."""
    return {
        "name": name, "ph": "X", "cat": cat,
        "pid": pid, "tid": tid,
        "ts": start_seconds * 1e6,
        "dur": (end_seconds - start_seconds) * 1e6,
        "args": dict(args) if args else {},
    }


def process_name_event(pid: int, name: str) -> Dict[str, Any]:
    """"M" metadata naming the ``pid`` lane (the per-rank process row)."""
    return {"ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": name}}


def thread_name_event(pid: int, tid: int, name: str) -> Dict[str, Any]:
    """"M" metadata naming the ``tid`` lane inside process ``pid``."""
    return {"ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
            "args": {"name": name}}


def trace_dict(events: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Wrap events in the standard chrome-trace envelope."""
    return {"traceEvents": list(events), "displayTimeUnit": "ms"}


def write_chrome_trace(path: str,
                       events_or_doc: Union[Iterable[Dict[str, Any]],
                                            Dict[str, Any]]) -> str:
    """Atomically write a chrome trace file.

    Accepts either a bare event list (wrapped via :func:`trace_dict`)
    or an already-enveloped document."""
    doc = events_or_doc if isinstance(events_or_doc, dict) \
        else trace_dict(events_or_doc)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path
