"""Request-lifecycle span tracing for the serving plane.

PR 14's ``ServeEngine`` reports aggregate histograms (``serve.ttft_seconds``
and friends); this module answers the question those cannot: *which*
request was slow and *why*. Every non-warmup request carries a span tree
(``Request.trace``) covering its full lifecycle —

    submit -> queue -> prefill(bucket) -> decode
                 ^                          |
                 +-- preempt <- ------------+   (pool exhausted)
                 |
                 +-> resume -> recompute -> decode -> ... -> finish

— where consecutive phases share boundaries (each transition closes the
open phase at the same timestamp that opens the next), so the leaf
durations sum to the request's total latency by construction and the
per-phase breakdown attributes ~100% of TTFT and latency to named
phases. All hooks are host-side bookkeeping on the engine's scheduler
path: nothing touches the compiled decode step, so ``serve.decode_traces``
stays pinned at 1 with tracing enabled.

Three consumers sit on top:

- **Chrome-trace export** (:meth:`ServeTracer.chrome_trace_dict`): one
  lane per decode slot plus a queue-wait lane and an engine lane of
  batched decode steps, in the same ``{"traceEvents": [...]}`` format as
  the profiler and fleet traces — ``observability.fleet.
  merge_chrome_trace_files`` merges serve timelines next to training
  ranks, and ``tools/metrics_report.py --serve-trace`` renders them.
- **Tail exemplars** (:class:`TailExemplars`): the N worst-TTFT and
  worst-latency requests keep their full span trees with a per-phase
  breakdown ("p99 request spent 82% in queue"), attached to SLO-breach
  flight dumps by ``observability/slo.py``.
- **Decode-gap accounting**: host-side time between consecutive decode
  steps while slots were runnable (``trace.decode_gap_seconds``) — the
  signal behind the ROADMAP's fused-decode item, linted as PTL404 by
  ``static/analysis/serve_trace_lint.py``.

Enablement: ``ServeEngine(trace=True)`` or ``PADDLE_TPU_TRACE=1``. The
tracer records through plain metric objects (always live once
constructed) because construction itself is the opt-in; overhead is
guarded by :func:`check_tracing_overhead` (PTL402, ``bench.py`` serve
config). PTL403 (:func:`validate_trace`) covers malformed trees.
"""
from __future__ import annotations

import bisect
import collections
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import chrome
from .metrics import registry

__all__ = [
    "Span", "RequestTrace", "ServeTracer", "TailExemplars",
    "validate_trace", "check_tracing_overhead", "render_phase_table",
    "render_serve_trace", "trace_enabled_from_env", "TRACE_ENV",
    "TRACE_CODES", "PHASES",
]

TRACE_ENV = "PADDLE_TPU_TRACE"

#: diagnostic codes this module emits (documented in
#: static/analysis/diagnostics.py:CODES; audited by tools/lint_registry.py)
TRACE_CODES = ("PTL402", "PTL403")

#: leaf phase names a request span tree is built from, in lifecycle order
PHASES = ("queue", "prefill", "decode", "preempt", "resume", "recompute")

#: wait phases live on the queue lane of the Chrome export; the rest on
#: the slot lane the request occupied
_WAIT_PHASES = ("queue", "preempt")

# --- trace. metric subsystem (prefix claimed in CLAIMED_SUBSYSTEMS) ----
M_REQUESTS_TRACED = registry.counter(
    "trace.requests_traced",
    "finished requests that carried a full span tree")
M_SPANS = registry.counter(
    "trace.spans_recorded", "leaf lifecycle spans closed, by phase "
    "(queue/prefill/decode/preempt/resume/recompute)")
M_PHASE_SECONDS = registry.histogram(
    "trace.phase_seconds",
    "per-request wall seconds spent in each lifecycle phase — the "
    "distribution behind the tail-attribution table")
M_DECODE_GAP = registry.gauge(
    "trace.decode_gap_seconds",
    "cumulative host-side gap between consecutive decode steps while "
    "slots were runnable (the fused-decode opportunity; PTL404)")
M_EXEMPLARS = registry.gauge(
    "trace.exemplars_kept",
    "tail exemplar span trees currently retained, by kind "
    "(ttft / latency)")
M_MALFORMED = registry.counter(
    "trace.spans_malformed",
    "span-tree validation findings (PTL403), by reason")
M_OVERHEAD = registry.gauge(
    "trace.overhead_pct",
    "tokens/sec cost of tracing: 100*(off-on)/off measured by the "
    "bench tracing-overhead guard (PTL402 above tolerance)")


def trace_enabled_from_env() -> bool:
    """True when ``PADDLE_TPU_TRACE`` opts serving engines into tracing."""
    return os.environ.get(TRACE_ENV, "").strip().lower() not in (
        "", "0", "false", "no", "off")


@dataclass
class Span:
    """One node of a request span tree: a named phase with wall-clock
    bounds on the engine's clock and free-form attributes (slot, prefill
    bucket, preemption reason, ...)."""

    name: str
    start: float
    end: Optional[float] = None
    attrs: Dict[str, Any] = field(default_factory=dict)
    children: List["Span"] = field(default_factory=list)

    @property
    def seconds(self) -> float:
        return 0.0 if self.end is None else max(self.end - self.start, 0.0)

    def close(self, t: float):
        if self.end is None:
            self.end = t

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "start": round(self.start, 9),
            "end": None if self.end is None else round(self.end, 9),
            "seconds": round(self.seconds, 9),
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class RequestTrace:
    """The span tree carried on one request (``Request.trace``).

    A ``request`` root span brackets submit->finish; leaf phases are its
    ordered children. Transitions are atomic — :meth:`begin_phase`
    closes the open phase at the timestamp that starts the next — so
    leaf durations tile the root exactly and attribution is loss-free.
    Every mutator is a no-op after :meth:`finish` (a late hook from the
    engine must not re-open a closed tree)."""

    __slots__ = ("request_id", "root", "open", "finished",
                 "first_token_time")

    def __init__(self, request_id: int, submit_time: float):
        self.request_id = request_id
        self.root = Span("request", submit_time)
        self.open: Optional[Span] = None
        self.finished = False
        self.first_token_time: Optional[float] = None

    def begin_phase(self, name: str, t: float, **attrs) -> Optional[Span]:
        if self.finished:
            return None
        if self.open is not None:
            self.open.close(t)
        s = Span(name, t, attrs=dict(attrs))
        self.root.children.append(s)
        self.open = s
        return s

    def annotate(self, **attrs):
        """Attach attributes to the currently open phase (e.g. the
        prefill bucket, known only once the padded shape is computed)."""
        if self.open is not None and not self.finished:
            self.open.attrs.update(attrs)

    def finish(self, t: float, reason: Optional[str] = None):
        if self.finished:
            return
        if self.open is not None:
            self.open.close(t)
            self.open = None
        self.root.close(t)
        if reason is not None:
            self.root.attrs["finish_reason"] = reason
        self.finished = True

    # -- attribution -------------------------------------------------------
    def phase_seconds(self) -> Dict[str, float]:
        """Total seconds per leaf phase name (a request can visit
        decode/preempt/resume/recompute several times)."""
        out: Dict[str, float] = {}
        for c in self.root.children:
            out[c.name] = out.get(c.name, 0.0) + c.seconds
        return out

    def attributed_seconds(self, upto: Optional[float] = None
                           ) -> Dict[str, float]:
        """Per-phase seconds clipped to ``[root.start, upto]`` — with
        ``upto=first_token_time`` this is the TTFT attribution."""
        if upto is None:
            return self.phase_seconds()
        out: Dict[str, float] = {}
        for c in self.root.children:
            end = upto if c.end is None else min(c.end, upto)
            ov = max(end - c.start, 0.0)
            if ov > 0:
                out[c.name] = out.get(c.name, 0.0) + ov
        return out


def _attributed_pct(breakdown: Dict[str, float], total: float
                    ) -> Optional[float]:
    if total is None or total <= 0:
        return None
    return round(100.0 * min(sum(breakdown.values()) / total, 1.0), 2)


class TailExemplars:
    """Keeps the N worst-TTFT and N worst-latency request span trees.

    ``offer()`` takes the finished-request doc the tracer builds; both
    lists stay sorted worst-first so the report reads p-worst down."""

    def __init__(self, n: int = 4, engine: str = "default"):
        self.n = max(1, int(n))
        self.engine = engine
        self.worst_ttft: List[Dict[str, Any]] = []
        self.worst_latency: List[Dict[str, Any]] = []

    def _insert(self, lst: List[Dict[str, Any]], doc: Dict[str, Any],
                key: str):
        v = doc.get(key)
        if v is None:
            return
        keys = [-(d[key]) for d in lst]
        lst.insert(bisect.bisect_right(keys, -v), doc)
        del lst[self.n:]

    def offer(self, doc: Dict[str, Any]):
        self._insert(self.worst_ttft, doc, "ttft_seconds")
        self._insert(self.worst_latency, doc, "latency_seconds")
        M_EXEMPLARS.set(len(self.worst_ttft), engine=self.engine,
                        kind="ttft")
        M_EXEMPLARS.set(len(self.worst_latency), engine=self.engine,
                        kind="latency")

    def to_dict(self) -> Dict[str, Any]:
        return {"n": self.n, "worst_ttft": list(self.worst_ttft),
                "worst_latency": list(self.worst_latency)}

    def render(self) -> str:
        lines = [f"tail exemplars (engine={self.engine}, "
                 f"keeping worst {self.n}):"]
        for title, lst, key, bkey in (
                ("worst TTFT", self.worst_ttft, "ttft_seconds",
                 "ttft_breakdown"),
                ("worst latency", self.worst_latency, "latency_seconds",
                 "breakdown")):
            lines.append(f"  {title}:")
            if not lst:
                lines.append("    (none)")
                continue
            for d in lst:
                total = d.get(key) or 0.0
                parts = sorted((d.get(bkey) or {}).items(),
                               key=lambda kv: -kv[1])
                split = ", ".join(
                    f"{name} {100 * sec / total:.0f}% ({sec * 1e3:.1f} ms)"
                    for name, sec in parts if total > 0)
                lines.append(
                    f"    req {d.get('id')}: {total * 1e3:.1f} ms "
                    f"[{d.get('preemptions', 0)} preemption(s)]"
                    + (f" — {split}" if split else ""))
        return "\n".join(lines)


class ServeTracer:
    """Request-scoped span tracer for one :class:`~paddle_tpu.serve.
    engine.ServeEngine` (the engine calls the ``on_*`` hooks from its
    scheduler path; all of them are host-side and O(1)).

    Retention is bounded: finished-request docs ride a ring
    (``max_requests``), decode-step records another (``max_decode_steps``),
    and only the tail exemplars keep full span trees indefinitely."""

    def __init__(self, engine: str = "default", clock=None, *,
                 max_slots: int = 0, exemplars: int = 4,
                 max_requests: int = 1024, max_decode_steps: int = 8192):
        import time as _time

        self.engine = str(engine)
        self._clock = clock if clock is not None else _time.perf_counter
        self.max_slots = int(max_slots)
        self.exemplars = TailExemplars(exemplars, engine=self.engine)
        self.requests: collections.deque = collections.deque(
            maxlen=max(1, int(max_requests)))
        self.decode_steps: collections.deque = collections.deque(
            maxlen=max(1, int(max_decode_steps)))
        self.total_decode_gap = 0.0
        self.n_traced = 0
        self._last_step_end: Optional[float] = None
        self._last_step_active = 0

    # -- engine hooks ------------------------------------------------------
    def on_submit(self, req):
        req.trace = RequestTrace(req.id, req.submit_time)
        req.trace.begin_phase("queue", req.submit_time)

    def on_admit(self, req, slot: int, resumed: bool):
        tr = req.trace
        if tr is None:
            return
        t = self._clock()
        if resumed:
            tr.begin_phase("resume", t, slot=slot,
                           preemptions=req.preemptions)
        else:
            tr.begin_phase("prefill", t, slot=slot)

    def on_prefill(self, req, bucket: int, tokens: int):
        tr = req.trace
        if tr is None:
            return
        if tr.open is not None and tr.open.name == "resume":
            # the re-prefill of prompt+generated after a preemption is
            # RECOMPUTE work, not first-time prefill — name it so the
            # breakdown bills eviction, not the prompt
            tr.begin_phase("recompute", self._clock(),
                           slot=req.slot, bucket=bucket, tokens=tokens)
        else:
            tr.annotate(bucket=bucket, tokens=tokens)

    def on_first_token(self, req, t: float):
        if req.trace is not None:
            req.trace.first_token_time = t

    def on_decode_begin(self, req):
        tr = req.trace
        if tr is None or tr.finished:
            return
        tr.begin_phase("decode", self._clock(), slot=req.slot)

    def on_preempt(self, req, reason: str = "pool_exhausted"):
        tr = req.trace
        if tr is None or tr.finished:
            return
        tr.begin_phase("preempt", self._clock(), reason=reason)

    def on_finish(self, req):
        tr = req.trace
        if tr is None:
            return
        tr.finish(req.finish_time, req.finish_reason)
        doc = self._request_doc(req)
        for c in tr.root.children:
            M_SPANS.inc(engine=self.engine, phase=c.name)
            M_PHASE_SECONDS.observe(c.seconds, engine=self.engine,
                                    phase=c.name)
        M_REQUESTS_TRACED.inc(engine=self.engine)
        self.n_traced += 1
        findings = validate_trace(doc)
        for d in findings:
            reason = (d.suggestion or {}).get("reason", "malformed")
            M_MALFORMED.inc(engine=self.engine, reason=reason)
        if findings.diagnostics:
            doc["malformed"] = [d.render() for d in findings]
        self.requests.append(doc)
        self.exemplars.offer(doc)

    def on_decode_step(self, start: float, end: float,
                       active_after: int, queued: int,
                       tokens: int = 1):
        """One batched decode dispatch on the engine lane — a single
        step, or a fused burst of ``tokens`` in-scan steps when the
        engine runs with ``decode_burst > 1`` (one host round-trip
        either way, which is exactly the point). The gap between the
        previous dispatch's end and this start, while the previous one
        left runnable slots behind, is host-side scheduler time the
        chip sat idle — the fused-decode opportunity PTL404 lints;
        bursts shrink the number of such gaps ~N x."""
        if self._last_step_end is not None and self._last_step_active > 0:
            gap = start - self._last_step_end
            if gap > 0:
                self.total_decode_gap += gap
                M_DECODE_GAP.set(round(self.total_decode_gap, 6),
                                 engine=self.engine)
        self._last_step_end = end
        self._last_step_active = int(active_after)
        self.decode_steps.append(
            {"start": round(start, 9), "end": round(end, 9),
             "active": int(active_after), "queued": int(queued),
             "tokens": int(tokens)})

    # -- per-request doc ---------------------------------------------------
    def _request_doc(self, req) -> Dict[str, Any]:
        tr = req.trace
        ttft = req.ttft
        latency = (None if req.finish_time is None
                   else req.finish_time - req.submit_time)
        breakdown = {k: round(v, 9)
                     for k, v in tr.phase_seconds().items()}
        ttft_breakdown = {
            k: round(v, 9)
            for k, v in tr.attributed_seconds(tr.first_token_time).items()}
        return {
            "id": req.id,
            "engine": self.engine,
            "submit": round(req.submit_time, 9),
            "finish": (None if req.finish_time is None
                       else round(req.finish_time, 9)),
            "finish_reason": req.finish_reason,
            "n_prompt": req.n_prompt,
            "n_generated": req.n_generated,
            "preemptions": req.preemptions,
            "ttft_seconds": None if ttft is None else round(ttft, 9),
            "latency_seconds": (None if latency is None
                                else round(latency, 9)),
            "breakdown": breakdown,
            "ttft_breakdown": ttft_breakdown,
            "ttft_attributed_pct": _attributed_pct(ttft_breakdown, ttft),
            "latency_attributed_pct": _attributed_pct(breakdown, latency),
            "spans": tr.root.to_dict(),
        }

    # -- exports -----------------------------------------------------------
    def _lane(self, span_dict: Dict[str, Any]) -> int:
        if span_dict["name"] in _WAIT_PHASES:
            return 0
        slot = (span_dict.get("attrs") or {}).get("slot")
        return 1 + int(slot) if slot is not None else 0

    def chrome_trace_events(self, pid: int = 0) -> List[Dict[str, Any]]:
        """Chrome ``traceEvents``: one lane (tid) per decode slot, a
        queue/preempt wait lane, and an engine lane of batched decode
        steps — built on the shared ``observability.chrome`` exporter,
        so the ts/dur µs conventions and lane metadata stay
        ``fleet.merge_chrome_trace_files`` compatible (pid re-mapped
        per rank at merge time) without drifting from the op profiler's
        timeline."""
        max_lane = self.max_slots
        evs: List[Dict[str, Any]] = []
        for doc in self.requests:
            for c in (doc.get("spans") or {}).get("children", ()):
                if c.get("end") is None:
                    continue
                lane = self._lane(c)
                max_lane = max(max_lane, lane)
                evs.append(chrome.complete_event(
                    c["name"], c["start"], c["end"], cat="serve",
                    pid=pid, tid=lane,
                    args={"request": doc["id"], **(c.get("attrs") or {})}))
        engine_lane = max_lane + 1
        for s in self.decode_steps:
            evs.append(chrome.complete_event(
                "decode_step", s["start"], s["end"], cat="serve",
                pid=pid, tid=engine_lane,
                args={"active": s["active"], "queued": s["queued"],
                      "tokens": s.get("tokens", 1)}))
        meta = [chrome.process_name_event(pid, f"serve:{self.engine}"),
                chrome.thread_name_event(pid, 0, "queue/preempt wait"),
                chrome.thread_name_event(pid, engine_lane,
                                         "engine (decode steps)")]
        for lane in range(1, engine_lane):
            meta.append(chrome.thread_name_event(pid, lane,
                                                 f"slot {lane - 1}"))
        return meta + evs

    def chrome_trace_dict(self, pid: int = 0) -> Dict[str, Any]:
        return chrome.trace_dict(self.chrome_trace_events(pid))

    def write_chrome_trace(self, path: str, pid: int = 0) -> str:
        return chrome.write_chrome_trace(path, self.chrome_trace_dict(pid))

    def dump_dict(self) -> Dict[str, Any]:
        return {
            "kind": "serve_trace",
            "version": 1,
            "engine": self.engine,
            "requests_traced": self.n_traced,
            "decode_gap_seconds": round(self.total_decode_gap, 6),
            "requests": list(self.requests),
            "decode_steps": list(self.decode_steps),
            "exemplars": self.exemplars.to_dict(),
        }

    def dump(self, path: str) -> str:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.dump_dict(), f, indent=1, default=str)
        os.replace(tmp, path)
        return path


# --- validation (PTL403) + overhead guard (PTL402) ------------------------

def validate_trace(doc: Dict[str, Any]):
    """Structural check of one finished-request doc: phases must close,
    nest inside the root, stay in order, and never run backwards. Emits
    PTL403 findings (each with a machine-readable ``reason`` slug the
    ``trace.spans_malformed`` counter labels by)."""
    from ..static.analysis.diagnostics import (DiagnosticReport,
                                               Severity)

    report = DiagnosticReport()

    def bad(reason, msg):
        report.add("PTL403", Severity.WARNING,
                   f"request {doc.get('id')}: {msg}",
                   hint="span-tree hooks ran out of order — a tracer "
                        "hook fired after finish() or a phase closed "
                        "before it opened",
                   suggestion={"reason": reason})

    spans = doc.get("spans") or {}
    root_start, root_end = spans.get("start"), spans.get("end")
    if root_end is None:
        bad("root_open", "root span never closed (request not finished)")
    children = spans.get("children") or []
    if not children:
        bad("no_phases", "span tree has no lifecycle phases")
    eps = 1e-9
    prev_end = None
    for c in children:
        name, s, e = c.get("name"), c.get("start"), c.get("end")
        if name not in PHASES:
            bad("unknown_phase", f"unknown phase {name!r}")
        if e is None:
            bad("phase_open", f"phase {name!r} never closed")
            continue
        if e < s - eps:
            bad("negative_span", f"phase {name!r} ends before it starts")
        if root_start is not None and s < root_start - eps:
            bad("outside_root", f"phase {name!r} starts before submit")
        if root_end is not None and e > root_end + eps:
            bad("outside_root", f"phase {name!r} ends after finish")
        if prev_end is not None and s < prev_end - eps:
            bad("overlap",
                f"phase {name!r} overlaps the previous phase")
        prev_end = e
    return report


def check_tracing_overhead(tokens_per_sec_on: float,
                           tokens_per_sec_off: float, *,
                           tolerance_pct: float = 3.0,
                           engine: str = "default"):
    """The instrumentation-cost guard: tokens/sec with tracing on must
    stay within ``tolerance_pct`` of tracing off. Publishes
    ``trace.overhead_pct`` and returns a report carrying PTL402 when the
    budget is exceeded (the bench serve config runs this; a tracer that
    costs real throughput is a tracer nobody leaves enabled)."""
    from ..static.analysis.diagnostics import (DiagnosticReport,
                                               Severity)

    report = DiagnosticReport()
    if tokens_per_sec_off <= 0:
        return report
    overhead = 100.0 * (tokens_per_sec_off - tokens_per_sec_on) \
        / tokens_per_sec_off
    M_OVERHEAD.set(round(overhead, 3), engine=engine)
    if overhead > tolerance_pct:
        report.add(
            "PTL402", Severity.WARNING,
            f"tracing overhead {overhead:.2f}% exceeds the "
            f"{tolerance_pct:.1f}% budget ({tokens_per_sec_on:.1f} "
            f"tok/s traced vs {tokens_per_sec_off:.1f} untraced)",
            hint="the tracer hooks are host-side O(1); an overhead this "
                 "large means a hook landed on the per-token path or "
                 "retention bounds grew — profile the engine step",
            suggestion={"overhead_pct": round(overhead, 3),
                        "tolerance_pct": tolerance_pct})
    return report


# --- rendering ------------------------------------------------------------

def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = (len(sorted_vals) - 1) * q
    lo, hi = int(idx), min(int(idx) + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def render_phase_table(request_docs) -> str:
    """Per-phase p50/p99 table over per-request phase totals — the
    exact-sample companion to the bucket-interpolated quantiles
    ``bench.py --metrics`` reads off ``trace.phase_seconds``."""
    per_phase: Dict[str, List[float]] = {}
    total_latency = 0.0
    for d in request_docs:
        for phase, sec in (d.get("breakdown") or {}).items():
            per_phase.setdefault(phase, []).append(float(sec))
        total_latency += float(d.get("latency_seconds") or 0.0)
    if not per_phase:
        return "no traced requests"
    rows = [("phase", "reqs", "p50 ms", "p99 ms", "total s", "share")]
    order = {p: i for i, p in enumerate(PHASES)}
    for phase in sorted(per_phase, key=lambda p: order.get(p, 99)):
        vals = sorted(per_phase[phase])
        tot = sum(vals)
        share = (100.0 * tot / total_latency) if total_latency > 0 else 0.0
        rows.append((phase, str(len(vals)),
                     f"{_percentile(vals, 0.50) * 1e3:.2f}",
                     f"{_percentile(vals, 0.99) * 1e3:.2f}",
                     f"{tot:.4f}", f"{share:.1f}%"))
    widths = [max(len(r[i]) for r in rows) for i in range(len(rows[0]))]
    return "\n".join(
        "  ".join(col.rjust(w) if i else col.ljust(w)
                  for i, (col, w) in enumerate(zip(r, widths)))
        for r in rows)


def render_serve_trace(doc: Dict[str, Any]) -> str:
    """Human report for one ``serve_trace`` dump (the ``dump_dict()``
    JSON ``tools/serve_load.py --trace-out`` writes): header, per-phase
    p50/p99 breakdown, tail exemplars."""
    if doc.get("kind") != "serve_trace":
        raise ValueError(
            f"not a serve_trace dump (kind={doc.get('kind')!r})")
    reqs = doc.get("requests") or []
    lines = [
        f"serve trace (engine={doc.get('engine')}): "
        f"{doc.get('requests_traced', len(reqs))} request(s) traced, "
        f"{len(doc.get('decode_steps') or [])} decode step(s), "
        f"decode gap {float(doc.get('decode_gap_seconds') or 0) * 1e3:.1f}"
        f" ms",
        "",
        render_phase_table(reqs),
    ]
    ex = doc.get("exemplars")
    if ex:
        t = TailExemplars(ex.get("n", 4), engine=doc.get("engine", "?"))
        t.worst_ttft = list(ex.get("worst_ttft") or [])
        t.worst_latency = list(ex.get("worst_latency") or [])
        lines += ["", t.render()]
    return "\n".join(lines)
