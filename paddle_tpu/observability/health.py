"""Continuous health monitoring: declarative detectors over time-series.

The SLO layer (``observability/slo.py``) judges the serving plane
against *fixed objectives*; this module judges any plane against *its
own history*. A :class:`HealthMonitor` owns a
:class:`~.timeseries.SeriesRecorder`, samples it at step boundaries
(``maybe_on_step`` is wired into ``obs.step_region`` and
``ServeEngine.step``), and evaluates declarative rules over the
recorded windows:

=========  ==========================================================
``drift``  z-score + relative-change gate of the recent samples
           against the window's own baseline half — "step time is
           +12% and 4 sigma above where this job started"
           (PTL601 up / PTL603 down by default)
``leak``   monotonic growth across the window with a minimum total
           rise — watermarks and occupancies that only go up
           (PTL602); sawtooth series (grow-then-free) stay quiet
``rate``   rate-of-change alarm on a counter-delta series — fires
           when the windowed sum of deltas crosses the threshold
           (PTL603; ``elastic.steps_lost``, ``fleet.ship_failures``)
=========  ==========================================================

A firing rule latches (one alert per excursion, re-arming on recovery)
and produces every artifact at once: the ``health.alerts{rule,series}``
counter, a ``health.alert`` structured event, a PTL6xx diagnostic on
:attr:`HealthMonitor.report`, and a flight dump with reason
``health_alert`` whose context carries the offending series window —
the post-mortem file shows the trajectory, not just the trip. A rule
whose series is missing or non-finite files PTL604 once instead of
silently evaluating garbage.

Enablement: ``PADDLE_TPU_HEALTH=1`` installs the default rules (and
implies ``obs.enable()``); set it to inline JSON or a JSON-file path
for custom rules. Unset, no monitor exists and the step hooks reduce
to one global load + None check — zero overhead, no ``health.``/``ts.``
series in any dump (solo equivalence).
"""
from __future__ import annotations

import json
import math
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

from . import flight
from .events import emit
from .metrics import registry
from .timeseries import SeriesRecorder

__all__ = ["HealthRule", "HealthMonitor", "parse_rules", "default_rules",
           "rules_from_env", "monitor_from_env", "install",
           "active_monitor", "maybe_on_step", "HEALTH_ENV",
           "HEALTH_CODES", "RULE_KINDS"]

HEALTH_ENV = "PADDLE_TPU_HEALTH"

#: diagnostic codes this module (plus tools/bench_compare.py, which
#: reuses PTL605) emits — documented in static/analysis/diagnostics.py
#: CODES, audited by tools/lint_registry.py.
HEALTH_CODES = ("PTL601", "PTL602", "PTL603", "PTL604", "PTL605")

RULE_KINDS = ("drift", "leak", "rate")

M_ALERTS = registry.counter(
    "health.alerts",
    "health-detector alert episodes (a rule fires once per excursion, "
    "re-arming on recovery), by rule and series")
M_EVALS = registry.counter(
    "health.evaluations",
    "health-rule evaluation passes (one per sampled step boundary), "
    "by rule")


@dataclass
class HealthRule:
    """One declarative detector over a recorded series."""

    name: str                      # the rule= label alerts carry
    kind: str                      # one of RULE_KINDS
    series: str                    # SeriesRecorder series name
    code: str = ""                 # PTL6xx; default per kind/direction
    direction: str = "up"          # drift only: "up" | "down" is bad
    min_points: int = 8            # don't judge a thin window
    threshold_z: float = 4.0       # drift: z-score gate
    rel_min: float = 0.05          # drift: minimum relative change
    min_growth_pct: float = 10.0   # leak: total rise across window (%)
    window_points: int = 8         # rate: trailing deltas summed
    threshold: float = 1.0         # rate: fires when windowed sum >= this

    def __post_init__(self):
        if self.kind not in RULE_KINDS:
            raise ValueError(
                f"health rule {self.name!r}: unknown kind {self.kind!r} "
                f"(expected one of {RULE_KINDS})")
        if self.direction not in ("up", "down"):
            raise ValueError(
                f"health rule {self.name!r}: direction must be 'up' or "
                f"'down', got {self.direction!r}")
        if not self.code:
            if self.kind == "leak":
                self.code = "PTL602"
            elif self.kind == "rate":
                self.code = "PTL603"
            else:
                self.code = "PTL601" if self.direction == "up" \
                    else "PTL603"

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "kind": self.kind,
                "series": self.series, "code": self.code,
                "direction": self.direction,
                "min_points": self.min_points,
                "threshold_z": self.threshold_z, "rel_min": self.rel_min,
                "min_growth_pct": self.min_growth_pct,
                "window_points": self.window_points,
                "threshold": self.threshold}


def default_rules() -> List[HealthRule]:
    """The detector set ``PADDLE_TPU_HEALTH=1`` installs."""
    return [
        HealthRule("step_time_drift", "drift", "train.step_seconds",
                   direction="up"),
        HealthRule("serve_throughput_drift", "drift",
                   "serve.tokens_per_sec", direction="down"),
        HealthRule("hbm_watermark_leak", "leak",
                   "device.hbm_watermark_bytes"),
        HealthRule("kv_pool_leak", "leak", "serve.pool_occupancy"),
        HealthRule("steps_lost_rate", "rate", "elastic.steps_lost",
                   threshold=8.0),
        HealthRule("ship_failure_rate", "rate", "fleet.ship_failures",
                   threshold=4.0),
    ]


def parse_rules(spec) -> List[HealthRule]:
    """Rules from a list of ``HealthRule``/dicts, an inline JSON string,
    a JSON-file path, or the literal enable values (``1``/``true``) for
    the defaults — the ``slo.parse_rules`` contract."""
    if spec is None:
        return []
    if isinstance(spec, str):
        s = spec.strip()
        if not s or s in ("0", "false", "off"):
            return []
        if s in ("1", "true", "on", "default"):
            return default_rules()
        if not s.startswith("["):
            with open(s) as f:
                s = f.read()
        spec = json.loads(s)
    if isinstance(spec, dict):
        spec = [spec]
    return [r if isinstance(r, HealthRule) else HealthRule(**r)
            for r in spec]


def rules_from_env() -> List[HealthRule]:
    return parse_rules(os.environ.get(HEALTH_ENV))


class HealthMonitor:
    """Samples the recorder and evaluates every rule at step boundaries.

    One ``on_step()`` = one recorder sample + one evaluation pass.
    Detectors are windowed over the recorder's ring, so memory stays
    bounded and a restarted excursion re-fires only after recovery
    (the ``_latched`` set, same episode semantics as ``SloMonitor``)."""

    def __init__(self, rules=None, *, recorder: Optional[SeriesRecorder]
                 = None, clock=None):
        self.rules = parse_rules(rules) if rules is not None \
            else default_rules()
        self.recorder = recorder if recorder is not None \
            else SeriesRecorder(clock=clock)
        self._latched: set = set()
        self._malformed: set = set()    # rules that already filed PTL604
        self.alerts: List[Dict[str, Any]] = []
        # the DiagnosticReport is created on first access:
        # monitor_from_env() runs at package-import time, where pulling
        # static.analysis in would be a circular import
        self._report = None

    @property
    def report(self):
        if self._report is None:
            from ..static.analysis.diagnostics import DiagnosticReport

            self._report = DiagnosticReport()
        return self._report

    # -- driving -----------------------------------------------------------
    def on_step(self, now: Optional[float] = None
                ) -> List[Dict[str, Any]]:
        """Sample the tracked series and evaluate every rule. Returns
        the alerts that FIRED this step (newly latched)."""
        self.recorder.sample(now)
        t = now if now is not None else self.recorder._clock()
        fired = []
        for rule in self.rules:
            M_EVALS.inc(rule=rule.name)
            rec = self._evaluate(rule, t)
            if rec is not None:
                fired.append(rec)
        return fired

    # -- detector math -----------------------------------------------------
    def _judge(self, rule: HealthRule,
               values: Sequence[float]) -> Optional[Dict[str, Any]]:
        """None = healthy / not enough data; dict = breach details."""
        if rule.kind == "rate":
            window = values[-rule.window_points:]
            if not window:
                return None
            total = sum(window)
            if total >= rule.threshold:
                return {"value": total, "threshold": rule.threshold,
                        "detail": f"sum of last {len(window)} deltas"}
            return None
        if len(values) < rule.min_points:
            return None
        if rule.kind == "leak":
            lo, hi = values[0], values[-1]
            for a, b in zip(values, values[1:]):
                if b < a:
                    return None       # freed at least once: sawtooth
            base = abs(lo) if lo else 1.0
            growth_pct = 100.0 * (hi - lo) / base
            if hi > lo and growth_pct >= rule.min_growth_pct:
                return {"value": hi, "growth_pct": round(growth_pct, 3),
                        "detail": f"monotonic {lo:g} -> {hi:g} over "
                                  f"{len(values)} samples"}
            return None
        # drift: baseline = first half of window, recent = last 3 points
        half = max(rule.min_points // 2, len(values) // 2)
        baseline = values[:half]
        recent = values[-min(3, len(values) - half):]
        if not baseline or not recent:
            return None
        bmean = sum(baseline) / len(baseline)
        bvar = sum((v - bmean) ** 2 for v in baseline) / len(baseline)
        bstd = max(math.sqrt(bvar), 0.01 * abs(bmean), 1e-12)
        rmean = sum(recent) / len(recent)
        z = (rmean - bmean) / bstd
        rel = (rmean - bmean) / abs(bmean) if bmean else 0.0
        if rule.direction == "down":
            z, rel = -z, -rel
        if z >= rule.threshold_z and rel >= rule.rel_min:
            return {"value": rmean, "baseline": round(bmean, 9),
                    "z": round(z, 3), "rel_change": round(rel, 4),
                    "detail": f"{'+' if rule.direction == 'up' else '-'}"
                              f"{100 * rel:.1f}% vs baseline, "
                              f"z={z:.1f}"}
        return None

    def _evaluate(self, rule: HealthRule,
                  now: float) -> Optional[Dict[str, Any]]:
        from ..static.analysis.diagnostics import Severity

        window = self.recorder.window(rule.series)
        values = [v for _t, v in window]
        bad = [v for v in values
               if not isinstance(v, (int, float)) or not math.isfinite(v)]
        if bad:
            if rule.name not in self._malformed:
                self._malformed.add(rule.name)
                self.report.add(
                    "PTL604", Severity.WARNING,
                    f"health rule {rule.name!r}: series {rule.series!r} "
                    f"carries {len(bad)} non-finite/non-numeric "
                    f"point(s) — detector cannot evaluate",
                    hint="a NaN step time or gauge usually means the "
                         "instrumented site computed 0/0; fix the "
                         "producer, the detector will resume on its own")
            return None
        breach = self._judge(rule, values)
        if breach is None:
            self._latched.discard(rule.name)
            return None
        if rule.name in self._latched:
            return None                # still the same excursion
        self._latched.add(rule.name)
        M_ALERTS.inc(rule=rule.name, series=rule.series)
        # "rule_kind", not "kind": the rec doubles as emit() **fields
        rec = {"rule": rule.name, "rule_kind": rule.kind,
               "series": rule.series, "code": rule.code,
               "at": round(now, 6), **breach}
        self.alerts.append(rec)
        emit("health.alert", **rec)
        self.report.add(
            rule.code, Severity.WARNING,
            f"health rule {rule.name!r} fired on {rule.series!r}: "
            f"{breach['detail']} (value {breach['value']:.6g})",
            hint="the health_alert flight dump context carries the "
                 "offending series window; render it with "
                 "tools/metrics_report.py --health",
            suggestion={"rule": rule.to_dict(), **breach})
        flight.recorder.dump(
            flight.REASON_HEALTH_ALERT,
            context={**rec,
                     "window": [[round(t, 6), v] for t, v in window]})
        return rec


# -- process-global monitor (the step_region/ServeEngine hook target) ----
_active: Optional[HealthMonitor] = None


def install(monitor: Optional[HealthMonitor]) -> Optional[HealthMonitor]:
    """Install (or clear, with None) the process-global monitor that
    ``maybe_on_step`` drives. Returns the monitor for chaining."""
    global _active
    _active = monitor
    return monitor


def active_monitor() -> Optional[HealthMonitor]:
    return _active


def maybe_on_step(now: Optional[float] = None) -> None:
    """Step-boundary hook: one global load + None check when health
    monitoring is off — the zero-overhead contract."""
    mon = _active
    if mon is None:
        return
    try:
        mon.on_step(now)
    except Exception:
        pass  # telemetry must never take down the training/serving loop


def monitor_from_env() -> Optional[HealthMonitor]:
    """Build + install a monitor from ``PADDLE_TPU_HEALTH`` (None and
    no-op when the env is unset/disabled)."""
    rules = rules_from_env()
    if not rules:
        return None
    return install(HealthMonitor(rules))


def _reset_active() -> None:
    """obs.reset() support: clear the installed monitor's state (rules
    and recorder capacity survive; history, latches and alerts do not)."""
    mon = _active
    if mon is None:
        return
    mon.recorder.clear()
    mon._latched.clear()
    mon._malformed.clear()
    mon.alerts.clear()
    mon._report = None
