"""Metrics registry: named counters, gauges and histograms with labels.

TPU-native analog of the reference's flag-gated runtime stats layer
(reference: paddle/fluid/platform/flags.h stat helpers + the
profiler_statistic tables): a process-global registry of typed metric
series, cheap enough to leave compiled into every hot path and gated by
one boolean (`observability.state.on`) at the instrumentation sites.

Naming convention (mirrors the ``PTLxxx`` diagnostic-code claiming from
static/analysis): every metric name is ``<subsystem>.<noun_verb>``
(``dispatch.cache_hits``, ``executor.compile_seconds``). A subsystem
claims its prefix by adding it to :data:`CLAIMED_SUBSYSTEMS` next to its
first metric; ``tools/lint_registry.py`` audits, once per test session,
that every import-time registration is unique, documented, matches the
scheme, and has a claimed prefix.

Concurrency: increments are plain dict updates under the GIL. A lost
increment under a data race costs one count of telemetry, never
correctness, so the hot path takes no lock.
"""
from __future__ import annotations

import re
import sys
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

#: subsystems that have claimed a metric-name prefix (the metric analog of
#: static/analysis/diagnostics.py CODES). Add yours here WITH your first
#: metric — tools/lint_registry.py fails on unclaimed prefixes.
CLAIMED_SUBSYSTEMS = {
    "dispatch",    # core/dispatch.py — primitive calls, executable cache
    "executor",    # static/program.py — compiles, replays, invalidations
    "passes",      # distributed/passes — per-pass timing, verifier counts
    "jit",         # jit/__init__.py — to_static compile cache
    "bench",       # bench.py — benchmark-side metrics
    "profiler",    # profiler/ — tracer self-metrics
    "train",       # observability/runtime.py — step seconds/throughput/MFU
    "device",      # observability/runtime.py — HBM gauges (device/memory.py)
    "comm",        # distributed/communication — collectives + watchdog
    "io",          # io/dataloader.py — prefetch queue depth / wait time
    "elastic",     # distributed/elastic.py — restarts, re-rendezvous,
                   # peer deaths, checkpoint-restore cost (ROADMAP item 1)
    "fleet",       # observability/fleet.py — cross-rank snapshot
                   # shipping/aggregation, step skew, stragglers
    "opt",         # static/analysis/rewrite.py — lint->rewrite driver:
                   # findings fixed/remaining by code, per-pass rewrite
                   # seconds, fixed-point iterations, passes skipped
    "cost",        # static/analysis/cost.py + memory.py — analytical
                   # FLOPs/bytes model and liveness peak-HBM estimator:
                   # predicted-vs-measured gauges, model error, OOM
                   # predictions
    "serve",       # serve/engine.py — continuous-batching server: queue
                   # depth, TTFT, tokens/sec, preemptions, pool
                   # occupancy, batch fill, decode/prefill traces;
                   # prefix-cache sharing (prefix_hits,
                   # prefix_blocks_shared, cow_copies) and fused decode
                   # bursts (burst_tokens, host_roundtrips)
    "trace",       # observability/tracing.py + slo.py — request-scoped
                   # span tracing: per-phase seconds, tail exemplars,
                   # decode-gap accounting, SLO breaches, overhead guard
    "opprof",      # observability/opprof.py — op-level execution
                   # profiler: per-op measured seconds, attribution
                   # coverage, measured/predicted drift, pacer skips,
                   # profiling overhead guard
    "ts",          # observability/timeseries.py — metric time-series
                   # recorder self-metrics (points recorded, series
                   # evicted)
    "health",      # observability/health.py — continuous-health
                   # detectors: latched alerts by rule/series,
                   # detector evaluations
    "test",        # scratch names registered by the test suite
}

#: ``subsystem.noun_verb`` — two snake_case segments, one dot.
NAME_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$")

#: default histogram bucket upper bounds, in seconds (wall-time shaped:
#: sub-ms dispatch up to multi-minute XLA compiles).
DEFAULT_TIME_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    if not labels:
        return ()
    items = [(k, v if type(v) is str else str(v))
             for k, v in labels.items()]
    if len(items) > 1:
        items.sort()  # canonical across call sites with other kwarg order
    return tuple(items)


class Metric:
    """Base: one named metric holding a family of labeled series."""

    kind = "metric"
    __slots__ = ("name", "doc", "_series")

    def __init__(self, name: str, doc: str = ""):
        if not NAME_RE.match(name):
            raise ValueError(
                f"metric name {name!r} does not match the "
                f"'subsystem.noun_verb' scheme ({NAME_RE.pattern})")
        self.name = name
        self.doc = doc
        self._series: Dict[LabelKey, Any] = {}

    def labelsets(self) -> List[Dict[str, str]]:
        return [dict(k) for k in self._series]

    def reset(self):
        self._series.clear()

    # -- serialization ----------------------------------------------------
    def _series_dict(self, key: LabelKey, value) -> Dict[str, Any]:
        return {"labels": dict(key), "value": value}

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "doc": self.doc,
            "series": [self._series_dict(k, v)
                       for k, v in sorted(self._series.items())],
        }


class Counter(Metric):
    kind = "counter"
    __slots__ = ()

    def inc(self, n: int = 1, **labels):
        key = _label_key(labels)
        self._series[key] = self._series.get(key, 0) + n

    def value(self, **labels) -> int:
        return self._series.get(_label_key(labels), 0)

    def total(self) -> int:
        return sum(self._series.values())


class Gauge(Metric):
    kind = "gauge"
    __slots__ = ()

    def set(self, value, **labels):
        self._series[_label_key(labels)] = value

    def value(self, default=None, **labels):
        return self._series.get(_label_key(labels), default)


class _HistSeries:
    __slots__ = ("count", "sum", "min", "max", "bucket_counts")

    def __init__(self, n_buckets: int):
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max = 0.0
        # one slot per bound plus the overflow (+inf) slot
        self.bucket_counts = [0] * (n_buckets + 1)


class Histogram(Metric):
    """Time/size histogram: count, sum, min, max + cumulative-free
    per-bucket counts over fixed upper bounds."""

    kind = "histogram"
    __slots__ = ("bounds",)

    def __init__(self, name: str, doc: str = "",
                 buckets: Sequence[float] = DEFAULT_TIME_BUCKETS):
        super().__init__(name, doc)
        self.bounds = tuple(sorted(float(b) for b in buckets))

    def observe(self, value: float, **labels):
        key = _label_key(labels)
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = _HistSeries(len(self.bounds))
        value = float(value)
        s.count += 1
        s.sum += value
        s.min = value if s.min is None else min(s.min, value)
        s.max = max(s.max, value)
        for i, b in enumerate(self.bounds):
            if value <= b:
                s.bucket_counts[i] += 1
                return
        s.bucket_counts[-1] += 1

    def time(self, **labels):
        """Context manager observing the elapsed wall seconds."""
        return _Timer(self, labels)

    def stats(self, **labels) -> Dict[str, float]:
        s = self._series.get(_label_key(labels))
        if s is None:
            return {"count": 0, "sum": 0.0, "min": 0.0, "max": 0.0,
                    "avg": 0.0}
        return {"count": s.count, "sum": s.sum, "min": s.min or 0.0,
                "max": s.max, "avg": s.sum / s.count if s.count else 0.0}

    def _series_dict(self, key: LabelKey, s: _HistSeries) -> Dict[str, Any]:
        return {
            "labels": dict(key), "count": s.count, "sum": s.sum,
            "min": s.min if s.min is not None else 0.0, "max": s.max,
            "bounds": list(self.bounds), "bucket_counts": list(s.bucket_counts),
        }


class _Timer:
    __slots__ = ("_hist", "_labels", "_t0", "seconds")

    def __init__(self, hist: Histogram, labels: Dict[str, Any]):
        self._hist = hist
        self._labels = labels
        self.seconds = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.seconds = time.perf_counter() - self._t0
        self._hist.observe(self.seconds, **self._labels)
        return False


class MetricsRegistry:
    """Process-global metric namespace (the PD flag-registry pattern:
    ``counter()``/``gauge()``/``histogram()`` are define-or-get, so two
    modules naming the same metric share one series family — but a name
    re-claimed as a DIFFERENT kind is a hard error)."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        # name -> source files that called counter()/gauge()/histogram()
        # for it. Define-or-get means a name collision SHARES one series
        # family silently, so the registry records where each definition
        # came from and tools/lint_registry.py flags names claimed from
        # more than one module (accidental cross-subsystem reuse).
        self._sites: Dict[str, set] = {}

    def _define(self, cls, name: str, doc: str, **kwargs) -> Metric:
        try:
            site = sys._getframe(2).f_code.co_filename
            self._sites.setdefault(name, set()).add(site)
        except Exception:
            pass
        m = self._metrics.get(name)
        if m is not None:
            if not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"cannot re-register as {cls.kind}")
            return m
        m = cls(name, doc, **kwargs)
        self._metrics[name] = m
        return m

    def counter(self, name: str, doc: str = "") -> Counter:
        return self._define(Counter, name, doc)

    def gauge(self, name: str, doc: str = "") -> Gauge:
        return self._define(Gauge, name, doc)

    def histogram(self, name: str, doc: str = "",
                  buckets: Sequence[float] = DEFAULT_TIME_BUCKETS
                  ) -> Histogram:
        return self._define(Histogram, name, doc, buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        return self._metrics.get(name)

    def __iter__(self) -> Iterator[Metric]:
        return iter(list(self._metrics.values()))

    def __len__(self) -> int:
        return len(self._metrics)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def definition_sites(self) -> Dict[str, List[str]]:
        return {n: sorted(s) for n, s in self._sites.items()}

    def reset(self):
        """Zero every series (metric definitions stay registered)."""
        for m in self._metrics.values():
            m.reset()

    def to_dict(self) -> Dict[str, Any]:
        return {name: m.to_dict()
                for name, m in sorted(self._metrics.items())}


#: the process-global registry every subsystem registers into.
registry = MetricsRegistry()
