"""The one-boolean hot-path gate.

Instrumented modules read ``state.on`` (two attribute loads, no call)
before touching any metric, so a disabled build adds nanoseconds to the
dispatch fast path. Kept in its own leaf module so ``events``/``report``
and ``observability/__init__`` can share it without import cycles.
"""
from __future__ import annotations


class _State:
    __slots__ = ("on",)

    def __init__(self):
        self.on = False


state = _State()
