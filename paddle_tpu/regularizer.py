"""Weight regularizers.

Reference: python/paddle/regularizer.py (L1Decay, L2Decay — applied to grads
by the optimizer when the param has no own regularizer).
"""
from __future__ import annotations

import jax.numpy as jnp


class WeightDecayRegularizer:
    def _apply(self, param, grad):
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def _apply(self, param, grad):
        return grad + jnp.asarray(self.coeff, grad.dtype) * param.astype(grad.dtype)


class L1Decay(WeightDecayRegularizer):
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def _apply(self, param, grad):
        return grad + jnp.asarray(self.coeff, grad.dtype) * jnp.sign(
            param.astype(grad.dtype)
        )
