"""paddle.callbacks namespace parity (re-exports hapi callbacks)."""
from .hapi.callbacks import (  # noqa: F401
    Callback, EarlyStopping, LRScheduler, ModelCheckpoint, ProgBarLogger,
    VisualDL,
)
