"""paddle_tpu — a TPU-native deep learning framework.

A ground-up re-design of the PaddlePaddle surface (reference:
/root/reference, see SURVEY.md) for TPU hardware: jax/XLA is the kernel and
compiler layer, Pallas supplies fused kernels, pjit/shard_map + jax.sharding
supply distributed execution over ICI/DCN meshes.

Public API mirrors ``import paddle``: tensors, ops, nn, optimizer, autograd,
amp, jit, io, distributed, vision, metric, profiler.
"""
from __future__ import annotations

__version__ = "0.1.0"

# Enable 64-bit dtypes: paddle semantics default integer tensors to int64.
# Compute dtypes stay explicit (float32/bfloat16) throughout the framework,
# so this does not push float64 onto the TPU MXU path.
import jax as _jax

_jax.config.update("jax_enable_x64", True)

# core types
from .core.tensor import Tensor, Parameter
from .core.dtype import (
    bool, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
)
from .core.place import (
    CPUPlace, TPUPlace, CUDAPlace, CustomPlace, Place,
    is_compiled_with_cuda, is_compiled_with_tpu,
)
from .core import flags as _flags
from .core.flags import set_flags, get_flags
from .core.generator import seed, default_generator, get_rng_state_tracker

# ops — star import puts the whole tensor-op surface at top level
# (paddle.matmul, paddle.reshape, ...), and patches Tensor methods.
from .ops import *  # noqa: F401,F403
from . import ops

# autograd
from .autograd import no_grad, enable_grad, set_grad_enabled, grad
from . import autograd

# subpackages (lazy-ish: imported on attribute access for heavy ones)
from . import nn
from . import optimizer
from . import io
from . import amp
from . import jit
from . import static
from . import inference
from . import sparse
from . import cost_model  # noqa: F401
from . import metric
from . import device
from . import incubate

from .framework.io_ import save, load
from .framework.misc import (
    dtype, iinfo, finfo, LazyGuard, create_parameter, get_rng_state,
    set_rng_state, get_cuda_rng_state, set_cuda_rng_state,
    set_printoptions, check_shape, disable_signal_handler, enable_static,
    disable_static,
)
from .core.place import CUDAPinnedPlace
from .ops.manipulation import flip as reverse  # deprecated paddle.reverse
# the ops star-import binds paddle.linalg to ops.linalg (the kernel
# module), which also stops `from . import linalg` from importing the
# package-level namespace module; import it explicitly and rebind (adds
# lu_unpack, matrix_exp, *_lowrank, ormqr, cholesky_inverse, fp8 gemm)
import importlib as _importlib

linalg = _importlib.import_module(".linalg", __name__)
from .nn.param_attr import ParamAttr
from . import framework

import sys as _sys


def __getattr__(name):
    # heavyweight subpackages loaded on demand
    if name in ("distributed", "vision", "profiler", "observability",
                "hapi", "callbacks",
                "fft", "signal", "distribution", "geometric", "quantization",
                "text", "audio", "dataset", "hub", "sysconfig", "linalg",
                "regularizer", "decomposition", "onnx", "utils", "reader"):
        import importlib

        try:
            mod = importlib.import_module(f".{name}", __name__)
        except ModuleNotFoundError as e:
            if e.name != f"{__name__}.{name}":
                # a dependency inside the submodule is missing — surface it
                raise
            # PEP 562: missing attributes must surface as AttributeError so
            # hasattr()/getattr()-based feature detection works.
            raise AttributeError(
                f"module 'paddle_tpu' has no attribute {name!r}"
            ) from e
        setattr(_sys.modules[__name__], name, mod)
        return mod
    if name in ("Model", "summary"):
        from . import hapi as _hapi

        val = getattr(_hapi, name)
        setattr(_sys.modules[__name__], name, val)
        return val
    if name == "flops":
        from .utils.flops import dynamic_flops

        setattr(_sys.modules[__name__], "flops", dynamic_flops)
        return dynamic_flops
    if name == "batch":
        from .reader import batch as _batch

        setattr(_sys.modules[__name__], "batch", _batch)
        return _batch
    if name == "DataParallel":
        from .distributed import DataParallel as _DP

        setattr(_sys.modules[__name__], "DataParallel", _DP)
        return _DP
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def is_grad_enabled():
    return autograd.is_grad_enabled()


def set_default_dtype(d):
    from .core.dtype import convert_dtype

    set_flags({"default_dtype": convert_dtype(d).name})


def get_default_dtype():
    return _flags.get_flag("default_dtype")


def set_device(device_str):
    from .core import place as _place

    return _place.set_device(device_str)


def get_device():
    from .core import place as _place

    return _place.get_device()


def device_count():
    from .core import place as _place

    return _place.device_count()


def in_dynamic_mode():
    from .framework.misc import in_static_mode
    from .jit.trace_state import in_tracing

    return not in_tracing() and not in_static_mode()


def synchronize():
    """Block until all enqueued device work completes (paddle.device.synchronize)."""
    import jax

    (jax.device_put(0.0) + 0).block_until_ready()


def _patch_tensor_method_surface():
    """Attach the remaining tensor_method_func names as Tensor methods
    (reference: python/paddle/tensor/__init__.py patches every listed
    function onto the eager Tensor type)."""
    _names = [
        "ormqr", "cov", "corrcoef", "cond", "lstsq", "t", "cholesky_inverse",
        "histogram_bin_edges", "histogramdd", "mv", "qr",
        "householder_product", "pca_lowrank", "svd_lowrank", "eigvals",
        "eigvalsh", "logit", "logaddexp", "multiplex", "sinc", "reduce_as",
        "multigammaln", "hypot", "block_diag", "floor_mod", "addmm", "isin",
        "isneginf", "isposinf", "isreal", "broadcast_shape", "gammaincc",
        "gammainc", "is_empty", "is_tensor", "reverse", "scatter_nd",
        "shard_index", "slice", "slice_scatter", "tensor_split", "hsplit",
        "dsplit", "vsplit", "stack", "unique_consecutive", "unstack",
        "top_p_sampling", "is_complex", "is_integer", "rank",
        "is_floating_point", "gammaln", "broadcast_tensors", "eig",
        "multi_dot", "cholesky_solve", "triangular_solve", "asinh", "atanh",
        "acosh", "lu", "lu_unpack", "cdist", "select_scatter", "heaviside",
        "index_put", "take", "bucketize", "sgn", "frexp", "ldexp",
        "trapezoid", "cumulative_trapezoid", "polar", "sigmoid_", "vander",
        "nextafter", "unflatten", "as_strided", "view", "view_as", "unfold",
        "i0", "i0e", "i1", "i1e", "polygamma", "diagflat", "multinomial",
        "renorm", "stft", "istft", "diag", "copysign", "bitwise_left_shift",
        "bitwise_right_shift", "index_fill", "atleast_1d", "atleast_2d",
        "atleast_3d", "diagonal_scatter", "masked_scatter", "combinations",
        "signbit",
    ]
    mod = _sys.modules[__name__]
    for n in _names:
        fn = getattr(mod, n, None)
        if fn is None and n == "sigmoid_":
            from .ops.math import _make_inplace
            from .ops.activation import sigmoid as _sig

            fn = _make_inplace(_sig)
        if callable(fn) and not hasattr(Tensor, n):
            setattr(Tensor, n, fn)
    # signal-domain methods + factory functions the reference also attaches
    from .signal import istft as _istft, stft as _stft

    for n, fn in (("stft", _stft), ("istft", _istft),
                  ("create_parameter", create_parameter),
                  ("create_tensor", getattr(mod, "create_tensor", None))):
        if callable(fn) and not hasattr(Tensor, n):
            setattr(Tensor, n, staticmethod(fn) if n.startswith("create")
                    else fn)


_patch_tensor_method_surface()
