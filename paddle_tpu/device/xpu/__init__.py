"""paddle.device.xpu parity surface (XPU hardware is not part of the
TPU build; reference: python/paddle/device/xpu/__init__.py)."""

__all__ = ["synchronize"]


def synchronize(device=None):
    raise NotImplementedError("XPU devices are not part of the TPU build")
