"""paddle_tpu.device — device management (paddle.device parity).

Reference parity: python/paddle/device/__init__.py (set_device :277,
get_device :309, get_all_device_type :349, Event :457, Stream :633,
current_stream :857, stream_guard :953, synchronize :1020).

TPU-native design: there is no user-visible stream on TPU — XLA owns
scheduling and JAX dispatch is async by default. ``Stream``/``Event`` are
kept as ordering facades: recording an event captures the set of in-flight
arrays; synchronizing blocks until they are ready. This preserves the
reference's compute/comm-overlap idioms without pretending to own the
hardware queues.
"""
from __future__ import annotations

from typing import List, Optional

from ..core.place import (device_count, get_device, is_compiled_with_cuda,
                          set_device)
from . import memory
from . import cuda  # noqa: F401
from . import xpu  # noqa: F401  # noqa: F401
from .memory import (empty_cache, max_memory_allocated, max_memory_reserved,
                     memory_allocated, memory_reserved, memory_stats)

__all__ = [
    "set_device", "get_device", "device_count", "is_compiled_with_cuda",
    "get_all_device_type", "get_available_device", "synchronize",
    "Stream", "Event", "current_stream", "set_stream", "stream_guard",
    "is_compiled_with_xpu", "is_compiled_with_ipu",
    "is_compiled_with_custom_device", "get_all_custom_device_type",
    "get_available_custom_device", "memory_allocated", "memory_reserved",
    "max_memory_allocated", "max_memory_reserved", "memory_stats",
    "empty_cache",
]


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str) -> bool:
    # TPU is our first-class device, surfaced the way the reference surfaces
    # plugin devices (reference: phi/backends/device_manager.h:134).
    return device_type in ("tpu",)


def get_all_device_type() -> List[str]:
    import jax
    return sorted({d.platform for d in jax.devices()} | {"cpu"})


def get_all_custom_device_type() -> List[str]:
    return [t for t in get_all_device_type() if t not in ("cpu", "gpu")]


def get_available_device() -> List[str]:
    import jax
    out = []
    for d in jax.devices():
        name = d.platform if d.platform == "cpu" else f"{d.platform}:{d.id}"
        out.append(name)
    return out


def get_available_custom_device() -> List[str]:
    return [d for d in get_available_device() if not d.startswith(("cpu", "gpu"))]


def synchronize(device: Optional[str] = None) -> None:
    """Block until all dispatched work on the device is complete."""
    import jax
    # The per-device dispatch queue is FIFO: enqueue a trivial computation and
    # drain it — everything dispatched earlier has then finished (the TPU
    # analog of cudaDeviceSynchronize). effects_barrier alone would only wait
    # on side-effecting computations, not plain jit dispatches.
    (jax.device_put(0.0) + 0).block_until_ready()
    jax.effects_barrier()


class Event:
    """Ordering fence. ``record`` snapshots in-flight arrays; ``synchronize``
    blocks on them; ``query`` polls readiness."""

    def __init__(self, device=None, enable_timing: bool = False,
                 blocking: bool = False, interprocess: bool = False):
        self._arrays: list = []
        self._time = None
        self.enable_timing = enable_timing

    def record(self, stream: Optional["Stream"] = None):
        import time
        if stream is not None:
            self._arrays = list(stream._pending)
        if self.enable_timing:
            synchronize()
            self._time = time.perf_counter()

    def query(self) -> bool:
        for a in self._arrays:
            if hasattr(a, "is_ready") and not a.is_ready():
                return False
        return True

    def synchronize(self):
        for a in self._arrays:
            if hasattr(a, "block_until_ready"):
                a.block_until_ready()
        if not self._arrays:
            synchronize()

    def elapsed_time(self, end_event: "Event") -> float:
        if self._time is None or end_event._time is None:
            return 0.0
        return (end_event._time - self._time) * 1e3


class Stream:
    """Async-dispatch facade. JAX dispatch is already asynchronous; a Stream
    tracks arrays launched "on" it so waits/events have real semantics."""

    def __init__(self, device=None, priority: int = 2):
        self._pending: list = []
        self.device = device
        self.priority = priority

    def track(self, *arrays):
        self._pending.extend(a for a in arrays if hasattr(a, "block_until_ready"))
        if len(self._pending) > 256:
            self._pending = self._pending[-256:]

    def record_event(self, event: Optional[Event] = None) -> Event:
        event = event or Event()
        event.record(self)
        return event

    def wait_event(self, event: Event):
        event.synchronize()

    def wait_stream(self, stream: "Stream"):
        for a in stream._pending:
            a.block_until_ready()

    def query(self) -> bool:
        return all(not hasattr(a, "is_ready") or a.is_ready()
                   for a in self._pending)

    def synchronize(self):
        for a in self._pending:
            a.block_until_ready()
        self._pending = []


_current_stream = Stream()


def current_stream(device=None) -> Stream:
    return _current_stream


def set_stream(stream: Stream) -> Stream:
    global _current_stream
    prev, _current_stream = _current_stream, stream
    return prev


class stream_guard:
    def __init__(self, stream: Stream):
        self._stream = stream
        self._prev = None

    def __enter__(self):
        self._prev = set_stream(self._stream)
        return self._stream

    def __exit__(self, *exc):
        set_stream(self._prev)
        return False


def get_cudnn_version():
    """No cuDNN on TPU (reference returns None when not compiled with CUDA)."""
    return None


def is_compiled_with_cinn() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_distribute() -> bool:
    return True


class XPUPlace:
    def __init__(self, *a, **k):
        raise NotImplementedError("XPU devices are not part of the TPU build")


class IPUPlace:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU devices are not part of the TPU build")


# ---------------------------------------------------------------------------
# Host-side model construction (TPU-first init path).
#
# Reference: the LazyGuard / LazyInit flow (python/paddle/nn/initializer/
# lazy_init.py) exists because materializing parameters one op at a time
# on the accelerator is slow. On a tunneled TPU it is pathological: each
# eager init op is a ~0.3-1s round-trip, so a 500-tensor model costs
# minutes before the first step. host_init() runs construction on the
# host CPU backend (fast, no tunnel), and to_accelerator() then moves
# the finished parameter set in ONE bulk jax.device_put.
# ---------------------------------------------------------------------------

class host_init:
    """Context manager: build models on the host CPU backend.

    >>> with paddle.device.host_init():
    ...     model = UNet2DConditionModel(cfg)   # fast host-side init
    ...     model.bfloat16()
    >>> paddle.device.to_accelerator(model)      # one bulk transfer

    No-op (but harmless) when the process has no accelerator.

    When it pays: on hosts with a direct (PCIe) accelerator link, where
    the bulk transfer is fast and eager init round-trips are the cost.
    Measured on THIS image's tunneled chip (2026-07-31, 588M-param
    UNet): on-device init 140s vs host init 122s + bulk transfer 97s —
    the ~12 MB/s tunnel makes on-device init the better default here,
    so nothing in-tree forces this path; it's an opt-in.
    """

    def __enter__(self):
        import jax

        try:
            cpu = jax.local_devices(backend="cpu")[0]
        except RuntimeError:
            self._ctx = None
            return self
        self._ctx = jax.default_device(cpu)
        self._ctx.__enter__()
        return self

    def __exit__(self, *exc):
        if self._ctx is not None:
            self._ctx.__exit__(*exc)
        return False


def to_accelerator(layer_or_tensors, device=None):
    """Move a Layer's parameters+buffers (or a list of Tensors) to the
    accelerator in one bulk ``jax.device_put`` — a single tunneled
    transfer instead of one round-trip per tensor."""
    import jax

    if device is None:
        accel = [d for d in jax.devices() if d.platform != "cpu"]
        if not accel:
            return layer_or_tensors
        device = accel[0]

    if hasattr(layer_or_tensors, "parameters"):
        tensors = list(layer_or_tensors.parameters())
        try:
            tensors += [b for b in layer_or_tensors.buffers()]
        except Exception:
            pass
    else:
        tensors = list(layer_or_tensors)
    values = [t._value for t in tensors]
    moved = jax.device_put(values, device)
    for t, v in zip(tensors, moved):
        t._replace_value(v)
    return layer_or_tensors
