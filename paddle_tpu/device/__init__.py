"""device namespace (paddle.device parity)."""
from ..core.place import set_device, get_device, device_count, is_compiled_with_cuda
def synchronize():
    import jax
    (jax.device_put(0.0) + 0).block_until_ready()

