"""Device memory statistics — the allocator-facade's stats surface.

Reference parity: paddle/fluid/memory/allocation/allocator_facade.h:45 and
stats (memory/stats.h); Python surface paddle.device.cuda.memory_allocated
etc. On TPU the allocator is PJRT's (BFC arena inside the runtime); we
surface its live statistics via ``Device.memory_stats()`` rather than
re-implementing an arena the runtime already owns.
"""
from __future__ import annotations

from typing import Dict, Optional


def _device(device_id: Optional[int] = None):
    import jax
    devs = jax.local_devices()
    return devs[device_id or 0]


def memory_stats(device_id: Optional[int] = None) -> Dict[str, int]:
    d = _device(device_id)
    try:
        return dict(d.memory_stats() or {})
    except Exception:
        return {}


def memory_allocated(device_id: Optional[int] = None) -> int:
    return memory_stats(device_id).get("bytes_in_use", 0)


def max_memory_allocated(device_id: Optional[int] = None) -> int:
    s = memory_stats(device_id)
    return s.get("peak_bytes_in_use", s.get("bytes_in_use", 0))


def memory_reserved(device_id: Optional[int] = None) -> int:
    s = memory_stats(device_id)
    return s.get("bytes_reserved", s.get("pool_bytes", s.get("bytes_in_use", 0)))


def max_memory_reserved(device_id: Optional[int] = None) -> int:
    s = memory_stats(device_id)
    return s.get("peak_bytes_reserved", max_memory_allocated(device_id))


def empty_cache() -> None:
    """Free cached device buffers held by dead Python references."""
    import gc
    gc.collect()


def get_device_properties(device_id: Optional[int] = None):
    d = _device(device_id)
    s = memory_stats(device_id)
    return {
        "name": getattr(d, "device_kind", str(d)),
        "platform": d.platform,
        "id": d.id,
        "total_memory": s.get("bytes_limit", 0),
    }
