"""Device memory statistics — the allocator-facade's stats surface.

Reference parity: paddle/fluid/memory/allocation/allocator_facade.h:45 and
stats (memory/stats.h); Python surface paddle.device.cuda.memory_allocated
etc. On TPU the allocator is PJRT's (BFC arena inside the runtime); we
surface its live statistics via ``Device.memory_stats()`` rather than
re-implementing an arena the runtime already owns.
"""
from __future__ import annotations

from typing import Dict, Optional


def _device(device_id: Optional[int] = None):
    import jax
    devs = jax.local_devices()
    return devs[device_id or 0]


def memory_stats(device_id: Optional[int] = None) -> Dict[str, int]:
    """Live allocator stats of one device; ``{}`` (never an exception)
    when the platform reports none — CPU PJRT returns None, and a
    missing/odd device_id must not crash telemetry samplers."""
    try:
        d = _device(device_id)
        return dict(d.memory_stats() or {})
    except Exception:
        return {}


def live_array_bytes() -> int:
    """Sum of bytes held by live jax arrays in this process — the
    backend-independent fallback for platforms whose PJRT client reports
    no allocator stats (CPU). An under-count of true allocator usage
    (no fragmentation, no runtime scratch) but moves with the workload."""
    try:
        import jax

        return int(sum(int(getattr(a, "nbytes", 0))
                       for a in jax.live_arrays()))
    except Exception:
        return 0


def memory_allocated(device_id: Optional[int] = None) -> int:
    return memory_stats(device_id).get("bytes_in_use", 0)


def max_memory_allocated(device_id: Optional[int] = None) -> int:
    s = memory_stats(device_id)
    return s.get("peak_bytes_in_use", s.get("bytes_in_use", 0))


def memory_reserved(device_id: Optional[int] = None) -> int:
    s = memory_stats(device_id)
    return s.get("bytes_reserved", s.get("pool_bytes", s.get("bytes_in_use", 0)))


def max_memory_reserved(device_id: Optional[int] = None) -> int:
    s = memory_stats(device_id)
    return s.get("peak_bytes_reserved", max_memory_allocated(device_id))


def empty_cache() -> None:
    """Free cached device buffers held by dead Python references."""
    import gc
    gc.collect()


def get_device_properties(device_id: Optional[int] = None):
    d = _device(device_id)
    s = memory_stats(device_id)
    return {
        "name": getattr(d, "device_kind", str(d)),
        "platform": d.platform,
        "id": d.id,
        "total_memory": s.get("bytes_limit", 0),
    }


def compiled_memory_stats(jitted_fn, *args) -> Dict[str, int]:
    """Compiler-reported memory budget of a jitted function at these
    argument shapes: {temp, argument, output, alias, generated_code}
    bytes. This is XLA's buffer-assignment result — the deterministic
    analog of peeking allocator stats after a run, and the measurement
    the recompute pass is judged by (reference: the memory estimates in
    auto_parallel/static/cost_model used by auto_parallel_recompute)."""
    try:
        compiled = jitted_fn.lower(*args).compile()
        ma = compiled.memory_analysis()
    except Exception:
        # telemetry surface: a backend without memory analysis (or a fn
        # that won't lower at these args) yields {}, never an exception
        return {}
    if ma is None:
        return {}
    return {
        "temp_size_in_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
        "argument_size_in_bytes": int(
            getattr(ma, "argument_size_in_bytes", 0)),
        "output_size_in_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
        "alias_size_in_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
        "generated_code_size_in_bytes": int(
            getattr(ma, "generated_code_size_in_bytes", 0)),
    }


def vjp_residual_bytes(fn, *args) -> int:
    """Bytes of residuals saved between forward and backward of ``fn``
    at these arguments — the fwd->bwd live set that activation
    recomputation (auto_parallel_recompute / jax.checkpoint) shrinks.
    Backend-independent, unlike buffer-assignment temp sizes (the CPU
    backend reports those as 0)."""
    import jax

    _, vjp_fn = jax.vjp(fn, *args)
    total = 0
    for leaf in jax.tree_util.tree_leaves(vjp_fn):
        if hasattr(leaf, "dtype") and hasattr(leaf, "size"):
            total += int(leaf.size) * leaf.dtype.itemsize
    return total
