"""paddle.device.cuda parity surface mapped onto the TPU runtime.

Reference: python/paddle/device/cuda/__init__.py. On TPU, "cuda" calls
mean "the accelerator": synchronization flushes the dispatch queue,
memory stats come from the PJRT allocator surface (device/memory.py),
and Stream/Event are ordering markers — XLA's data-dependency scheduler
owns real stream assignment, so recording/waiting are host-side fences.
"""
from __future__ import annotations

import contextlib

__all__ = [
    "Stream", "Event", "current_stream", "synchronize", "device_count",
    "empty_cache", "max_memory_allocated", "max_memory_reserved",
    "memory_allocated", "memory_reserved", "stream_guard",
    "get_device_properties", "get_device_name", "get_device_capability",
]


def _devices():
    import jax

    return [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()


def device_count() -> int:
    return len(_devices())


def synchronize(device=None):
    """Block until all dispatched work on the accelerator finished."""
    import jax

    try:
        jax.effects_barrier()
    except Exception:
        pass
    for d in _devices():
        try:
            d.synchronize_all_activity()
        except Exception:
            break


class Stream:
    """Ordering marker (reference: core.CUDAStream). XLA schedules real
    streams; two Streams here only order host-side dispatch."""

    def __init__(self, device=None, priority=2):
        self.device = device
        self.priority = priority

    def synchronize(self):
        synchronize(self.device)

    def wait_event(self, event):
        event.synchronize()

    def wait_stream(self, stream):
        stream.synchronize()

    def record_event(self, event=None):
        event = event or Event()
        event.record(self)
        return event

    def query(self) -> bool:
        return True


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        self._t = None

    def record(self, stream=None):
        import time

        self._t = time.perf_counter()

    def query(self) -> bool:
        return True

    def synchronize(self):
        synchronize()

    def elapsed_time(self, end_event) -> float:
        if self._t is None or end_event._t is None:
            return 0.0
        return (end_event._t - self._t) * 1e3


_current = Stream()


def current_stream(device=None) -> Stream:
    return _current


@contextlib.contextmanager
def stream_guard(stream):
    global _current
    prev, _current = _current, stream
    try:
        yield
    finally:
        _current = prev


def empty_cache():
    from .. import memory as _memory

    if hasattr(_memory, "empty_cache"):
        _memory.empty_cache()


def _mem_stat(kind: str, device=None) -> int:
    from .. import memory as _memory

    fn = getattr(_memory, kind, None)
    return int(fn(device)) if fn is not None else 0


def memory_allocated(device=None) -> int:
    return _mem_stat("memory_allocated", device)


def max_memory_allocated(device=None) -> int:
    return _mem_stat("max_memory_allocated", device)


def memory_reserved(device=None) -> int:
    return _mem_stat("memory_reserved", device)


def max_memory_reserved(device=None) -> int:
    return _mem_stat("max_memory_reserved", device)


def get_device_properties(device=None):
    import collections

    d = _devices()[0]
    Props = collections.namedtuple(
        "DeviceProperties",
        ["name", "major", "minor", "total_memory", "multi_processor_count"])
    stats = {}
    try:
        stats = d.memory_stats() or {}
    except Exception:
        pass
    return Props(name=str(d.device_kind), major=0, minor=0,
                 total_memory=stats.get("bytes_limit", 0),
                 multi_processor_count=1)


def get_device_name(device=None) -> str:
    return str(_devices()[0].device_kind)


def get_device_capability(device=None):
    return (0, 0)  # TPU: no CUDA compute capability
