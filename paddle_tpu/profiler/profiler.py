"""Profiler facade: host spans + XLA device trace (xplane) + chrome export.

Reference parity: python/paddle/profiler/profiler.py —
ProfilerState (:79), ProfilerTarget (:99), make_scheduler (:117),
export_chrome_tracing (:215), Profiler (:346).

TPU-native design: the reference stitches a CUPTI device tracer and a host
tracer into one event tree. On TPU the device side is owned by XLA's
profiler — ``jax.profiler.start_trace`` captures xplane/perfetto data
(MXU/HBM utilisation, per-HLO timing) which TensorBoard renders. We run
both: our HostTracer records the Python-side spans (exportable as
chrome-trace), and when ``ProfilerTarget.TPU`` is requested the XLA trace
is captured into the same log dir.
"""
from __future__ import annotations

import json
import os
import time
from enum import Enum
from typing import Callable, Iterable, Optional, Union

from .host_tracer import TracerEventType, get_host_tracer
from .statistic import summary_table
from .utils import RecordEvent, _set_profiler_mode


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


def make_scheduler(*, closed: int, ready: int, record: int, repeat: int = 0,
                   skip_first: int = 0) -> Callable[[int], ProfilerState]:
    """Window scheduler: skip_first → [closed → ready → record]*repeat."""
    num_steps = closed + ready + record

    def getter(step: int) -> ProfilerState:
        assert step >= 0
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        period = step // num_steps
        if repeat > 0 and period >= repeat:
            return ProfilerState.CLOSED
        pos = step % num_steps
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == num_steps - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    assert closed >= 0 and ready >= 0 and record > 0 and repeat >= 0
    return getter


def _default_state_scheduler(step: int) -> ProfilerState:
    return ProfilerState.RECORD


def export_chrome_tracing(dir_name: str,
                          worker_name: Optional[str] = None) -> Callable:
    """on_trace_ready callback writing chrome-trace json into dir_name."""

    def handle(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        name = worker_name or f"host_{os.getpid()}"
        path = os.path.join(dir_name, f"{name}_time_{prof._span_idx}.json")
        prof.export(path, format="json")

    return handle


def export_protobuf(dir_name: str,
                    worker_name: Optional[str] = None) -> Callable:
    # No protobuf schema of our own; the XLA xplane capture in log_dir is the
    # binary artifact. Host spans still get a chrome-trace dump.
    return export_chrome_tracing(dir_name, worker_name)


def _get_supported_targets() -> Iterable[ProfilerTarget]:
    targets = [ProfilerTarget.CPU]
    try:
        import jax
        if any(d.platform == "tpu" for d in jax.devices()):
            targets.append(ProfilerTarget.TPU)
    except Exception:
        pass
    return targets


class Profiler:
    """Collect host spans and (on TPU) an XLA device trace over scheduled
    step windows.

    Usage::

        p = profiler.Profiler(targets=[profiler.ProfilerTarget.CPU],
                              scheduler=(2, 5))
        p.start()
        for it, batch in enumerate(loader):
            train_step(batch)
            p.step()
        p.stop()
        p.summary()
    """

    def __init__(self, *, targets: Optional[Iterable[ProfilerTarget]] = None,
                 scheduler: Union[Callable, tuple, None] = None,
                 on_trace_ready: Optional[Callable] = None,
                 record_shapes: bool = False, profile_memory: bool = False,
                 timer_only: bool = False, emit_nvtx: bool = False,
                 custom_device_types: list = []):
        self.targets = list(targets) if targets else list(_get_supported_targets())
        if callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)):
            start, end = scheduler
            self._scheduler = make_scheduler(
                closed=max(start - 1, 0), ready=min(start, 1),
                record=end - start, repeat=1)
        else:
            self._scheduler = _default_state_scheduler
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.record_shapes = record_shapes
        self.profile_memory = profile_memory
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._span_idx = 0
        self._events = []
        self._device_tracing = False
        self._record_step_event: Optional[RecordEvent] = None
        self.log_dir = os.environ.get("PADDLE_TPU_PROFILER_DIR",
                                      "./profiler_log")

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        from .timer import benchmark
        benchmark().step()
        if self.timer_only:
            return
        self.current_state = self._scheduler(self.step_num)
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._start_record()
        self._open_step_span()

    def stop(self):
        from .timer import benchmark
        benchmark().step()
        if self.timer_only:
            return
        self._close_step_span()
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._stop_record()
            if self.on_trace_ready:
                self.on_trace_ready(self)
            self._span_idx += 1
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        from .timer import benchmark
        benchmark().step(num_samples or 0)
        if self.timer_only:
            self.step_num += 1
            return
        self._close_step_span()
        prev = self.current_state
        self.step_num += 1
        self.current_state = self._scheduler(self.step_num)
        recording = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        # RECORD_AND_RETURN always ends its window, even when the next window
        # starts immediately (closed=0, ready=0, repeat>1 back-to-back case)
        window_closed = prev == ProfilerState.RECORD_AND_RETURN or (
            prev in recording and self.current_state not in recording)
        if window_closed:
            self._stop_record()
            if self.on_trace_ready:
                self.on_trace_ready(self)
            self._span_idx += 1
            if self.current_state in recording:
                self._start_record()
        elif prev not in recording and self.current_state in recording:
            self._start_record()
        self._open_step_span()

    def step_info(self, unit: str = "samples") -> str:
        from .timer import benchmark
        return benchmark().step_info(unit)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- internals ---------------------------------------------------------
    def _open_step_span(self):
        if self.current_state in (ProfilerState.RECORD,
                                  ProfilerState.RECORD_AND_RETURN):
            self._record_step_event = RecordEvent(
                f"ProfileStep#{self.step_num}", TracerEventType.ProfileStep)
            self._record_step_event.begin()

    def _close_step_span(self):
        if self._record_step_event is not None:
            self._record_step_event.end()
            self._record_step_event = None

    def _start_record(self):
        get_host_tracer().start()
        _set_profiler_mode(True)
        from .utils import _native_tracer
        nat = _native_tracer()
        if nat is not None:
            nat.clear()
            nat.enable(True)
        if ProfilerTarget.TPU in self.targets or ProfilerTarget.GPU in self.targets:
            try:
                import jax.profiler as jp
                os.makedirs(self.log_dir, exist_ok=True)
                jp.start_trace(self.log_dir)
                self._device_tracing = True
                self._device_trace_started = time.time()
            except Exception:
                self._device_tracing = False

    def _stop_record(self):
        _set_profiler_mode(False)
        from .utils import _native_tracer
        nat = _native_tracer()
        if nat is not None:
            nat.enable(False)
            self._native_events = json.loads(nat.export_json())
        if self._device_tracing:
            try:
                import jax.profiler as jp
                jp.stop_trace()
                self._device_trace_captured = True
            except Exception:
                pass
            self._device_tracing = False
        self._events = get_host_tracer().stop()

    # -- results -----------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        """Write collected host spans as a chrome trace."""
        from .host_tracer import flatten_events
        traces = []
        for ev in flatten_events(self._events):
            traces.append({
                "name": ev.name, "ph": "X", "cat": ev.type,
                "ts": ev.start_ns / 1e3, "dur": ev.duration_ns / 1e3,
                "pid": os.getpid(), "tid": ev.thread_id,
            })
        # Merge spans recorded by the native (C++) tracer — e.g. dataloader
        # worker threads and counters. RecordEvent mirrors its spans into the
        # native tracer too (so pure-C consumers see them); skip those here
        # to avoid duplicating what the host tracer already exported.
        py_cats = {v for k, v in vars(TracerEventType).items()
                   if not k.startswith("_")}
        open_stack: dict = {}  # tid -> [was_mirrored_span, ...] (LIFO)
        for ev in getattr(self, "_native_events", []):
            tid = ev.get("tid")
            ph = ev.get("ph")
            if ph == "B":
                mirrored = ev.get("cat") in py_cats
                open_stack.setdefault(tid, []).append(mirrored)
                if mirrored:
                    continue
            elif ph == "E":
                stack = open_stack.get(tid) or [False]
                if stack.pop():
                    continue
            ev = dict(ev)
            ev.setdefault("cat", "native")
            traces.append(ev)
        # Merge device (TPU) events decoded from the XLA xplane capture, so
        # one chrome trace holds both host and device timelines — the
        # reference's ChromeTracingLogger shape. Gated on a capture having
        # happened THIS session (plus an mtime filter) so a stale
        # xplane.pb left in log_dir by an earlier run is never merged.
        if getattr(self, "_device_trace_captured", False):
            from .xplane import device_trace_events
            traces.extend(device_trace_events(
                self.log_dir,
                newer_than=getattr(self, "_device_trace_started", 0.0)))
        with open(path, "w") as f:
            json.dump({"traceEvents": traces,
                       "displayTimeUnit": "ms"}, f)
        return traces

    def summary(self, sorted_by=SummaryView.OverView, op_detail: bool = True,
                thread_sep: bool = False, time_unit: str = "ms"):
        """Host-span table plus, when a device capture happened, the
        per-op and op-class device tables (reference:
        profiler_statistic.py's operator + kernel summaries)."""
        table = summary_table(self._events, time_unit=time_unit)
        if getattr(self, "_device_trace_captured", False):
            from .statistic import device_summary_table
            from .xplane import device_trace_events

            try:
                devs = device_trace_events(
                    self.log_dir,
                    newer_than=getattr(self, "_device_trace_started", 0.0))
            except Exception:
                devs = []
            if devs:
                table += "\n\n" + device_summary_table(devs, by="op")
                if op_detail:
                    table += "\n\n" + device_summary_table(devs, by="class")
        print(table)
        return table

    def get_summary(self) -> str:
        return summary_table(self._events)


def get_profiler(config_path: Optional[str] = None) -> Profiler:
    return Profiler()
