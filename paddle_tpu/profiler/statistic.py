"""Summary statistics over collected host events.

Reference parity: python/paddle/profiler/profiler_statistic.py (summary
tables by event type / name: calls, total, avg, max, min, ratio).
"""
from __future__ import annotations

from typing import Dict, List

from .host_tracer import HostEvent, flatten_events


class _Item:
    __slots__ = ("name", "calls", "total_ns", "max_ns", "min_ns")

    def __init__(self, name: str):
        self.name = name
        self.calls = 0
        self.total_ns = 0
        self.max_ns = 0
        self.min_ns = None

    def add(self, ns: int):
        self.calls += 1
        self.total_ns += ns
        self.max_ns = max(self.max_ns, ns)
        self.min_ns = ns if self.min_ns is None else min(self.min_ns, ns)

    @property
    def avg_ns(self):
        return self.total_ns / self.calls if self.calls else 0.0


def collect_statistic(roots: List[HostEvent]) -> Dict[str, _Item]:
    items: Dict[str, _Item] = {}
    for ev in flatten_events(roots):
        it = items.setdefault(ev.name, _Item(ev.name))
        it.add(ev.duration_ns)
    return items


def _fmt_ms(ns) -> str:
    return f"{ns / 1e6:.3f}"


def summary_table(roots: List[HostEvent], sorted_by: str = "total",
                  time_unit: str = "ms") -> str:
    items = sorted(collect_statistic(roots).values(),
                   key=lambda it: -it.total_ns if sorted_by == "total"
                   else -it.avg_ns)
    wall = sum(r.duration_ns for r in roots) or 1
    header = (f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
              f"{'Max(ms)':>10}{'Min(ms)':>10}{'Ratio(%)':>10}")
    lines = ["-" * len(header), header, "-" * len(header)]
    for it in items:
        lines.append(
            f"{it.name[:39]:<40}{it.calls:>8}{_fmt_ms(it.total_ns):>12}"
            f"{_fmt_ms(it.avg_ns):>10}{_fmt_ms(it.max_ns):>10}"
            f"{_fmt_ms(it.min_ns or 0):>10}{100.0 * it.total_ns / wall:>10.2f}")
    lines.append("-" * len(header))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Device (XLA op) statistics over the xplane-decoded chrome events the
# profiler exports (reference: profiler_statistic.py's kernel/op summary
# tables — there fed by CUPTI kernel records, here by the TPU xplane).
# ---------------------------------------------------------------------------

#: chrome-trace lanes that carry actual op executions (xplane.py emits
#: async DMA lanes and step/module framing lanes alongside)
_OP_LANES = ("XLA Ops",)


def op_class(base_name: str) -> str:
    """Map an HLO op base name to a coarse class for the overview table."""
    n = base_name.lower()
    if "convolution" in n:
        return "convolution"
    if "dot" in n or "matmul" in n or "gemm" in n:
        return "matmul"
    if n.startswith("_") or "custom-call" in n:
        return "custom-call (pallas)"
    if n.startswith(("copy", "slice", "async-copy", "dynamic-slice",
                     "dynamic-update-slice", "bitcast", "transpose",
                     "reshape")):
        return "data-movement"
    if "fusion" in n:
        return "fusion"
    if n.startswith(("all-reduce", "all-gather", "reduce-scatter",
                     "collective-permute", "all-to-all")):
        return "collective"
    return "other"


def _base_name(name: str) -> str:
    # HLO instruction names are <op>.<id>; strip the numeric id so all
    # instances of one op aggregate (fusion.1, fusion.42 -> fusion)
    head, _, tail = name.rpartition(".")
    if head and tail.isdigit():
        return head
    return name


def collect_device_statistic(trace_events, by: str = "op",
                             lanes=_OP_LANES) -> Dict[str, _Item]:
    """Aggregate exported chrome events with cat == 'device'.

    by='op' groups HLO base names; by='class' groups op_class buckets.
    Durations in the chrome export are microseconds; items store ns so
    the host/device tables share formatting.
    """
    items: Dict[str, _Item] = {}
    for ev in trace_events:
        if not isinstance(ev, dict) or ev.get("cat") != "device":
            continue
        if lanes is not None and ev.get("tid") not in lanes:
            continue
        base = _base_name(str(ev.get("name", "")))
        key = op_class(base) if by == "class" else base
        it = items.setdefault(key, _Item(key))
        it.add(int(float(ev.get("dur", 0.0)) * 1e3))
    return items


def device_summary_table(trace_events, sorted_by: str = "total",
                         by: str = "op", top: int = 30) -> str:
    """Per-op device-time table (the kernel summary of the reference)."""
    items = sorted(collect_device_statistic(trace_events, by=by).values(),
                   key=lambda it: -it.total_ns if sorted_by == "total"
                   else -it.avg_ns)
    wall = sum(it.total_ns for it in items) or 1
    title = "Device (XLA op) Summary" if by == "op" \
        else "Device Op-Class Summary"
    header = (f"{'Op':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
              f"{'Max(ms)':>10}{'Min(ms)':>10}{'Ratio(%)':>10}")
    lines = [title, "-" * len(header), header, "-" * len(header)]
    for it in items[:top]:
        lines.append(
            f"{it.name[:39]:<40}{it.calls:>8}{_fmt_ms(it.total_ns):>12}"
            f"{_fmt_ms(it.avg_ns):>10}{_fmt_ms(it.max_ns):>10}"
            f"{_fmt_ms(it.min_ns or 0):>10}{100.0 * it.total_ns / wall:>10.2f}")
    lines.append("-" * len(header))
    return "\n".join(lines)


def statistic_from_trace(path: str, by: str = "op") -> Dict[str, _Item]:
    """Per-op device statistics from a saved chrome trace (the file
    ``Profiler.export`` / bench.py write)."""
    import json

    with open(path) as f:
        d = json.load(f)
    evs = d.get("traceEvents", d) if isinstance(d, dict) else d
    return collect_device_statistic(evs, by=by)
