"""Summary statistics over collected host events.

Reference parity: python/paddle/profiler/profiler_statistic.py (summary
tables by event type / name: calls, total, avg, max, min, ratio).
"""
from __future__ import annotations

from typing import Dict, List

from .host_tracer import HostEvent, flatten_events


class _Item:
    __slots__ = ("name", "calls", "total_ns", "max_ns", "min_ns")

    def __init__(self, name: str):
        self.name = name
        self.calls = 0
        self.total_ns = 0
        self.max_ns = 0
        self.min_ns = None

    def add(self, ns: int):
        self.calls += 1
        self.total_ns += ns
        self.max_ns = max(self.max_ns, ns)
        self.min_ns = ns if self.min_ns is None else min(self.min_ns, ns)

    @property
    def avg_ns(self):
        return self.total_ns / self.calls if self.calls else 0.0


def collect_statistic(roots: List[HostEvent]) -> Dict[str, _Item]:
    items: Dict[str, _Item] = {}
    for ev in flatten_events(roots):
        it = items.setdefault(ev.name, _Item(ev.name))
        it.add(ev.duration_ns)
    return items


def _fmt_ms(ns) -> str:
    return f"{ns / 1e6:.3f}"


def summary_table(roots: List[HostEvent], sorted_by: str = "total",
                  time_unit: str = "ms") -> str:
    items = sorted(collect_statistic(roots).values(),
                   key=lambda it: -it.total_ns if sorted_by == "total"
                   else -it.avg_ns)
    wall = sum(r.duration_ns for r in roots) or 1
    header = (f"{'Name':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>10}"
              f"{'Max(ms)':>10}{'Min(ms)':>10}{'Ratio(%)':>10}")
    lines = ["-" * len(header), header, "-" * len(header)]
    for it in items:
        lines.append(
            f"{it.name[:39]:<40}{it.calls:>8}{_fmt_ms(it.total_ns):>12}"
            f"{_fmt_ms(it.avg_ns):>10}{_fmt_ms(it.max_ns):>10}"
            f"{_fmt_ms(it.min_ns or 0):>10}{100.0 * it.total_ns / wall:>10.2f}")
    lines.append("-" * len(header))
    return "\n".join(lines)
