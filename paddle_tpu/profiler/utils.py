"""RecordEvent and profiler-mode helpers.

Reference parity: python/paddle/profiler/utils.py:43 (RecordEvent),
:153 (load_profiler_result), :182 (in_profiler_mode). TPU-native twist:
while a device trace is active, each span is also emitted as a
jax.profiler.TraceAnnotation so host spans line up with XLA device
activity in the xplane/perfetto view.
"""
from __future__ import annotations

import json
from typing import Any, Optional

from .host_tracer import TracerEventType, get_host_tracer

_profiler_active = False


def _native_tracer():
    """Native C++ tracer class, or None (lazy; see csrc/ptpu_tracer.cc)."""
    global _NATIVE_TRACER
    if _NATIVE_TRACER is False:
        try:
            from paddle_tpu import native

            _NATIVE_TRACER = native.NativeTracer if native.is_available() \
                else None
        except Exception:
            _NATIVE_TRACER = None
    return _NATIVE_TRACER


_NATIVE_TRACER: Any = False


def _set_profiler_mode(on: bool):
    global _profiler_active
    _profiler_active = on


def in_profiler_mode() -> bool:
    return _profiler_active


class RecordEvent:
    """Context-manager/decorator marking a named host span.

    Usage::

        with profiler.RecordEvent("forward"):
            loss = model(x)
    """

    def __init__(self, name: str,
                 event_type: str = TracerEventType.PythonUserDefined):
        self.name = name
        self.event_type = event_type
        self._ev = None
        self._jax_ann = None

    def begin(self):
        tracer = get_host_tracer()
        if tracer.enabled:
            self._ev = tracer.push(self.name, self.event_type)
            nat = _native_tracer()
            if nat is not None and nat.enabled():
                nat.begin(self.name, self.event_type)
                self._nat_open = True
        if in_profiler_mode():
            try:
                import jax.profiler as jp
                self._jax_ann = jp.TraceAnnotation(self.name)
                self._jax_ann.__enter__()
            except Exception:
                self._jax_ann = None

    def end(self):
        if self._jax_ann is not None:
            self._jax_ann.__exit__(None, None, None)
            self._jax_ann = None
        if self._ev is not None:
            get_host_tracer().pop(self._ev)
            self._ev = None
            if getattr(self, "_nat_open", False):
                self._nat_open = False
                nat = _native_tracer()
                if nat is not None:
                    nat.end()

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with RecordEvent(self.name, self.event_type):
                return fn(*args, **kwargs)

        return wrapper


def load_profiler_result(filename: str) -> Any:
    """Load a chrome-trace json previously exported by the profiler."""
    with open(filename) as f:
        return json.load(f)
