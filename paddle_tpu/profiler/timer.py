"""Step/throughput timing (ips) used by hapi's fit loop.

Reference parity: python/paddle/profiler/timer.py:304 (TimeAverager),
:351 (Benchmark), :448 (benchmark()).
"""
from __future__ import annotations

import time


class TimeAverager:
    def __init__(self):
        self.reset()

    def reset(self):
        self._total = 0.0
        self._count = 0
        self._total_samples = 0

    def record(self, usetime: float, num_samples: int = 0):
        self._total += usetime
        self._count += 1
        self._total_samples += num_samples

    def get_average(self) -> float:
        return self._total / self._count if self._count else 0.0

    def get_ips_average(self) -> float:
        return self._total_samples / self._total if self._total > 0 else 0.0


class Benchmark:
    """Tracks reader/batch cost and instantaneous ips across steps."""

    def __init__(self):
        self.reader = TimeAverager()
        self.batch = TimeAverager()
        self._batch_start = None
        self._reader_start = None
        self.num_samples = 0
        self.current_event = self

    def before_reader(self):
        self._reader_start = time.perf_counter()

    def after_reader(self):
        if self._reader_start is not None:
            self.reader.record(time.perf_counter() - self._reader_start)

    def step(self, num_samples: int = 0):
        now = time.perf_counter()
        if self._batch_start is not None:
            self.batch.record(now - self._batch_start, num_samples)
        self._batch_start = now

    def step_info(self, unit: str = "samples") -> str:
        ips = self.batch.get_ips_average()
        out = (f"avg_batch_cost: {self.batch.get_average():.5f} sec, "
               f"avg_reader_cost: {self.reader.get_average():.5f} sec")
        if ips:
            out += f", ips: {ips:.2f} {unit}/sec"
        self.reader.reset()
        self.batch.reset()
        return out


_benchmark = Benchmark()


def benchmark() -> Benchmark:
    return _benchmark
