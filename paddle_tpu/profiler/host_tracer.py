"""Host-side span tracer.

TPU-native analog of the reference's HostTracer
(reference: paddle/fluid/platform/profiler/host_tracer.h:26,
paddle/fluid/platform/profiler/event_tracing.h:43): spans opened/closed on
the host thread are collected into a per-thread event list and merged into a
tree for statistics and Chrome-trace export. Device-side activity is traced
separately via XLA's profiler (xplane) — see profiler.Profiler.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class TracerEventType:
    Operator = "Operator"
    Dataloader = "Dataloader"
    ProfileStep = "ProfileStep"
    CudaRuntime = "DeviceRuntime"
    Kernel = "Kernel"
    Memcpy = "Memcpy"
    Memset = "Memset"
    UserDefined = "UserDefined"
    OperatorInner = "OperatorInner"
    Forward = "Forward"
    Backward = "Backward"
    Optimization = "Optimization"
    Communication = "Communication"
    PythonOp = "PythonOp"
    PythonUserDefined = "PythonUserDefined"


@dataclass
class HostEvent:
    name: str
    type: str
    start_ns: int
    end_ns: int = 0
    thread_id: int = 0
    children: List["HostEvent"] = field(default_factory=list)

    @property
    def duration_ns(self) -> int:
        return max(0, self.end_ns - self.start_ns)

    def self_ns(self) -> int:
        return self.duration_ns - sum(c.duration_ns for c in self.children)


class _ThreadLocalState(threading.local):
    def __init__(self):
        self.stack: List[HostEvent] = []
        self.roots: List[HostEvent] = []


class HostTracer:
    """Collects nested host spans across threads while enabled."""

    def __init__(self):
        self._tls = _ThreadLocalState()
        self._lock = threading.Lock()
        self._all_roots: List[HostEvent] = []
        self._enabled = False

    @property
    def enabled(self) -> bool:
        return self._enabled

    def start(self):
        with self._lock:
            self._all_roots = []
        self._tls.roots = []
        self._tls.stack = []
        self._enabled = True

    def stop(self) -> List[HostEvent]:
        self._enabled = False
        self._flush_thread()
        with self._lock:
            roots, self._all_roots = self._all_roots, []
        return roots

    def push(self, name: str, type: str = TracerEventType.UserDefined) -> HostEvent:
        ev = HostEvent(name=name, type=type, start_ns=time.perf_counter_ns(),
                       thread_id=threading.get_ident())
        stack = self._tls.stack
        if stack:
            stack[-1].children.append(ev)
        else:
            self._tls.roots.append(ev)
        stack.append(ev)
        return ev

    def pop(self, ev: HostEvent):
        ev.end_ns = time.perf_counter_ns()
        stack = self._tls.stack
        while stack and stack[-1] is not ev:
            stack.pop()  # unbalanced push/pop (exception paths): close over-open spans
        if stack:
            stack.pop()
        if not stack:
            self._flush_thread()

    def _flush_thread(self):
        if self._tls.roots:
            with self._lock:
                self._all_roots.extend(self._tls.roots)
            self._tls.roots = []


_tracer = HostTracer()


def get_host_tracer() -> HostTracer:
    return _tracer


def flatten_events(roots: List[HostEvent]) -> List[HostEvent]:
    out: List[HostEvent] = []

    def rec(e: HostEvent):
        out.append(e)
        for c in e.children:
            rec(c)

    for r in roots:
        rec(r)
    return out
