"""paddle_tpu.profiler — tracing/profiling subsystem.

Reference parity: python/paddle/profiler/__init__.py:28 (__all__ surface).
Host spans via HostTracer; device tracing via XLA/jax.profiler (xplane).
"""
from .host_tracer import TracerEventType
from .profiler import (Profiler, ProfilerState, ProfilerTarget, SummaryView,
                       export_chrome_tracing, export_protobuf, get_profiler,
                       make_scheduler)
from .utils import RecordEvent, in_profiler_mode, load_profiler_result
from .statistic import (collect_device_statistic, device_summary_table,
                        op_class, statistic_from_trace, summary_table)

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "SortedKeys",
    "SummaryView", "TracerEventType", "RecordEvent", "make_scheduler",
    "export_chrome_tracing", "export_protobuf", "load_profiler_result",
    "in_profiler_mode", "get_profiler", "collect_device_statistic",
    "device_summary_table", "op_class", "statistic_from_trace",
    "summary_table",
]


class SortedKeys:
    """Summary-table sort orders (reference: profiler/profiler_statistic.py
    SortedKeys enum)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7
