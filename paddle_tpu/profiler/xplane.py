"""Minimal XSpace (xplane.pb) reader → chrome trace events.

``jax.profiler.start_trace(log_dir)`` writes the device-side trace as a
serialized ``tensorflow.profiler.XSpace`` protobuf under
``log_dir/plugins/profile/<run>/<host>.xplane.pb``.  The reference exposes
device timelines through its own ChromeTracingLogger
(/root/reference/paddle/fluid/platform/profiler/chrometracing_logger.cc);
here the device timeline comes from XLA, so we parse the xplane wire
format directly (hand-rolled varint decoder — no TF/tensorboard
dependency, which this image does not ship) and convert each device
XLine/XEvent into a chrome ``"X"`` span.

Only the fields needed for a timeline are decoded:

    XSpace   { repeated XPlane planes = 1; }
    XPlane   { int64 id = 1; string name = 2; repeated XLine lines = 3;
               map<int64, XEventMetadata> event_metadata = 4; }
    XLine    { int64 id = 1; string name = 2; int64 timestamp_ns = 3;
               repeated XEvent events = 4; string display_name = 11; }
    XEvent   { int64 metadata_id = 1; int64 offset_ps = 2;
               int64 duration_ps = 3; }
    XEventMetadata { int64 id = 1; string name = 2; string display_name=4 }
"""
from __future__ import annotations

import glob
import os
from typing import Iterator, List, Tuple


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over a message's wire bytes."""
    pos, n = 0, len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:  # varint
            val, pos = _read_varint(buf, pos)
        elif wt == 1:  # fixed64
            val = int.from_bytes(buf[pos:pos + 8], "little")
            pos += 8
        elif wt == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:  # fixed32
            val = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        else:  # group / unknown: cannot skip safely
            return
        yield field, wt, val


def _parse_event_metadata(buf: bytes) -> Tuple[int, str]:
    """map entry value: XEventMetadata {id=1, name=2, display_name=4}."""
    mid, name, display = 0, "", ""
    for field, _, val in _fields(buf):
        if field == 1:
            mid = val
        elif field == 2:
            name = val.decode("utf-8", "replace")
        elif field == 4:
            display = val.decode("utf-8", "replace")
    return mid, display or name


def _parse_map_entry(buf: bytes) -> Tuple[int, bytes]:
    key, value = 0, b""
    for field, _, val in _fields(buf):
        if field == 1:
            key = val if isinstance(val, int) else 0
        elif field == 2:
            value = val
    return key, value


def _zigzag_ok(v: int) -> int:
    # xplane int64s are plain (not zigzag); mask to signed 64-bit
    return v - (1 << 64) if v >= (1 << 63) else v


def _is_device_plane(name: str) -> bool:
    # XLA device planes are "/device:TPU:0" etc.; host planes
    # ("/host:CPU", python/TSL lines) are already covered by the host
    # tracer and must not be re-labeled as device events.
    return "/device:" in name or name.startswith(("TPU", "GPU"))


def parse_xspace(data: bytes) -> List[dict]:
    """Decode an XSpace blob into chrome trace event dicts.

    Only DEVICE planes are emitted (see _is_device_plane)."""
    traces: List[dict] = []
    for field, _, plane_buf in _fields(data):
        if field != 1:
            continue
        plane_id, plane_name = 0, ""
        lines: List[bytes] = []
        meta: dict = {}
        for pf, _, pval in _fields(plane_buf):
            if pf == 1:
                plane_id = pval
            elif pf == 2:
                plane_name = pval.decode("utf-8", "replace")
            elif pf == 3:
                lines.append(pval)
            elif pf == 4:
                k, v = _parse_map_entry(pval)
                mid, mname = _parse_event_metadata(v)
                meta[mid or k] = mname
        if not _is_device_plane(plane_name):
            continue
        for line_buf in lines:
            line_name, ts_ns = "", 0
            events: List[bytes] = []
            for lf, _, lval in _fields(line_buf):
                if lf == 2:
                    line_name = lval.decode("utf-8", "replace")
                elif lf == 3:
                    ts_ns = _zigzag_ok(lval)
                elif lf == 4:
                    events.append(lval)
                elif lf == 11 and lval:
                    line_name = lval.decode("utf-8", "replace")
            for ev_buf in events:
                mid, off_ps, dur_ps = 0, 0, 0
                for ef, _, eval_ in _fields(ev_buf):
                    if ef == 1:
                        mid = eval_
                    elif ef == 2:
                        off_ps = _zigzag_ok(eval_)
                    elif ef == 3:
                        dur_ps = _zigzag_ok(eval_)
                traces.append({
                    "name": meta.get(mid, f"event#{mid}"),
                    "ph": "X", "cat": "device",
                    # chrome trace wants microseconds
                    "ts": (ts_ns + off_ps / 1e3) / 1e3,
                    "dur": max(dur_ps / 1e6, 0.001),
                    "pid": f"{plane_name or f'plane#{plane_id}'}",
                    "tid": line_name or "line",
                })
    return traces


def device_trace_events(log_dir: str, newer_than: float = 0.0) -> List[dict]:
    """Find the newest ``*.xplane.pb`` under log_dir and decode it.

    ``newer_than`` (unix mtime) filters out stale captures from earlier
    runs sharing the same log_dir. Returns [] when no capture exists
    (CPU-only run, trace disabled).
    """
    paths = [p for p in glob.glob(os.path.join(log_dir, "plugins", "profile",
                                               "*", "*.xplane.pb"))
             if os.path.getmtime(p) >= newer_than]
    if not paths:
        return []
    path = max(paths, key=os.path.getmtime)
    try:
        with open(path, "rb") as f:
            return parse_xspace(f.read())
    except Exception:
        return []
