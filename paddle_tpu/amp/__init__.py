"""Automatic mixed precision.

Reference: python/paddle/amp/ (auto_cast.py — O1 white/black lists, O2 pure
half; grad_scaler.py — dynamic loss scaling). TPU design: bfloat16 is the
native half type (MXU), so the default amp dtype is bf16 and loss scaling is
a no-op unless float16 is requested (kept for parity).

The op-level cast hook lives here and is consulted by core.tensor.apply.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from . import debugging  # noqa: F401

_state = threading.local()

# O1 lists (subset of reference auto_cast white/black lists,
# python/paddle/amp/amp_lists.py): compute-bound ops run in half, numerically
# sensitive ops stay fp32.
WHITE_LIST = {
    "matmul", "linear_p", "linear_nobias_p", "conv_p", "conv_transpose_p",
    "einsum_1", "einsum_2", "einsum_3", "bilinear_p", "bilinear_nobias_p",
    "sdpa_p", "sdpa_mask_p", "flash_attention_p", "flash_attn_varlen_p",
}
BLACK_LIST = {
    "reduce_sum", "reduce_mean", "softmax_p", "log_softmax_p", "layer_norm_p",
    "rms_norm_p", "rms_norm_pallas_p", "batch_norm_train_p",
    "batch_norm_infer_p", "exp", "log",
    "pow_p", "hard_ce_p", "soft_ce_p", "logsumexp_p", "p_norm", "fro_norm",
    "cumsum_p",
}


class _AmpState:
    __slots__ = ("enabled", "dtype", "level", "custom_white", "custom_black")

    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"
        self.custom_white = set()
        self.custom_black = set()


def _amp_state() -> _AmpState:
    st = getattr(_state, "amp", None)
    if st is None:
        st = _state.amp = _AmpState()
    return st


def amp_cast_inputs(prim_name: str, arrays):
    """Called from core.tensor.apply for every op when amp is on."""
    st = _amp_state()
    if not st.enabled:
        return arrays
    in_white = (prim_name in WHITE_LIST or prim_name in st.custom_white) and (
        prim_name not in st.custom_black
    )
    if st.level == "O2":
        in_white = prim_name not in BLACK_LIST and prim_name not in st.custom_black
    if not in_white:
        return arrays
    out = []
    for a in arrays:
        if hasattr(a, "dtype") and a.dtype == jnp.float32:
            out.append(a.astype(st.dtype))
        else:
            out.append(a)
    return tuple(out)


def amp_active() -> bool:
    return _amp_state().enabled


from ..core.tensor import _install_amp_hook

_install_amp_hook(amp_cast_inputs)


class auto_cast:
    """paddle.amp.auto_cast parity (auto_cast.py)."""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16",
                 use_promote=True):
        self.enable = enable
        self.level = level
        self.dtype = jnp.float16 if str(dtype) in ("float16", "fp16") else jnp.bfloat16
        self.white = set(custom_white_list or [])
        self.black = set(custom_black_list or [])

    def __enter__(self):
        st = _amp_state()
        self._prev = (st.enabled, st.dtype, st.level, st.custom_white, st.custom_black)
        st.enabled = self.enable
        st.dtype = self.dtype
        st.level = self.level
        st.custom_white = self.white
        st.custom_black = self.black
        return self

    def __exit__(self, *exc):
        st = _amp_state()
        (st.enabled, st.dtype, st.level, st.custom_white, st.custom_black) = self._prev
        return False


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity: O2 casts model params to half (master
    weights live in the optimizer)."""
    from ..nn.layer import Layer

    single = isinstance(models, Layer)
    model_list = [models] if single else list(models)
    if level == "O2":
        dt = "float16" if str(dtype) in ("float16", "fp16") else "bfloat16"
        for m in model_list:
            m.to(dtype=dt)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


class GradScaler:
    """paddle.amp.GradScaler parity (grad_scaler.py). With bf16 the scale is
    1 and enable=False is recommended; dynamic scaling is implemented for
    fp16 parity."""

    def __init__(self, enable=True, init_loss_scaling=2.0**15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        from ..ops.math import scale as _scale

        return _scale(var, self._scale)

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p._grad_value is None:
                continue
            g = p._grad_value * inv if self._scale != 1.0 else p._grad_value
            if not bool(jnp.all(jnp.isfinite(g.astype(jnp.float32)))):
                found = True
            p._grad_value = g
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor._from_value(jnp.asarray(self._scale, jnp.float32))

    def state_dict(self):
        return {
            "scale": self._scale,
            "good_steps": self._good_steps,
            "bad_steps": self._bad_steps,
        }

    def load_state_dict(self, sd):
        self._scale = sd["scale"]
        self._good_steps = sd["good_steps"]
        self._bad_steps = sd["bad_steps"]


def is_bfloat16_supported(place=None):
    return True


def is_float16_supported(place=None):
    return True
