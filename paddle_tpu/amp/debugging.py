"""AMP debugging utilities.

Reference: python/paddle/amp/debugging.py — TensorCheckerConfig,
enable_tensor_checker/disable_tensor_checker (drive FLAGS_check_nan_inf),
check_numerics, collect_operator_stats (per-op dtype counters),
compare_accuracy (cross-run op-output diff).

TPU re-design: the checker rides the dispatch-layer NaN/Inf watchdog
(core/dispatch.py behind FLAGS_check_nan_inf — the nan_inf_utils.cc
analog); operator stats hook the same dispatch path.
"""
from __future__ import annotations

import contextlib
from collections import defaultdict
from enum import Enum
from typing import Dict, Optional

import jax.numpy as jnp

from ..core import flags
from ..core.tensor import Tensor

__all__ = [
    "DebugMode", "TensorCheckerConfig", "enable_tensor_checker",
    "disable_tensor_checker", "check_numerics",
    "enable_operator_stats_collection", "disable_operator_stats_collection",
    "collect_operator_stats", "compare_accuracy",
]


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


class TensorCheckerConfig:
    """Reference: debugging.py TensorCheckerConfig."""

    def __init__(self, enable: bool = False,
                 debug_mode: "DebugMode" = DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir: Optional[str] = None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None,
                 stack_height_limit=1, **kw):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list or []
        self.skipped_op_list = skipped_op_list or []
        self.debug_step = debug_step
        self.stack_height_limit = stack_height_limit


def enable_tensor_checker(checker_config: Optional[TensorCheckerConfig] = None):
    """Reference: debugging.py enable_tensor_checker → sets
    FLAGS_check_nan_inf(+level)."""
    config = checker_config or TensorCheckerConfig(enable=True)
    if config.enable:
        level = 0 if config.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT \
            else 1
        flags.set_flags({"check_nan_inf": True,
                         "check_nan_inf_level": level})


def disable_tensor_checker():
    flags.set_flags({"check_nan_inf": False})


def check_numerics(tensor, op_type: str = "", var_name: str = "",
                   debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT):
    """Reference: debugging.py check_numerics — count NaN/Inf in one
    tensor and abort/warn. Returns (num_nan, num_inf, num_zero)."""
    v = tensor._value if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    num_nan = int(jnp.isnan(v).sum())
    num_inf = int(jnp.isinf(v).sum())
    num_zero = int((v == 0).sum())
    if num_nan or num_inf:
        msg = (f"check_numerics: op={op_type} var={var_name} "
               f"nan={num_nan} inf={num_inf}")
        if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
            raise FloatingPointError(msg)
        import warnings

        warnings.warn(msg)
    return (Tensor._from_value(jnp.asarray(num_nan)),
            Tensor._from_value(jnp.asarray(num_inf)),
            Tensor._from_value(jnp.asarray(num_zero)))


# ---------------------------------------------------------------- op stats
_op_stats: Optional[Dict[str, Dict[str, int]]] = None
_orig_call_primitive = None


def _install_stats_hook():
    """Wrap dispatch.call_primitive to count per-op output dtypes
    (reference: debugging.py collect_operator_stats tables)."""
    from ..core import dispatch

    global _orig_call_primitive
    if _orig_call_primitive is not None:
        return
    _orig_call_primitive = dispatch.call_primitive

    def counted(name, arrays, static):
        outs = _orig_call_primitive(name, arrays, static)
        if _op_stats is not None:
            for o in outs:
                dt = str(getattr(o, "dtype", "other"))
                _op_stats[name][dt] = _op_stats[name].get(dt, 0) + 1
        return outs

    dispatch.call_primitive = counted


def enable_operator_stats_collection():
    global _op_stats
    _op_stats = defaultdict(dict)
    _install_stats_hook()


def disable_operator_stats_collection():
    global _op_stats
    stats = _op_stats
    _op_stats = None
    if stats:
        print("<" + "-" * 28 + " op list " + "-" * 28 + ">")
        print(f"{'op':<32}{'dtype':<12}{'count':<8}")
        for op, by_dtype in sorted(stats.items()):
            for dt, n in by_dtype.items():
                print(f"{op:<32}{dt:<12}{n:<8}")
    return dict(stats) if stats is not None else {}


@contextlib.contextmanager
def collect_operator_stats():
    """Reference: debugging.py collect_operator_stats context manager."""
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()


def compare_accuracy(dump_path: str, another_dump_path: str,
                     output_filename: str, loss_scale: float = 1,
                     dump_all_tensors: bool = False):
    """Reference: debugging.py compare_accuracy consumes check_nan_inf
    GPU dump files; this framework checks values in-process instead."""
    raise NotImplementedError(
        "compare_accuracy consumes dump files from the reference's GPU "
        "runs; use collect_operator_stats() + check_numerics() in-process"
    )
