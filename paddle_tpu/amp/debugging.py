"""AMP debugging tools.

Reference: python/paddle/amp/debugging.py (TensorCheckerConfig,
enable_operator_stats_collection, compare_accuracy). Minimal parity: op
stats collection over the dispatch cache + nan/inf checking toggles.
"""
from __future__ import annotations

from ..core import flags


def enable_tensor_checker(checker_config=None):
    flags.set_flags({"check_nan_inf": True})


def disable_tensor_checker():
    flags.set_flags({"check_nan_inf": False})


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=None, **kw):
        self.enable = enable


def collect_operator_stats():
    from ..core.dispatch import dispatch_cache_info

    return dispatch_cache_info()


def enable_operator_stats_collection():
    pass


def disable_operator_stats_collection():
    pass
