"""CIFAR-10/100 reader-factory API.

Reference: python/paddle/dataset/cifar.py — train10/test10/train100/test100
yield (3072-float image in [0, 1], int label) read from the pickled batch
tarballs; ``synthetic=True`` generates deterministic samples.
"""
from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from . import common

__all__ = ["train10", "test10", "train100", "test100"]


def _reader_from_tar(tar_path, sub_name, label_key):
    def reader():
        with tarfile.open(tar_path, mode="r") as f:
            names = [
                n for n in f.getnames() if sub_name in n and "batches.meta" not in n
            ]
            for name in sorted(names):
                batch = pickle.load(f.extractfile(name), encoding="latin1")
                data = batch["data"].astype("float32") / 255.0
                labels = batch.get(label_key)
                for sample, label in zip(data, labels):
                    yield sample, int(label)

    return reader


def _synthetic_reader(n, n_classes, seed_name):
    rng = common._synthetic_rng(seed_name)
    images = rng.random((n, 3072), dtype=np.float32)
    labels = rng.integers(0, n_classes, size=n)

    def reader():
        for img, lab in zip(images, labels):
            yield img, int(lab)

    return reader


def _path(fname):
    return os.path.join(common.DATA_HOME, "cifar", fname)


def train10(synthetic=False, n_synthetic=512):
    if synthetic:
        return _synthetic_reader(n_synthetic, 10, "cifar10-train")
    return _reader_from_tar(_path("cifar-10-python.tar.gz"), "data_batch", "labels")


def test10(synthetic=False, n_synthetic=128):
    if synthetic:
        return _synthetic_reader(n_synthetic, 10, "cifar10-test")
    return _reader_from_tar(_path("cifar-10-python.tar.gz"), "test_batch", "labels")


def train100(synthetic=False, n_synthetic=512):
    if synthetic:
        return _synthetic_reader(n_synthetic, 100, "cifar100-train")
    return _reader_from_tar(_path("cifar-100-python.tar.gz"), "train", "fine_labels")


def test100(synthetic=False, n_synthetic=128):
    if synthetic:
        return _synthetic_reader(n_synthetic, 100, "cifar100-test")
    return _reader_from_tar(_path("cifar-100-python.tar.gz"), "test", "fine_labels")
