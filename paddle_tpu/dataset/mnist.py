"""MNIST reader-factory API.

Reference: python/paddle/dataset/mnist.py — train()/test() yield
(784-float image in [-1, 1], int label). Reads idx-ubyte files from the
local cache; ``synthetic=True`` yields deterministic generated digits.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from . import common

__all__ = ["train", "test"]


def _reader_from_files(image_path, label_path):
    def reader():
        opener = gzip.open if image_path.endswith(".gz") else open
        with opener(image_path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
        opener = gzip.open if label_path.endswith(".gz") else open
        with opener(label_path, "rb") as f:
            _, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), dtype=np.uint8)
        images = images.astype("float32") / 127.5 - 1.0
        for img, lab in zip(images, labels):
            yield img, int(lab)

    return reader


def _synthetic_reader(n, seed_name):
    rng = common._synthetic_rng(seed_name)
    images = (rng.random((n, 784), dtype=np.float32) * 2.0 - 1.0)
    labels = rng.integers(0, 10, size=n)

    def reader():
        for img, lab in zip(images, labels):
            yield img, int(lab)

    return reader


def train(synthetic: bool = False, n_synthetic: int = 512):
    if synthetic:
        return _synthetic_reader(n_synthetic, "mnist-train")
    base = os.path.join(common.DATA_HOME, "mnist")
    return _reader_from_files(
        os.path.join(base, "train-images-idx3-ubyte.gz"),
        os.path.join(base, "train-labels-idx1-ubyte.gz"),
    )


def test(synthetic: bool = False, n_synthetic: int = 128):
    if synthetic:
        return _synthetic_reader(n_synthetic, "mnist-test")
    base = os.path.join(common.DATA_HOME, "mnist")
    return _reader_from_files(
        os.path.join(base, "t10k-images-idx3-ubyte.gz"),
        os.path.join(base, "t10k-labels-idx1-ubyte.gz"),
    )
