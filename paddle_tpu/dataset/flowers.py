"""Oxford-102 flowers reader.

Reference: python/paddle/dataset/flowers.py — train()/test()/valid() yield
(3x224x224 float image, int label) from the image tarball + .mat label
files. Synthetic mode generates deterministic images so vision pipelines
can run without the archives.
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = ["train", "test", "valid"]


def _synthetic_reader(n, seed_name, size=(3, 32, 32)):
    rng = common._synthetic_rng(seed_name)

    def reader():
        for _ in range(n):
            img = rng.random(size, dtype=np.float32)
            yield img, int(rng.integers(0, 102))

    return reader


def train(synthetic: bool = True, mapper=None, buffered_size: int = 1024,
          use_xmap: bool = False):
    r = _synthetic_reader(256, "flowers-train")
    if mapper is not None:
        from ..reader import map_readers

        return map_readers(mapper, r)
    return r


def test(synthetic: bool = True, mapper=None, buffered_size: int = 1024,
         use_xmap: bool = False):
    r = _synthetic_reader(64, "flowers-test")
    if mapper is not None:
        from ..reader import map_readers

        return map_readers(mapper, r)
    return r


def valid(synthetic: bool = True, mapper=None, buffered_size: int = 1024,
          use_xmap: bool = False):
    r = _synthetic_reader(64, "flowers-valid")
    if mapper is not None:
        from ..reader import map_readers

        return map_readers(mapper, r)
    return r
