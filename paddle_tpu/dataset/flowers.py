"""Oxford-102 flowers reader.

Reference: python/paddle/dataset/flowers.py — train()/test()/valid() yield
(float image CHW, int label). The real-archive path (image tarball + .mat
label files, scipy-loaded) requires files in the local cache; synthetic
mode generates deterministic 3x32x32 images (a reduced stand-in shape —
the reference emits 3x224x224 crops) so vision pipelines can run without
the archives.
"""
from __future__ import annotations

import os

import numpy as np

from . import common

__all__ = ["train", "test", "valid"]


def _synthetic_reader(n, seed_name, size=(3, 32, 32)):
    rng = common._synthetic_rng(seed_name)

    def reader():
        for _ in range(n):
            img = rng.random(size, dtype=np.float32)
            yield img, int(rng.integers(0, 102))

    return reader


def _make(split, n, synthetic, mapper):
    if not synthetic:
        base = os.path.join(common.DATA_HOME, "flowers")
        raise RuntimeError(
            f"flowers.{split}(synthetic=False) needs 102flowers.tgz + "
            f"setid.mat + imagelabels.mat in {base}; this build has no "
            "network egress. Use synthetic=True for generated data."
        )
    r = _synthetic_reader(n, f"flowers-{split}")
    if mapper is not None:
        from ..reader import map_readers

        return map_readers(mapper, r)
    return r


def train(synthetic: bool = True, mapper=None, buffered_size: int = 1024,
          use_xmap: bool = False):
    return _make("train", 256, synthetic, mapper)


def test(synthetic: bool = True, mapper=None, buffered_size: int = 1024,
         use_xmap: bool = False):
    return _make("test", 64, synthetic, mapper)


def valid(synthetic: bool = True, mapper=None, buffered_size: int = 1024,
          use_xmap: bool = False):
    return _make("valid", 64, synthetic, mapper)
