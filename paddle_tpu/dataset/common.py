"""Shared dataset helpers.

Reference: python/paddle/dataset/common.py (DATA_HOME, md5file, download,
cluster-split helpers). Download here resolves against the local cache only.
"""
from __future__ import annotations

import hashlib
import os

from ..utils.download import _md5check as _md5check  # noqa: F401

__all__ = ["DATA_HOME", "md5file", "download", "split", "cluster_files_reader"]

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset")
)


def md5file(fname: str) -> str:
    from ..utils.download import md5file as _md5

    return _md5(fname)


def download(url: str, module_name: str, md5sum: str | None = None,
             save_name: str | None = None) -> str:
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(dirname,
                            save_name or url.split("/")[-1])
    if os.path.exists(filename) and (
        md5sum is None or md5file(filename) == md5sum
    ):
        return filename
    raise RuntimeError(
        f"'{filename}' missing from the local dataset cache and this build "
        f"has no network egress; place the file there manually (source: {url})."
    )


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    import pickle

    dumper = dumper or pickle.dump
    lines = []
    indx_f = 0
    for i, d in enumerate(reader()):
        lines.append(d)
        if i >= (indx_f + 1) * line_count - 1:
            with open(suffix % indx_f, "wb") as f:
                dumper(lines, f)
            lines = []
            indx_f += 1
    if lines:
        with open(suffix % indx_f, "wb") as f:
            dumper(lines, f)


def cluster_files_reader(files_pattern, trainer_count, trainer_id,
                         loader=None):
    import glob
    import pickle

    loader = loader or pickle.load

    def reader():
        file_list = sorted(glob.glob(files_pattern))
        my_files = [
            fn for i, fn in enumerate(file_list)
            if i % trainer_count == trainer_id
        ]
        for fn in my_files:
            with open(fn, "rb") as f:
                for item in loader(f):
                    yield item

    return reader


def _synthetic_rng(name: str):
    import numpy as np

    # stable across processes (str hash() is randomized per interpreter)
    seed = int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "little")
    return np.random.default_rng(seed)
