"""Legacy ``paddle.dataset`` namespace (reader-factory API).

Reference: python/paddle/dataset/ — each submodule exposes ``train()`` /
``test()`` returning zero-arg reader callables that yield tuples, fed to
``paddle.batch``. This build reads the standard file formats from a local
cache (zero network egress; see paddle_tpu/utils/download.py) and offers a
deterministic ``synthetic=True`` mode for CI so the reader pipeline is
testable without the original archives.
"""
from . import common
from . import mnist
from . import cifar
from . import uci_housing
from . import imdb
from . import imikolov
from . import movielens
from . import conll05
from . import flowers

__all__ = [
    "common", "mnist", "cifar", "uci_housing", "imdb", "imikolov",
    "movielens", "conll05", "flowers",
]
