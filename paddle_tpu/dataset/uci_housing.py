"""UCI housing regression reader.

Reference: python/paddle/dataset/uci_housing.py — 13 features normalized by
feature-wise (max-min)/count stats, 80/20 train/test split. Reads the
space-separated ``housing.data`` file from the local cache; synthetic mode
generates a deterministic linear-plus-noise regression set.
"""
from __future__ import annotations

import os

import numpy as np

from . import common

__all__ = ["train", "test", "feature_names"]

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE", "DIS", "RAD", "TAX",
    "PTRATIO", "B", "LSTAT",
]

TRAIN_RATIO = 0.8


def _load_real():
    path = os.path.join(common.DATA_HOME, "uci_housing", "housing.data")
    data = np.loadtxt(path)
    features = data[:, :-1]
    maximums, minimums = features.max(axis=0), features.min(axis=0)
    avgs = features.sum(axis=0) / features.shape[0]
    features = (features - avgs) / (maximums - minimums)
    return np.concatenate([features, data[:, -1:]], axis=1).astype("float32")


def _load_synthetic():
    rng = common._synthetic_rng("uci-housing")
    n = 506
    x = rng.standard_normal((n, 13)).astype("float32") * 0.3
    w = rng.standard_normal((13, 1)).astype("float32")
    y = x @ w + 22.5 + rng.standard_normal((n, 1)).astype("float32") * 0.1
    return np.concatenate([x, y], axis=1)


def _make_reader(rows):
    def reader():
        for row in rows:
            yield row[:-1], row[-1:]

    return reader


def train(synthetic: bool = False):
    data = _load_synthetic() if synthetic else _load_real()
    n = int(data.shape[0] * TRAIN_RATIO)
    return _make_reader(data[:n])


def test(synthetic: bool = False):
    data = _load_synthetic() if synthetic else _load_real()
    n = int(data.shape[0] * TRAIN_RATIO)
    return _make_reader(data[n:])
