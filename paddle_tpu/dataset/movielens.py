"""MovieLens-1M reader.

Reference: python/paddle/dataset/movielens.py — MovieInfo/UserInfo records,
train()/test() yield (user features..., movie features..., score). Reads the
ml-1m zip from the local cache; synthetic mode fabricates a small consistent
catalog.
"""
from __future__ import annotations

import os
import re
import zipfile

import numpy as np

from . import common

__all__ = [
    "MovieInfo", "UserInfo", "train", "test", "get_movie_title_dict",
    "max_movie_id", "max_user_id", "max_job_id", "age_table",
]

age_table = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [
            self.index,
            [CATEGORIES_DICT[c] for c in self.categories],
            [MOVIE_TITLE_DICT[w.lower()] for w in self.title.split()],
        ]

    def __repr__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]

    def __repr__(self):
        return (f"<UserInfo id({self.index}), gender({'M' if self.is_male else 'F'}), "
                f"age({age_table[self.age]}), job({self.job_id})>")


MOVIE_INFO: dict | None = None
MOVIE_TITLE_DICT: dict | None = None
CATEGORIES_DICT: dict | None = None
USER_INFO: dict | None = None
RATINGS: list | None = None
_LOADED_MODE: bool | None = None  # synthetic flag the globals were built with


def _load_synthetic():
    global MOVIE_INFO, MOVIE_TITLE_DICT, CATEGORIES_DICT, USER_INFO, RATINGS
    rng = common._synthetic_rng("movielens")
    cats = ["Action", "Comedy", "Drama", "Horror", "Sci-Fi"]
    CATEGORIES_DICT = {c: i for i, c in enumerate(cats)}
    words = [f"title{i}" for i in range(32)]
    MOVIE_TITLE_DICT = {w: i for i, w in enumerate(words)}
    MOVIE_INFO = {}
    for mid in range(1, 65):
        n_cat = int(rng.integers(1, 3))
        title = " ".join(
            words[int(i)] for i in rng.integers(0, 32, size=3)
        )
        MOVIE_INFO[mid] = MovieInfo(
            mid, [cats[int(i)] for i in rng.integers(0, 5, size=n_cat)], title
        )
    USER_INFO = {
        uid: UserInfo(uid, "M" if rng.integers(0, 2) else "F",
                      age_table[int(rng.integers(0, len(age_table)))],
                      int(rng.integers(0, 21)))
        for uid in range(1, 33)
    }
    RATINGS = []
    for _ in range(512):
        uid = int(rng.integers(1, 33))
        mid = int(rng.integers(1, 65))
        score = float(rng.integers(1, 6))
        RATINGS.append((uid, mid, score))


def _load_real():
    global MOVIE_INFO, MOVIE_TITLE_DICT, CATEGORIES_DICT, USER_INFO, RATINGS
    path = os.path.join(common.DATA_HOME, "movielens", "ml-1m.zip")
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    CATEGORIES_DICT = {}
    MOVIE_TITLE_DICT = {}
    MOVIE_INFO = {}
    with zipfile.ZipFile(path) as package:
        for info in package.infolist():
            assert isinstance(info, zipfile.ZipInfo)
        with package.open("ml-1m/movies.dat") as movie_file:
            for line in movie_file:
                line = line.decode(encoding="latin1")
                movie_id, title, categories = line.strip().split("::")
                categories = categories.split("|")
                for c in categories:
                    CATEGORIES_DICT.setdefault(c, len(CATEGORIES_DICT))
                title = pattern.match(title).group(1)
                for w in title.split():
                    MOVIE_TITLE_DICT.setdefault(w.lower(), len(MOVIE_TITLE_DICT))
                MOVIE_INFO[int(movie_id)] = MovieInfo(movie_id, categories, title)
        USER_INFO = {}
        with package.open("ml-1m/users.dat") as user_file:
            for line in user_file:
                uid, gender, age, job, _ = line.decode("latin1").strip().split("::")
                USER_INFO[int(uid)] = UserInfo(uid, gender, age, job)
        RATINGS = []
        with package.open("ml-1m/ratings.dat") as rating:
            for line in rating:
                uid, mid, score, _ = line.decode("latin1").strip().split("::")
                RATINGS.append((int(uid), int(mid), float(score)))


def _ensure_loaded(synthetic):
    global _LOADED_MODE
    if MOVIE_INFO is None or _LOADED_MODE != bool(synthetic):
        if synthetic:
            _load_synthetic()
        else:
            _load_real()
        _LOADED_MODE = bool(synthetic)


def _reader(synthetic, is_test, test_ratio=0.1):
    _ensure_loaded(synthetic)

    def reader():
        # fresh RNG per iteration: the train/test split must be identical
        # every epoch (and between the train() and test() readers)
        rng = common._synthetic_rng("movielens-split")
        for uid, mid, score in RATINGS:
            in_test = rng.random() < test_ratio
            if in_test != is_test:
                continue
            usr = USER_INFO[uid]
            mov = MOVIE_INFO[mid]
            yield usr.value() + mov.value() + [[score]]

    return reader


def train(synthetic: bool = False):
    return _reader(synthetic, is_test=False)


def test(synthetic: bool = False):
    return _reader(synthetic, is_test=True)


def get_movie_title_dict(synthetic: bool = False):
    _ensure_loaded(synthetic)
    return MOVIE_TITLE_DICT


def max_movie_id(synthetic: bool = False):
    _ensure_loaded(synthetic)
    return max(MOVIE_INFO)


def max_user_id(synthetic: bool = False):
    _ensure_loaded(synthetic)
    return max(USER_INFO)


def max_job_id(synthetic: bool = False):
    _ensure_loaded(synthetic)
    return max(u.job_id for u in USER_INFO.values())
