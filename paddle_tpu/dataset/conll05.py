"""CoNLL-2005 SRL reader.

Reference: python/paddle/dataset/conll05.py — test() yields
(word_ids, ctx_n2/n1/0/p1/p2, verb_id, mark, label_ids) tuples built from
word/verb/label dictionaries. Synthetic mode fabricates a consistent tagged
corpus so the 9-slot feature pipeline is exercised end to end.
"""
from __future__ import annotations

from . import common

__all__ = ["get_dict", "get_embedding", "test"]

_UNK = "<unk>"


def _synthetic_corpus(n=128):
    rng = common._synthetic_rng("conll05")
    words = [f"w{i}" for i in range(48)]
    labels = ["B-A0", "I-A0", "B-A1", "I-A1", "B-V", "O"]
    sents = []
    for _ in range(n):
        length = int(rng.integers(4, 12))
        sent = [words[int(i)] for i in rng.integers(0, 48, size=length)]
        verb_idx = int(rng.integers(0, length))
        tags = [labels[int(i)] for i in rng.integers(0, 6, size=length)]
        tags[verb_idx] = "B-V"
        sents.append((sent, verb_idx, tags))
    return sents


def get_dict(synthetic: bool = True):
    """Returns (word_dict, verb_dict, label_dict)."""
    corpus = _synthetic_corpus()
    word_dict, verb_dict, label_dict = {}, {}, {}
    for sent, verb_idx, tags in corpus:
        for w in sent:
            word_dict.setdefault(w, len(word_dict))
        verb_dict.setdefault(sent[verb_idx], len(verb_dict))
        for t in tags:
            label_dict.setdefault(t, len(label_dict))
    word_dict.setdefault(_UNK, len(word_dict))
    return word_dict, verb_dict, label_dict


def get_embedding(word_dict=None, dim: int = 32):
    import numpy as np

    word_dict = word_dict or get_dict()[0]
    rng = common._synthetic_rng("conll05-emb")
    return rng.standard_normal((len(word_dict), dim)).astype("float32")


def test(synthetic: bool = True):
    word_dict, verb_dict, label_dict = get_dict(synthetic)
    unk = word_dict[_UNK]

    def reader():
        for sent, verb_idx, tags in _synthetic_corpus():
            n = len(sent)

            def ctx(offset):
                i = min(max(verb_idx + offset, 0), n - 1)
                return word_dict.get(sent[i], unk)

            word_ids = [word_dict.get(w, unk) for w in sent]
            ctx_n2, ctx_n1 = [ctx(-2)] * n, [ctx(-1)] * n
            ctx_0, ctx_p1, ctx_p2 = [ctx(0)] * n, [ctx(1)] * n, [ctx(2)] * n
            verb_id = [verb_dict[sent[verb_idx]]] * n
            mark = [1 if i == verb_idx else 0 for i in range(n)]
            label_ids = [label_dict[t] for t in tags]
            yield (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
                   verb_id, mark, label_ids)

    return reader
