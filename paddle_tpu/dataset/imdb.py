"""IMDB sentiment reader.

Reference: python/paddle/dataset/imdb.py — word_dict() built from the
aclImdb tarball by frequency, train()/test() yield (word-id list, 0/1
label). Local-cache tarball or deterministic synthetic corpus.
"""
from __future__ import annotations

import os
import re
import string
import tarfile
from collections import Counter

from . import common

__all__ = ["word_dict", "train", "test"]

_SYN_VOCAB = 256
_SYN_POS = ["good great fine nice best love", "enjoy superb brilliant strong"]
_SYN_NEG = ["bad poor worst awful hate", "boring weak terrible dull"]


def _tokenize(text: str):
    text = text.lower()
    return re.sub(f"[{re.escape(string.punctuation)}]", " ", text).split()


def _tar_reader(pattern):
    path = os.path.join(common.DATA_HOME, "imdb", "aclImdb_v1.tar.gz")
    pat = re.compile(pattern)
    with tarfile.open(path) as t:
        for name in t.getnames():
            if pat.match(name):
                yield _tokenize(t.extractfile(name).read().decode("utf-8"))


def _synthetic_docs(n, seed_name):
    rng = common._synthetic_rng(seed_name)
    docs = []
    for i in range(n):
        pos = bool(rng.integers(0, 2))
        base = (_SYN_POS if pos else _SYN_NEG)[int(rng.integers(0, 2))]
        filler = " ".join(
            f"w{int(v)}" for v in rng.integers(0, _SYN_VOCAB, size=20)
        )
        docs.append((_tokenize(base + " " + filler), int(pos)))
    return docs


def word_dict(synthetic: bool = False, cutoff: int = 150):
    cnt: Counter = Counter()
    if synthetic:
        for tokens, _ in _synthetic_docs(512, "imdb-train"):
            cnt.update(tokens)
        cutoff = 0
    else:
        for tokens in _tar_reader(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"):
            cnt.update(tokens)
    words = [w for w, c in cnt.items() if c > cutoff]
    words.sort(key=lambda w: (-cnt[w], w))
    d = {w: i for i, w in enumerate(words)}
    d["<unk>"] = len(d)
    return d

def _reader_creator(docs, w_dict):
    unk = w_dict["<unk>"]

    def reader():
        for tokens, label in docs:
            yield [w_dict.get(t, unk) for t in tokens], label

    return reader


def train(word_idx=None, synthetic: bool = False):
    w = word_idx or word_dict(synthetic=synthetic)
    if synthetic:
        return _reader_creator(_synthetic_docs(512, "imdb-train"), w)
    docs = (
        [(tok, 1) for tok in _tar_reader(r"aclImdb/train/pos/.*\.txt$")]
        + [(tok, 0) for tok in _tar_reader(r"aclImdb/train/neg/.*\.txt$")]
    )
    return _reader_creator(docs, w)


def test(word_idx=None, synthetic: bool = False):
    w = word_idx or word_dict(synthetic=synthetic)
    if synthetic:
        return _reader_creator(_synthetic_docs(128, "imdb-test"), w)
    docs = (
        [(tok, 1) for tok in _tar_reader(r"aclImdb/test/pos/.*\.txt$")]
        + [(tok, 0) for tok in _tar_reader(r"aclImdb/test/neg/.*\.txt$")]
    )
    return _reader_creator(docs, w)
