"""PTB (imikolov) language-model reader.

Reference: python/paddle/dataset/imikolov.py — build_dict() over the PTB
text, train()/test() yield n-gram tuples (NGRAM mode) or (src, trg)
sequences (SEQ mode).
"""
from __future__ import annotations

import os
import tarfile
from collections import Counter

from . import common

__all__ = ["build_dict", "train", "test", "DataType"]


class DataType:
    NGRAM = 1
    SEQ = 2


def _lines(split):
    path = os.path.join(common.DATA_HOME, "imikolov", "simple-examples.tgz")
    fname = f"./simple-examples/data/ptb.{split}.txt"
    with tarfile.open(path) as t:
        for line in t.extractfile(fname):
            yield line.decode("utf-8").split()


def _synthetic_lines(split, n=256):
    rng = common._synthetic_rng(f"imikolov-{split}")
    vocab = [f"tok{i}" for i in range(64)]
    for _ in range(n):
        length = int(rng.integers(3, 12))
        yield [vocab[int(i)] for i in rng.integers(0, 64, size=length)]


def build_dict(min_word_freq: int = 50, synthetic: bool = False):
    cnt: Counter = Counter()
    lines = _synthetic_lines("train") if synthetic else _lines("train")
    for words in lines:
        cnt.update(words)
    cnt.pop("<unk>", None)
    if synthetic:
        min_word_freq = 0
    keep = [w for w, c in cnt.items() if c > min_word_freq]
    keep.sort(key=lambda w: (-cnt[w], w))
    word_idx = {w: i for i, w in enumerate(keep)}
    word_idx["<unk>"] = len(word_idx)
    return word_idx


def _reader_creator(split, word_idx, n, data_type, synthetic):
    def reader():
        lines = _synthetic_lines(split) if synthetic else _lines(split)
        UNK = word_idx["<unk>"]
        for words in lines:
            if data_type == DataType.NGRAM:
                assert n > -1, "Invalid gram length"
                sent = ["<s>"] + words + ["<e>"]
                if len(sent) >= n:
                    ids = [word_idx.get(w, UNK) for w in sent]
                    for i in range(n, len(ids) + 1):
                        yield tuple(ids[i - n : i])
            elif data_type == DataType.SEQ:
                ids = [word_idx.get(w, UNK) for w in words]
                src = [word_idx.get("<s>", UNK)] + ids
                trg = ids + [word_idx.get("<e>", UNK)]
                yield src, trg
            else:
                raise ValueError(f"Unknown data type {data_type}")

    return reader


def train(word_idx, n, data_type=DataType.NGRAM, synthetic: bool = False):
    return _reader_creator("train", word_idx, n, data_type, synthetic)


def test(word_idx, n, data_type=DataType.NGRAM, synthetic: bool = False):
    return _reader_creator("valid", word_idx, n, data_type, synthetic)
