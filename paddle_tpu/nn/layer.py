"""nn.Layer base class.

Reference: python/paddle/nn/layer/layers.py:351 (Layer — parameters,
sublayers, state_dict, hooks, train/eval). Parameters are Tensors with
stop_gradient=False; buffers are non-trainable persistent state (running
stats etc.).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor


class HookRemoveHelper:
    def __init__(self, hooks: Dict[int, Callable], hook_id: int):
        self._hooks = hooks
        self._id = hook_id

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    _hook_counter = 0

    def __init__(self, name_scope: Optional[str] = None, dtype: str = "float32"):
        self.training = True
        self._dtype = dtype
        self._parameters: "OrderedDict[str, Optional[Parameter]]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Optional[Layer]]" = OrderedDict()
        self._buffers: "OrderedDict[str, Optional[Tensor]]" = OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: Dict[int, Callable] = OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = OrderedDict()
        self._casted_by_pure_fp16 = False
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ------------------------------------------------------------------
    # attribute protocol
    # ------------------------------------------------------------------
    def __setattr__(self, name: str, value: Any):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            layers and layers.pop(name, None)
            buffers is not None and buffers.pop(name, None)
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            params and params.pop(name, None)
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                params[name] = None
            if buffers is not None and name in buffers:
                buffers[name] = value
            object.__setattr__(self, name, value)

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        object.__setattr__(self, name, parameter)
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        object.__setattr__(self, name, sublayer)
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        object.__setattr__(self, name, tensor)
        return tensor

    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias: bool = False,
        default_initializer=None,
    ) -> Parameter:
        """Reference: layers.py create_parameter — ParamAttr + initializer."""
        from . import initializer as I
        from .param_attr import ParamAttr

        dtype = dtype or self._dtype
        attr = ParamAttr._to_attr(attr)
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        else:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        name = attr.name if attr is not None else None
        value = init(shape, dtype)
        p = Parameter(value, trainable=(attr is None or attr.trainable), name=name)
        if attr is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
            p.regularizer = attr.regularizer
        return p

    # ------------------------------------------------------------------
    # traversal
    # ------------------------------------------------------------------
    def named_parameters(
        self, prefix: str = "", include_sublayers: bool = True
    ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer, in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_sublayers(
        self, prefix: str = "", include_self: bool = False, layers_set=None
    ) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(
                prefix=sub_prefix, include_self=True, layers_set=layers_set
            )

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # ------------------------------------------------------------------
    # mode
    # ------------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # ------------------------------------------------------------------
    # state dict
    # ------------------------------------------------------------------
    def state_dict(
        self,
        destination=None,
        include_sublayers: bool = True,
        structured_name_prefix: str = "",
        use_hook: bool = True,
    ) -> Dict[str, Tensor]:
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, layer in self.named_sublayers(
            prefix=structured_name_prefix.rstrip("."), include_self=True
        ):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                dest[(f"{name}.{bname}" if name else bname)] = b
        return dest

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name: bool = True):
        """Reference: layers.py set_state_dict — copies values into existing
        params/buffers (shape-checked)."""
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            target = own[k]
            src = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            if tuple(src.shape) != tuple(target._value.shape):
                raise ValueError(
                    f"state_dict shape mismatch for {k}: "
                    f"{tuple(src.shape)} vs {tuple(target._value.shape)}"
                )
            target._replace_value(src.astype(target._value.dtype))
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # ------------------------------------------------------------------
    # dtype / device movement
    # ------------------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        from ..core.dtype import convert_dtype, is_floating_point

        dt = convert_dtype(dtype) if dtype is not None else None
        for t in list(self.state_dict().values()):
            v = t._value
            if dt is not None and is_floating_point(v.dtype):
                v = v.astype(dt)
            t._replace_value(v)
        if dt is not None:
            for l in self.sublayers(include_self=True):
                l._dtype = np.dtype(dt).name
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    # ------------------------------------------------------------------
    # hooks
    # ------------------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        Layer._hook_counter += 1
        self._forward_pre_hooks[Layer._hook_counter] = hook
        return HookRemoveHelper(self._forward_pre_hooks, Layer._hook_counter)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        Layer._hook_counter += 1
        self._forward_post_hooks[Layer._hook_counter] = hook
        return HookRemoveHelper(self._forward_post_hooks, Layer._hook_counter)

    # ------------------------------------------------------------------
    # call
    # ------------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    # ------------------------------------------------------------------
    def full_name(self) -> str:
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            mod_str = repr(sub)
            mod_str = "\n".join(
                "  " + line for line in mod_str.split("\n")
            )
            lines.append(f"  ({name}): " + mod_str.lstrip())
        main = self.__class__.__name__ + "(" + extra
        if lines:
            main += "\n" + "\n".join(lines) + "\n"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()
