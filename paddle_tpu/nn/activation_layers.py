"""Activation layers. Reference: python/paddle/nn/layer/activation.py."""
from __future__ import annotations

from . import functional as F
from . import initializer as I
from .layer import Layer


def _act_layer(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, **kw):
            super().__init__()
            merged = dict(defaults)
            merged.update({k: v for k, v in kw.items() if k != "name"})
            self._kw = merged

        def forward(self, x):
            return fn(x, **self._kw)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _act_layer("ReLU", F.relu)
ReLU6 = _act_layer("ReLU6", F.relu6)
Sigmoid = _act_layer("Sigmoid", F.sigmoid)
Tanh = _act_layer("Tanh", F.tanh)
GELU = _act_layer("GELU", F.gelu)
SiLU = _act_layer("SiLU", F.silu)
Swish = _act_layer("Swish", F.swish)
Mish = _act_layer("Mish", F.mish)
Hardswish = _act_layer("Hardswish", F.hardswish)
Hardsigmoid = _act_layer("Hardsigmoid", F.hardsigmoid)
Hardtanh = _act_layer("Hardtanh", F.hardtanh)
Hardshrink = _act_layer("Hardshrink", F.hardshrink)
Softshrink = _act_layer("Softshrink", F.softshrink)
Tanhshrink = _act_layer("Tanhshrink", F.tanhshrink)
Softplus = _act_layer("Softplus", F.softplus)
Softsign = _act_layer("Softsign", F.softsign)
LogSigmoid = _act_layer("LogSigmoid", F.log_sigmoid)
ELU = _act_layer("ELU", F.elu)
SELU = _act_layer("SELU", F.selu)
CELU = _act_layer("CELU", F.celu)
LeakyReLU = _act_layer("LeakyReLU", F.leaky_relu)
ThresholdedReLU = _act_layer("ThresholdedReLU", F.thresholded_relu)
Maxout = _act_layer("Maxout", F.maxout)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init),
        )

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8.0, upper=1.0 / 3.0, name=None):
        super().__init__()
        self.lower = lower
        self.upper = upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, self.training)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.glu(x, self.axis)
