"""Recurrent layers.

Reference: python/paddle/nn/layer/rnn.py — RNNCellBase:*, SimpleRNNCell:741,
LSTMCell:918 (gate order i,f,g,o; optional proj_size), GRUCell:1144
(h = z*h_prev + (1-z)*c), RNN:1339, BiRNN:1421, SimpleRNN:1859, LSTM:1982,
GRU:2119.

TPU design: the per-step cell math is plain framework ops (usable eagerly
and inside custom cells); the full-sequence layers run ONE `lax.scan`
primitive per direction per layer — the recurrence compiles to a single
fused XLA while-loop instead of per-step dispatch, and jax differentiates
through the scan for BPTT. Variable-length sequences freeze the carried
state and zero the outputs past each row's length, matching the reference's
mask_fn semantics.
"""
from __future__ import annotations

import math as _math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ..ops._helpers import defprim, ensure_tensor
from .layer import Layer

__all__ = [
    "RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
    "SimpleRNN", "LSTM", "GRU",
]


# ---------------------------------------------------------------------------
# sequence-scan primitives (one per cell type)
# ---------------------------------------------------------------------------
def _mask_step(t_idx, seq_lens, new, old):
    """Freeze state rows whose sequence already ended (t >= len)."""
    if seq_lens is None:
        return new
    alive = (t_idx < seq_lens)[:, None]
    return jnp.where(alive, new, old)


def _simple_rnn_step(x_t, h, w_ih, w_hh, b_ih, b_hh, act):
    z = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    return jnp.tanh(z) if act == "tanh" else jnp.maximum(z, 0)


def _simple_rnn_seq(x, h0, w_ih, w_hh, b_ih, b_hh, seq_lens, *, act,
                    reverse, use_lens):
    T = x.shape[0]
    lens = seq_lens if use_lens else None

    def step(h, xs):
        t_idx, x_t = xs
        h_new = _simple_rnn_step(x_t, h, w_ih, w_hh, b_ih, b_hh, act)
        h_new = _mask_step(t_idx, lens, h_new, h)
        out = h_new if lens is None else jnp.where(
            (t_idx < lens)[:, None], h_new, 0.0)
        return h_new, out

    ts = jnp.arange(T)
    if reverse:
        x = x[::-1]
        ts = ts[::-1]
    h_T, outs = jax.lax.scan(step, h0, (ts, x))
    if reverse:
        outs = outs[::-1]
    return outs, h_T


defprim("simple_rnn_seq_p", _simple_rnn_seq, multi_out=True)


def _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh, w_ho):
    gates = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    if w_ho is not None:
        h_new = h_new @ w_ho.T
    return h_new, c_new


def _lstm_seq(x, h0, c0, w_ih, w_hh, b_ih, b_hh, seq_lens, *, reverse,
              use_lens, proj):
    T = x.shape[0]
    lens = seq_lens if use_lens else None
    w_ho = None  # proj variant uses the 9-arg prim below

    def step(carry, xs):
        h, c = carry
        t_idx, x_t = xs
        h_new, c_new = _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh, w_ho)
        h_new = _mask_step(t_idx, lens, h_new, h)
        c_new = _mask_step(t_idx, lens, c_new, c)
        out = h_new if lens is None else jnp.where(
            (t_idx < lens)[:, None], h_new, 0.0)
        return (h_new, c_new), out

    ts = jnp.arange(T)
    if reverse:
        x = x[::-1]
        ts = ts[::-1]
    (h_T, c_T), outs = jax.lax.scan(step, (h0, c0), (ts, x))
    if reverse:
        outs = outs[::-1]
    return outs, h_T, c_T


defprim("lstm_seq_p", _lstm_seq, multi_out=True)


def _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh):
    xg = x_t @ w_ih.T + b_ih
    hg = h @ w_hh.T + b_hh
    xr, xz, xc = jnp.split(xg, 3, axis=-1)
    hr, hz, hc = jnp.split(hg, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    c = jnp.tanh(xc + r * hc)
    return z * h + (1.0 - z) * c


def _gru_seq(x, h0, w_ih, w_hh, b_ih, b_hh, seq_lens, *, reverse, use_lens):
    T = x.shape[0]
    lens = seq_lens if use_lens else None

    def step(h, xs):
        t_idx, x_t = xs
        h_new = _gru_step(x_t, h, w_ih, w_hh, b_ih, b_hh)
        h_new = _mask_step(t_idx, lens, h_new, h)
        out = h_new if lens is None else jnp.where(
            (t_idx < lens)[:, None], h_new, 0.0)
        return h_new, out

    ts = jnp.arange(T)
    if reverse:
        x = x[::-1]
        ts = ts[::-1]
    h_T, outs = jax.lax.scan(step, h0, (ts, x))
    if reverse:
        outs = outs[::-1]
    return outs, h_T


defprim("gru_seq_p", _gru_seq, multi_out=True)


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------
class RNNCellBase(Layer):
    """Reference: nn/layer/rnn.py RNNCellBase — get_initial_states."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or getattr(self, "state_shape")
        if isinstance(shape, (list, tuple)) and shape and \
                isinstance(shape[0], (list, tuple)):
            return tuple(
                Tensor._from_value(jnp.full((batch,) + tuple(
                    s if s > 0 else 1 for s in sub), init_value,
                    jnp.float32))
                for sub in shape
            )
        return Tensor._from_value(
            jnp.full((batch,) + tuple(s if s > 0 else 1 for s in shape),
                     init_value, jnp.float32))

    def _uniform_init(self):
        from .initializer import Uniform

        k = 1.0 / _math.sqrt(self.hidden_size)
        return Uniform(-k, k)


class SimpleRNNCell(RNNCellBase):
    """Reference: nn/layer/rnn.py:741 — h = act(Wih x + bih + Whh h + bhh)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        init = self._uniform_init()
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        inputs = ensure_tensor(inputs)
        if states is None:
            states = self.get_initial_states(inputs)
        h = apply("simple_rnn_cell_p", inputs, ensure_tensor(states),
                  self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh,
                  act=self.activation)
        return h, h

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


defprim(
    "simple_rnn_cell_p",
    lambda x, h, w_ih, w_hh, b_ih, b_hh, *, act: _simple_rnn_step(
        x, h, w_ih, w_hh, b_ih, b_hh, act),
)


class LSTMCell(RNNCellBase):
    """Reference: nn/layer/rnn.py:918 — gate order (i, f, g, o);
    optional proj_size projects h."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        if proj_size is not None and proj_size >= hidden_size:
            raise ValueError("proj_size must be smaller than hidden_size")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.proj_size = proj_size
        init = self._uniform_init()
        h_in = proj_size or hidden_size
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, h_in], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)
        self.weight_ho = (
            self.create_parameter([proj_size, hidden_size],
                                  default_initializer=init)
            if proj_size else None
        )

    @property
    def state_shape(self):
        return ((self.proj_size or self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        inputs = ensure_tensor(inputs)
        if states is None:
            states = self.get_initial_states(inputs)
        h_prev, c_prev = states
        if self.weight_ho is not None:
            h, c = apply("lstm_cell_proj_p", inputs, ensure_tensor(h_prev),
                         ensure_tensor(c_prev), self.weight_ih,
                         self.weight_hh, self.bias_ih, self.bias_hh,
                         self.weight_ho)
        else:
            h, c = apply("lstm_cell_p", inputs, ensure_tensor(h_prev),
                         ensure_tensor(c_prev), self.weight_ih,
                         self.weight_hh, self.bias_ih, self.bias_hh)
        return h, (h, c)

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


defprim(
    "lstm_cell_p",
    lambda x, h, c, w_ih, w_hh, b_ih, b_hh: _lstm_step(
        x, h, c, w_ih, w_hh, b_ih, b_hh, None),
    multi_out=True,
)
defprim(
    "lstm_cell_proj_p",
    lambda x, h, c, w_ih, w_hh, b_ih, b_hh, w_ho: _lstm_step(
        x, h, c, w_ih, w_hh, b_ih, b_hh, w_ho),
    multi_out=True,
)


class GRUCell(RNNCellBase):
    """Reference: nn/layer/rnn.py:1144 — gate order (r, z, c);
    h = z*h_prev + (1-z)*c."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        if hidden_size <= 0:
            raise ValueError("hidden_size must be positive")
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = self._uniform_init()
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        inputs = ensure_tensor(inputs)
        if states is None:
            states = self.get_initial_states(inputs)
        h = apply("gru_cell_p", inputs, ensure_tensor(states),
                  self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)
        return h, h

    def extra_repr(self):
        return f"{self.input_size}, {self.hidden_size}"


defprim(
    "gru_cell_p",
    lambda x, h, w_ih, w_hh, b_ih, b_hh: _gru_step(
        x, h, w_ih, w_hh, b_ih, b_hh),
)


# ---------------------------------------------------------------------------
# wrappers
# ---------------------------------------------------------------------------
class RNN(Layer):
    """Generic cell-over-time wrapper (reference: nn/layer/rnn.py:1339).
    Runs any RNNCell across the time dim with a Python loop (custom cells
    may carry arbitrary state pytrees)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import stack, transpose

        inputs = ensure_tensor(inputs)
        if not self.time_major:
            inputs = transpose(inputs, [1, 0, 2])
        T = inputs.shape[0]
        states = initial_states
        if states is None:
            batch_ref = transpose(inputs, [1, 0, 2])
            states = self.cell.get_initial_states(batch_ref)
        lens = (np.asarray(ensure_tensor(sequence_length)._value)
                if sequence_length is not None else None)
        order = range(T - 1, -1, -1) if self.is_reverse else range(T)
        outs = [None] * T
        for t in order:
            out_t, new_states = self.cell(inputs[t], states)
            if lens is not None:
                alive = Tensor._from_value(
                    jnp.asarray(t < lens)[:, None].astype(jnp.float32))
                out_t = out_t * alive

                def keep(new, old):
                    return new * alive + ensure_tensor(old) * (
                        Tensor._from_value(jnp.asarray(1.0)) - alive)

                states = jax.tree_util.tree_map(
                    keep, new_states, states,
                    is_leaf=lambda v: isinstance(v, Tensor))
            else:
                states = new_states
            outs[t] = out_t
        outputs = stack(outs, axis=0)
        if not self.time_major:
            outputs = transpose(outputs, [1, 0, 2])
        return outputs, states


class BiRNN(Layer):
    """Two RNN passes (fw/bw) with concatenated outputs
    (reference: nn/layer/rnn.py:1421)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import concat

        if initial_states is None:
            states_fw = states_bw = None
        else:
            states_fw, states_bw = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw, sequence_length)
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class RNNBase(Layer):
    """Multi-layer (bi)directional recurrence over the scan primitives.

    Reference: nn/layer/rnn.py RNNBase — mode in SimpleRNN/LSTM/GRU,
    direction "forward" | "bidirect"/"bidirectional", dropout between
    layers, time_major, sequence_length masking.
    """

    MODE = None  # "RNN_TANH"/"RNN_RELU"/"LSTM"/"GRU"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation=None, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=None,
                 name=None):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"unknown direction {direction!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirectional = direction != "forward"
        self.num_directions = 2 if self.bidirectional else 1
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.proj_size = proj_size

        gate_mult = {"LSTM": 4, "GRU": 3}.get(self.MODE, 1)
        init_std = 1.0 / _math.sqrt(hidden_size)
        from .initializer import Uniform

        init = Uniform(-init_std, init_std)
        h_out = proj_size or hidden_size

        self._all_weights = []
        for layer in range(num_layers):
            for direction_i in range(self.num_directions):
                in_sz = (input_size if layer == 0
                         else h_out * self.num_directions)
                suffix = "_reverse" if direction_i else ""
                w_ih = self.create_parameter(
                    [gate_mult * hidden_size, in_sz], attr=weight_ih_attr,
                    default_initializer=init)
                w_hh = self.create_parameter(
                    [gate_mult * hidden_size, h_out], attr=weight_hh_attr,
                    default_initializer=init)
                b_ih = self.create_parameter(
                    [gate_mult * hidden_size], attr=bias_ih_attr,
                    is_bias=True, default_initializer=init)
                b_hh = self.create_parameter(
                    [gate_mult * hidden_size], attr=bias_hh_attr,
                    is_bias=True, default_initializer=init)
                names = [f"weight_ih_l{layer}{suffix}",
                         f"weight_hh_l{layer}{suffix}",
                         f"bias_ih_l{layer}{suffix}",
                         f"bias_hh_l{layer}{suffix}"]
                params = [w_ih, w_hh, b_ih, b_hh]
                if self.MODE == "LSTM" and proj_size:
                    w_ho = self.create_parameter(
                        [proj_size, hidden_size], default_initializer=init)
                    names.append(f"weight_ho_l{layer}{suffix}")
                    params.append(w_ho)
                for n, p in zip(names, params):
                    self.add_parameter(n, p)
                self._all_weights.append(dict(zip(
                    ["w_ih", "w_hh", "b_ih", "b_hh", "w_ho"],
                    params + [None] * (5 - len(params)))))

    def _run_direction(self, xt, h0, c0, weights, reverse, lens):
        """xt: [T, B, I] Tensor; returns (outs [T, B, H], h_T, c_T|None)."""
        use_lens = lens is not None
        lens_t = (Tensor._from_value(lens) if use_lens
                  else Tensor._from_value(jnp.zeros((xt.shape[1],),
                                                    jnp.int64)))
        if self.MODE == "LSTM":
            if weights["w_ho"] is not None:
                outs, h_T, c_T = apply(
                    "lstm_seq_proj_p", xt, h0, c0, weights["w_ih"],
                    weights["w_hh"], weights["b_ih"], weights["b_hh"],
                    weights["w_ho"], lens_t, reverse=reverse,
                    use_lens=use_lens)
            else:
                outs, h_T, c_T = apply(
                    "lstm_seq_p", xt, h0, c0, weights["w_ih"],
                    weights["w_hh"], weights["b_ih"], weights["b_hh"],
                    lens_t, reverse=reverse, use_lens=use_lens, proj=False)
            return outs, h_T, c_T
        if self.MODE == "GRU":
            outs, h_T = apply(
                "gru_seq_p", xt, h0, weights["w_ih"], weights["w_hh"],
                weights["b_ih"], weights["b_hh"], lens_t, reverse=reverse,
                use_lens=use_lens)
            return outs, h_T, None
        act = "relu" if self.MODE == "RNN_RELU" else "tanh"
        outs, h_T = apply(
            "simple_rnn_seq_p", xt, h0, weights["w_ih"], weights["w_hh"],
            weights["b_ih"], weights["b_hh"], lens_t, act=act,
            reverse=reverse, use_lens=use_lens)
        return outs, h_T, None

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..nn.functional.common import dropout as dropout_fn
        from ..ops.manipulation import concat, stack, transpose

        inputs = ensure_tensor(inputs)
        if not self.time_major:
            inputs = transpose(inputs, [1, 0, 2])
        T, B = inputs.shape[0], inputs.shape[1]
        nd = self.num_directions
        h_out = self.proj_size or self.hidden_size

        lens = (ensure_tensor(sequence_length)._value
                if sequence_length is not None else None)

        is_lstm = self.MODE == "LSTM"
        if initial_states is None:
            zeros_h = Tensor._from_value(
                jnp.zeros((self.num_layers * nd, B, h_out), jnp.float32))
            zeros_c = Tensor._from_value(
                jnp.zeros((self.num_layers * nd, B, self.hidden_size),
                          jnp.float32))
            initial_states = (zeros_h, zeros_c) if is_lstm else zeros_h
        if is_lstm:
            h_all, c_all = initial_states
            h_all, c_all = ensure_tensor(h_all), ensure_tensor(c_all)
        else:
            h_all = ensure_tensor(initial_states)
            c_all = None

        x = inputs
        final_h, final_c = [], []
        for layer in range(self.num_layers):
            outs_dir = []
            for d in range(nd):
                idx = layer * nd + d
                weights = self._all_weights[idx]
                h0 = h_all[idx]
                c0 = c_all[idx] if c_all is not None else h0
                outs, h_T, c_T = self._run_direction(
                    x, h0, c0, weights, reverse=bool(d), lens=lens)
                outs_dir.append(outs)
                final_h.append(h_T)
                if c_T is not None:
                    final_c.append(c_T)
            x = outs_dir[0] if nd == 1 else concat(outs_dir, axis=-1)
            if self.dropout > 0.0 and layer < self.num_layers - 1:
                x = dropout_fn(x, self.dropout, training=self.training)

        outputs = x
        if not self.time_major:
            outputs = transpose(outputs, [1, 0, 2])
        h_stack = stack(final_h, axis=0)
        if is_lstm:
            return outputs, (h_stack, stack(final_c, axis=0))
        return outputs, h_stack

    def extra_repr(self):
        return (f"{self.input_size}, {self.hidden_size}, "
                f"num_layers={self.num_layers}, "
                f"bidirectional={self.bidirectional}")


def _lstm_seq_proj(x, h0, c0, w_ih, w_hh, b_ih, b_hh, w_ho, seq_lens, *,
                   reverse, use_lens):
    T = x.shape[0]
    lens = seq_lens if use_lens else None

    def step(carry, xs):
        h, c = carry
        t_idx, x_t = xs
        h_new, c_new = _lstm_step(x_t, h, c, w_ih, w_hh, b_ih, b_hh, w_ho)
        h_new = _mask_step(t_idx, lens, h_new, h)
        c_new = _mask_step(t_idx, lens, c_new, c)
        out = h_new if lens is None else jnp.where(
            (t_idx < lens)[:, None], h_new, 0.0)
        return (h_new, c_new), out

    ts = jnp.arange(T)
    if reverse:
        x = x[::-1]
        ts = ts[::-1]
    (h_T, c_T), outs = jax.lax.scan(step, (h0, c0), (ts, x))
    if reverse:
        outs = outs[::-1]
    return outs, h_T, c_T


defprim("lstm_seq_proj_p", _lstm_seq_proj, multi_out=True)


class SimpleRNN(RNNBase):
    """Reference: nn/layer/rnn.py:1859."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        self.MODE = "RNN_RELU" if activation == "relu" else "RNN_TANH"
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class LSTM(RNNBase):
    """Reference: nn/layer/rnn.py:1982."""

    MODE = "LSTM"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, proj_size=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, None, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr,
                         proj_size)


class GRU(RNNBase):
    """Reference: nn/layer/rnn.py:2119."""

    MODE = "GRU"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, None, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)
