"""``paddle.nn.utils`` — weight reparameterization hooks + grad/param utils.

Reference: python/paddle/nn/utils/ (weight_norm_hook.py,
spectral_norm_hook.py, transform_parameters.py, clip_grad_norm_.py,
clip_grad_value_.py). The hooks use this framework's forward-pre-hook
mechanism: the reparameterized weight is recomputed from the stored
(g, v) / power-iteration state right before each forward.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "weight_norm", "remove_weight_norm", "spectral_norm",
    "parameters_to_vector", "vector_to_parameters", "clip_grad_norm_",
    "clip_grad_value_",
]


def _norm_except(v, dim):
    """||v|| over all axes except `dim`. dim=None or -1 means the whole-
    tensor scalar norm (reference weight_norm_hook.py dim semantics)."""
    import jax.numpy as jnp

    if dim is None or dim == -1:
        return jnp.sqrt(jnp.sum(v * v))
    dim = dim % v.ndim
    axes = tuple(i for i in range(v.ndim) if i != dim)
    shape = [1] * v.ndim
    shape[dim] = v.shape[dim]
    return jnp.sqrt(jnp.sum(v * v, axis=axes)).reshape(shape)


def weight_norm(layer, name="weight", dim=0):
    """w = g * v / ||v||_dim (reference weight_norm_hook.py)."""
    from ..layer import Layer

    if not isinstance(layer, Layer):
        raise TypeError("weight_norm expects a Layer")
    w = getattr(layer, name)
    import jax.numpy as jnp

    v0 = w._value
    g0 = _norm_except(v0, dim)
    g = layer.create_parameter(list(np.shape(g0)), dtype=str(w.dtype))
    v = layer.create_parameter(list(v0.shape), dtype=str(w.dtype))
    g._replace_value(jnp.asarray(g0))
    v._replace_value(jnp.asarray(v0))
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    # the original weight becomes a derived value (not a parameter)
    if name in layer._parameters:
        del layer._parameters[name]

    def _recompute(lyr, _inputs):
        setattr(lyr, name, _wn_weight(g, v, dim))
        return None

    handle = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_handles = getattr(layer, "_weight_norm_handles", {})
    layer._weight_norm_handles[name] = (handle, g, v, dim)
    _recompute(layer, None)
    return layer


def _wn_weight(g, v, dim):
    """Differentiable w = g * v/||v|| built from framework ops."""
    from ...core.tensor import apply

    return apply("weight_norm_w_p", g, v, dim=dim)


def _wn_fwd(g, v, *, dim):
    import jax.numpy as jnp

    n = _norm_except(v, dim)
    return g * (v / jnp.maximum(n, 1e-12))


def remove_weight_norm(layer, name="weight"):
    handles = getattr(layer, "_weight_norm_handles", {})
    if name not in handles:
        raise ValueError(f"no weight_norm hook on parameter {name!r}")
    handle, g, v, dim = handles.pop(name)
    handle.remove()
    import jax.numpy as jnp

    w = layer.create_parameter(list(v.shape), dtype=str(v.dtype))
    w._replace_value(_wn_fwd(g._value, v._value, dim=dim))
    for pname in (name + "_g", name + "_v"):
        if pname in layer._parameters:
            del layer._parameters[pname]
    layer.add_parameter(name, w)
    setattr(layer, name, w)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Divide the weight by its largest singular value, estimated by power
    iteration on persistent u/v buffers (reference spectral_norm_hook.py).

    Power iteration runs detached and only while ``layer.training`` (the
    reference's do_power_iteration); sigma itself is computed ON the tape
    from the weight, so grads keep the -(w/sigma^2) * u v^T term."""
    import jax.numpy as jnp

    from ...core.tensor import Tensor
    from ..layer import Layer

    if not isinstance(layer, Layer):
        raise TypeError("spectral_norm expects a Layer")
    if dim is None:
        # Linear-style weights store [in, out]: normalize over output dim
        dim = 1 if type(layer).__name__ in ("Linear",) else 0
    w = getattr(layer, name)
    w_orig = layer.create_parameter(list(w.shape), dtype=str(w.dtype))
    w_orig._replace_value(w._value)
    if name in layer._parameters:
        del layer._parameters[name]
    layer.add_parameter(name + "_orig", w_orig)

    h = int(w.shape[dim])
    cols = int(np.prod(w.shape)) // h
    rng = np.random.RandomState(0)
    u_buf = Tensor._from_value(
        jnp.asarray(rng.normal(size=(h,)).astype("float32")))
    v_buf = Tensor._from_value(
        jnp.asarray(rng.normal(size=(cols,)).astype("float32")))
    layer.register_buffer(name + "_u", u_buf)
    layer.register_buffer(name + "_v", v_buf)
    perm = [dim] + [i for i in range(len(w.shape)) if i != dim]

    def _apply(lyr, _inputs):
        if lyr.training:
            mat = jnp.transpose(w_orig._value, perm).reshape(h, cols)
            u = u_buf._value
            vv = v_buf._value
            for _ in range(max(1, int(n_power_iterations))):
                vv = mat.T @ u
                vv = vv / (jnp.linalg.norm(vv) + eps)
                u = mat @ vv
                u = u / (jnp.linalg.norm(u) + eps)
            u_buf._replace_value(u)
            v_buf._replace_value(vv)
        from ...core.tensor import apply as _op

        setattr(lyr, name, _op("spectral_norm_w_p", w_orig, u_buf, v_buf,
                               perm=tuple(perm), eps=float(eps)))
        return None

    handle = layer.register_forward_pre_hook(_apply)
    layer._spectral_norm_handles = getattr(layer, "_spectral_norm_handles",
                                           {})
    layer._spectral_norm_handles[name] = handle
    _apply(layer, None)
    return layer


def parameters_to_vector(parameters, name=None):
    """Concatenate flattened parameters (reference
    transform_parameters.py)."""
    from ...ops.manipulation import concat, reshape

    return concat([reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    """Slice a flat vector back into the given parameters (in place)."""
    import jax.numpy as jnp

    from ...ops._helpers import ensure_tensor

    v = ensure_tensor(vec)._value
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if p.shape else 1
        p._replace_value(jnp.reshape(v[off: off + n],
                                     tuple(p.shape)).astype(p._value.dtype))
        off += n
    if off != v.size:
        raise ValueError(
            f"vector has {v.size} elements but parameters take {off}")


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Scale gradients in place so their global norm <= max_norm
    (reference clip_grad_norm_.py). Returns the pre-clip total norm."""
    import jax.numpy as jnp

    from ...core.tensor import Tensor

    if isinstance(parameters, Tensor):
        parameters = [parameters]
    parameters = list(parameters)  # may be a generator; we iterate twice
    grads = [p._grad_value for p in parameters if p._grad_value is not None]
    if not grads:
        return Tensor._from_value(jnp.asarray(0.0, jnp.float32))
    norm_type = float(norm_type)
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"the total norm of gradients is non-finite ({total})")
    clip = jnp.minimum(float(max_norm) / (total + 1e-6), 1.0)
    for p in parameters:
        if p._grad_value is not None:
            p._grad_value = (p._grad_value * clip).astype(
                p._grad_value.dtype)
    return Tensor._from_value(total)


def clip_grad_value_(parameters, clip_value):
    """Clamp gradients into [-clip_value, clip_value] in place
    (reference clip_grad_value_.py)."""
    import jax.numpy as jnp

    from ...core.tensor import Tensor

    if isinstance(parameters, Tensor):
        parameters = [parameters]
    cv = abs(float(clip_value))
    parameters = list(parameters)
    for p in parameters:
        if p._grad_value is not None:
            p._grad_value = jnp.clip(p._grad_value, -cv, cv)


def _register_prims():
    import jax.numpy as jnp

    from ...core import dispatch

    def _sn_fwd(w, u, v, *, perm, eps):
        # sigma = u^T W v computed FROM w inside the traced forward, so the
        # fallback VJP differentiates through it (u, v are constants)
        h = w.shape[perm[0]]
        mat = jnp.transpose(w, perm).reshape(h, -1)
        sigma = u @ (mat @ v)
        return w / jnp.maximum(sigma, eps)

    dispatch.register_primitive("spectral_norm_w_p", _sn_fwd)
    dispatch.register_primitive(
        "weight_norm_w_p", lambda g, v, *, dim: _wn_fwd(g, v, dim=dim))


_register_prims()
