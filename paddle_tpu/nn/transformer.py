"""Transformer layers.

Reference: python/paddle/nn/layer/transformer.py — MultiHeadAttention (:221
q/k/v/out projections, Cache/StaticCache gen_cache, forward :484),
TransformerEncoderLayer (:~640), TransformerEncoder, TransformerDecoderLayer,
TransformerDecoder, Transformer (full seq2seq with
generate_square_subsequent_mask).

Attention rides the framework SDPA path (Pallas flash on chip); caches are
functional tuples returned alongside outputs, matching the reference's
namedtuple Cache semantics.
"""
from __future__ import annotations

import collections

import numpy as np

from ..core.tensor import Tensor
from ..ops._helpers import ensure_tensor
from .common_layers import Dropout, Linear
from .container import LayerList
from .layer import Layer
from .norm_layers import LayerNorm

__all__ = [
    "MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
    "TransformerDecoderLayer", "TransformerDecoder", "Transformer",
]


def _convert_attn_mask(mask, dtype):
    """bool mask (True=keep) -> additive; float mask passes through."""
    if mask is None:
        return None
    mask = ensure_tensor(mask)
    import jax.numpy as jnp

    v = mask._value
    if v.dtype == jnp.bool_:
        v = jnp.where(v, 0.0, -1e9).astype(jnp.float32)
    return Tensor._from_value(v)


class MultiHeadAttention(Layer):
    """Reference: nn/layer/transformer.py MultiHeadAttention."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        assert embed_dim > 0 and num_heads > 0
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr,
                             bias_attr=bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr,
                             bias_attr=bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr,
                             bias_attr=bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr,
                               bias_attr=bias_attr)

    def _shape(self, x):
        from ..ops.manipulation import reshape

        b, s, _ = x.shape
        return reshape(x, [b, s, self.num_heads, self.head_dim])

    def compute_kv(self, key, value):
        return self._shape(self.k_proj(key)), self._shape(self.v_proj(value))

    def gen_cache(self, key, value=None, type=None):
        """Reference :356 — StaticCache for cross-attention (precomputed
        k/v), Cache for incremental self-attention."""
        type = type or MultiHeadAttention.Cache
        if type is MultiHeadAttention.StaticCache:
            k, v = self.compute_kv(key, value if value is not None else key)
            return self.StaticCache(k, v)
        import jax.numpy as jnp

        if value is None:
            # key is a batch-reference tensor
            b = key.shape[0]
            k = Tensor._from_value(jnp.zeros(
                (b, 0, self.num_heads, self.head_dim), jnp.float32))
            return self.Cache(k, k)
        return self.Cache(key, value)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        from ..nn.functional.attention import scaled_dot_product_attention
        from ..ops.manipulation import concat, reshape

        query = ensure_tensor(query)
        key = query if key is None else ensure_tensor(key)
        value = key if value is None else ensure_tensor(value)

        q = self._shape(self.q_proj(query))
        if isinstance(cache, MultiHeadAttention.StaticCache):
            k, v = cache.k, cache.v
        else:
            k, v = self.compute_kv(key, value)
            if isinstance(cache, MultiHeadAttention.Cache):
                k = concat([cache.k, k], axis=1)
                v = concat([cache.v, v], axis=1)
                cache = self.Cache(k, v)

        mask = _convert_attn_mask(attn_mask, q.dtype)
        out = scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout,
            is_causal=False, training=self.training)
        b, s = out.shape[0], out.shape[1]
        out = self.out_proj(reshape(out, [b, s, self.embed_dim]))
        if self.need_weights:
            # flash path doesn't expose probs; recompute explicitly
            import jax
            import jax.numpy as jnp

            qv = q._value.transpose(0, 2, 1, 3).astype(jnp.float32)
            kv_ = k._value.transpose(0, 2, 1, 3).astype(jnp.float32)
            scores = jnp.einsum("bhqd,bhkd->bhqk", qv, kv_) / np.sqrt(
                self.head_dim)
            if mask is not None:
                scores = scores + mask._value
            weights = Tensor._from_value(jax.nn.softmax(scores, axis=-1))
            outs = (out, weights)
        else:
            outs = (out,)
        if cache is not None and not isinstance(
                cache, MultiHeadAttention.StaticCache):
            outs = outs + (cache,)
        return out if len(outs) == 1 else outs


class TransformerEncoderLayer(Layer):
    """Reference: nn/layer/transformer.py TransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead,
                                            dropout=attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr=bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr=bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self._act = activation

    def _activation(self, x):
        from ..ops import activation as A

        return {"relu": A.relu, "gelu": A.gelu}[self._act](x)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            attn_out = self.self_attn(src, src, src, src_mask)
        else:
            attn_out, cache = self.self_attn(src, src, src, src_mask, cache)
        src = residual + self.dropout1(attn_out)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self._activation(
            self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([
            encoder_layer if i == 0 else copy.deepcopy(encoder_layer)
            for i in range(num_layers)
        ])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, src_mask)
            else:
                output, c = layer(output, src_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    """Self-attn + cross-attn + FFN (reference TransformerDecoderLayer)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead,
                                            dropout=attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead,
                                             dropout=attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr,
                              bias_attr=bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr,
                              bias_attr=bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self._act = activation

    def _activation(self, x):
        from ..ops import activation as A

        return {"relu": A.relu, "gelu": A.gelu}[self._act](x)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            attn_out = self.self_attn(tgt, tgt, tgt, tgt_mask)
            new_self_cache = None
        else:
            attn_out, new_self_cache = self.self_attn(
                tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(attn_out)
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            cross_out = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            cross_out = self.cross_attn(tgt, memory, memory, memory_mask,
                                        cache[1])
        tgt = residual + self.dropout2(cross_out)
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self._activation(
            self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (new_self_cache, cache[1]))

    def gen_cache(self, memory):
        incremental = self.self_attn.gen_cache(memory)
        static = self.cross_attn.gen_cache(
            memory, memory, type=MultiHeadAttention.StaticCache)
        return incremental, static


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy

        self.layers = LayerList([
            decoder_layer if i == 0 else copy.deepcopy(decoder_layer)
            for i in range(num_layers)
        ])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                output = layer(output, memory, tgt_mask, memory_mask)
            else:
                output, c = layer(output, memory, tgt_mask, memory_mask,
                                  cache[i])
                new_caches.append(c)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        caches = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            caches = list(zip(*caches))
        return caches


class Transformer(Layer):
    """Full encoder-decoder (reference: nn/layer/transformer.py Transformer)."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    def generate_square_subsequent_mask(self, length):
        import jax.numpy as jnp

        mask = jnp.where(
            jnp.arange(length)[:, None] >= jnp.arange(length)[None, :],
            0.0, float("-inf"),
        ).astype(jnp.float32)
        return Tensor._from_value(mask)
