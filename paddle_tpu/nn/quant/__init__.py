"""paddle.nn.quant — weight-only quantization helpers.

Reference: python/paddle/nn/quant/ (quantized_linear.py
weight_quantize/weight_dequantize/weight_only_linear/llm_int8_linear,
format.py Stub). TPU path: per-channel absmax int8/int4 quantization in
plain jnp; weight_only_linear dequantizes into bf16/fp16 GEMMs (the MXU
has no int8 path exposed here, so memory savings come from storage and
the matmul runs in the activation dtype, matching the reference's
weight-only contract).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops._helpers import ensure_tensor

__all__ = ["Stub", "weight_only_linear", "llm_int8_linear",
           "weight_quantize", "weight_dequantize"]


class Stub:
    """Observer placeholder inserted by quant-aware training configs
    (reference: nn/quant/format.py Stub)."""

    def __init__(self, observer=None):
        self._observer = observer

    def forward(self, x):
        return x

    __call__ = forward


def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """[K, N] weight -> (quantized int8 weight, per-column fp scales).

    Reference: nn/quant/quantized_linear.py weight_quantize (absmax
    per output channel)."""
    w = ensure_tensor(x)._value.astype(jnp.float32)
    if algo not in ("weight_only_int8", "llm.int8", "weight_only_int4"):
        raise ValueError(f"unsupported quant algo: {algo!r}")
    qmax = 7.0 if algo == "weight_only_int4" else 127.0
    scale = jnp.max(jnp.abs(w), axis=0) / qmax
    safe = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(w / safe), -qmax, qmax).astype(jnp.int8)
    return Tensor._from_value(q), Tensor._from_value(scale)


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float16",
                      group_size=-1):
    q = ensure_tensor(x)._value.astype(jnp.float32)
    s = ensure_tensor(scale)._value.astype(jnp.float32)
    return Tensor._from_value((q * s).astype(jnp.dtype(out_dtype)))


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) + bias (reference weight_only_linear)."""
    xv = ensure_tensor(x)._value
    w = ensure_tensor(weight)._value.astype(jnp.float32)
    if weight_scale is not None:
        w = w * ensure_tensor(weight_scale)._value.astype(jnp.float32)
    y = jnp.matmul(xv.astype(jnp.float32), w,
                   preferred_element_type=jnp.float32)
    if bias is not None:
        y = y + ensure_tensor(bias)._value.astype(jnp.float32)
    return Tensor._from_value(y.astype(xv.dtype))


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """LLM.int8 matmul (outlier split on GPU; numerically the dequantized
    GEMM here — reference llm_int8_linear contract)."""
    return weight_only_linear(x, weight, bias=bias,
                              weight_scale=weight_scale)
