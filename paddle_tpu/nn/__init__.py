"""paddle.nn parity surface.

Reference: python/paddle/nn/__init__.py.
"""
from __future__ import annotations

from .layer import Layer
from .param_attr import ParamAttr
from . import initializer
from . import functional
from . import functional as F  # noqa: F401
from . import utils  # noqa: F401
from . import quant  # noqa: F401

from .container import Sequential, LayerList, LayerDict, ParameterList
from .common_layers import (
    Identity, Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout,
    Flatten, Unflatten, Upsample, UpsamplingNearest2D, UpsamplingBilinear2D,
    Bilinear, PixelShuffle, PixelUnshuffle, ChannelShuffle, CosineSimilarity,
    Pad1D, Pad2D, Pad3D, ZeroPad2D,
)
from .conv_layers import (
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from .norm_layers import (
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm, LayerNorm,
    RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm,
)
from .pooling_layers import (
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
)
from .loss_layers import (
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, MarginRankingLoss, HingeEmbeddingLoss,
    CosineEmbeddingLoss, TripletMarginLoss, SoftMarginLoss,
    MultiLabelSoftMarginLoss,
)
from .activation_layers import (
    ReLU, ReLU6, Sigmoid, Tanh, GELU, SiLU, Swish, Mish, Hardswish,
    Hardsigmoid, Hardtanh, Hardshrink, Softshrink, Tanhshrink, Softplus,
    Softsign, LogSigmoid, ELU, SELU, CELU, LeakyReLU, ThresholdedReLU, Maxout,
    Softmax, LogSoftmax, PReLU, RReLU, GLU,
)
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue

from .rnn import (
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN,
    LSTM, GRU,
)
from .transformer import (
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .decode import Decoder, BeamSearchDecoder, dynamic_decode
from .extra_layers import (
    CTCLoss, RNNTLoss, HSigmoidLoss, PoissonNLLLoss, GaussianNLLLoss,
    MultiMarginLoss, TripletMarginWithDistanceLoss,
    AdaptiveLogSoftmaxWithLoss, PairwiseDistance, MaxUnPool1D, MaxUnPool2D,
    MaxUnPool3D, LPPool1D, LPPool2D, FractionalMaxPool2D,
    FractionalMaxPool3D, ZeroPad1D, ZeroPad3D, Fold, Unfold,
    FeatureAlphaDropout, Silu, Softmax2D, SpectralNorm,
)
