"""Layer-surface completion.

Reference: python/paddle/nn/layer/ — loss.py (CTCLoss, RNNTLoss,
HSigmoidLoss, PoissonNLLLoss, GaussianNLLLoss, MultiMarginLoss,
TripletMarginWithDistanceLoss, AdaptiveLogSoftmaxWithLoss), distance.py
(PairwiseDistance), pooling.py (MaxUnPool*, LPPool*, FractionalMaxPool*),
padding.py (ZeroPad1D/3D), common.py (Fold, Unfold, FeatureAlphaDropout,
Unflatten), activation.py (Silu, Softmax2D), norm.py (SpectralNorm).
"""
from __future__ import annotations

import numpy as np

from . import functional as F
from .layer import Layer

__all__ = [
    "CTCLoss", "RNNTLoss", "HSigmoidLoss", "PoissonNLLLoss",
    "GaussianNLLLoss", "MultiMarginLoss", "TripletMarginWithDistanceLoss",
    "AdaptiveLogSoftmaxWithLoss", "PairwiseDistance", "MaxUnPool1D",
    "MaxUnPool2D", "MaxUnPool3D", "LPPool1D", "LPPool2D",
    "FractionalMaxPool2D", "FractionalMaxPool3D", "ZeroPad1D", "ZeroPad3D",
    "Fold", "Unfold", "FeatureAlphaDropout", "Silu", "Softmax2D",
    "SpectralNorm",
]


class CTCLoss(Layer):
    """Reference: nn/layer/loss.py CTCLoss."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class RNNTLoss(Layer):
    """Reference: nn/layer/loss.py RNNTLoss."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           self.blank, self.fastemit_lambda, self.reduction)


class HSigmoidLoss(Layer):
    """Reference: nn/layer/loss.py HSigmoidLoss (default complete-binary
    tree, or custom tree via path_table/path_code when is_custom)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self.is_custom = is_custom
        if not is_custom and num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self.num_classes = num_classes
        # custom mode: num_classes counts the tree's non-leaf nodes, so the
        # table has num_classes rows (reference nn/layer/loss.py:572)
        rows = num_classes if is_custom else num_classes - 1
        self.weight = self.create_parameter(
            [rows, feature_size], attr=weight_attr)
        self.bias = (None if bias_attr is False else self.create_parameter(
            [rows], attr=bias_attr, is_bias=True))

    def forward(self, input, label, path_table=None, path_code=None):
        if self.is_custom and (path_table is None or path_code is None):
            raise ValueError(
                "custom-tree HSigmoidLoss requires path_table and path_code")
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               self.bias, path_table, path_code)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.log_input = log_input
        self.full = full
        self.epsilon = epsilon
        self.reduction = reduction

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, self.log_input, self.full,
                                  self.epsilon, self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full = full
        self.epsilon = epsilon
        self.reduction = reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p = p
        self.margin = margin
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin = margin
        self.swap = swap
        self.reduction = reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Reference: nn/layer/loss.py AdaptiveLogSoftmaxWithLoss — head plus
    factorized tail clusters (div_value controls tail down-projection)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        # last cluster of size 1 (cutoff == n_classes - 1) is valid, like
        # the reference/torch
        if any(c <= 0 or c > n_classes - 1 for c in cutoffs) or \
                sorted(set(cutoffs)) != cutoffs:
            raise ValueError("invalid cutoffs")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        self.shortlist_size = self.cutoffs[0]
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.shortlist_size + self.n_clusters
        self.head_weight = self.create_parameter(
            [in_features, self.head_size])
        self.head_bias = (self.create_parameter([self.head_size],
                                                is_bias=True)
                          if head_bias else None)
        self.tail_weights = []
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            w_down = self.create_parameter([in_features, hsz])
            w_out = self.create_parameter([hsz, osz])
            self.add_parameter(f"tail_down_{i}", w_down)
            self.add_parameter(f"tail_out_{i}", w_out)
            self.tail_weights.append((w_down, w_out))

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs[:-1] + [self.n_classes], self.head_bias)

    def log_prob(self, input):
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..ops._helpers import ensure_tensor

        x = ensure_tensor(input)._value.astype(jnp.float32)
        hw = self.head_weight._value.astype(jnp.float32)
        head = x @ hw
        if self.head_bias is not None:
            head = head + self.head_bias._value
        head_lp = jax.nn.log_softmax(head, axis=-1)
        parts = [head_lp[:, : self.shortlist_size]]
        for i, (w_down, w_out) in enumerate(self.tail_weights):
            tail_lp = jax.nn.log_softmax(
                (x @ w_down._value.astype(jnp.float32))
                @ w_out._value.astype(jnp.float32), axis=-1)
            parts.append(head_lp[:, self.shortlist_size + i: self.shortlist_size + i + 1]
                         + tail_lp)
        return Tensor._from_value(jnp.concatenate(parts, axis=-1))

    def predict(self, input):
        from ..ops.manipulation import argmax

        return argmax(self.log_prob(input), axis=-1)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        return F.pairwise_distance(x, y, self.p, self.epsilon, self.keepdim)


class _MaxUnPoolNd(Layer):
    FN = None
    FORMAT = None

    def __init__(self, kernel_size, stride=None, padding=0, data_format=None,
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.data_format = data_format or self.FORMAT
        self.output_size = output_size

    def forward(self, x, indices):
        return type(self).FN(x, indices, self.kernel_size, self.stride,
                             self.padding, self.data_format,
                             self.output_size)


class MaxUnPool1D(_MaxUnPoolNd):
    FN = staticmethod(F.max_unpool1d)
    FORMAT = "NCL"


class MaxUnPool2D(_MaxUnPoolNd):
    FN = staticmethod(F.max_unpool2d)
    FORMAT = "NCHW"


class MaxUnPool3D(_MaxUnPoolNd):
    FN = staticmethod(F.max_unpool3d)
    FORMAT = "NCDHW"


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool1d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.norm_type = norm_type
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool2d(x, self.norm_type, self.kernel_size, self.stride,
                           self.padding, self.ceil_mode, self.data_format)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.kernel_size = kernel_size
        self.random_u = random_u
        self.return_mask = return_mask

    def forward(self, x):
        return F.fractional_max_pool2d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)


class FractionalMaxPool3D(FractionalMaxPool2D):
    def forward(self, x):
        return F.fractional_max_pool3d(x, self.output_size, self.kernel_size,
                                       self.random_u, self.return_mask)


class ZeroPad1D(Layer):
    """Reference: nn/layer/padding ZeroPad1D — pad [left, right] on NCL."""

    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        self.padding = ([padding, padding] if isinstance(padding, int)
                        else list(padding))
        self.data_format = data_format

    def forward(self, x):
        from ..ops.manipulation import pad as pad_op

        return pad_op(x, self.padding, mode="constant", value=0.0,
                      data_format=self.data_format)


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        self.padding = ([padding] * 6 if isinstance(padding, int)
                        else list(padding))
        self.data_format = data_format

    def forward(self, x):
        from ..ops.manipulation import pad as pad_op

        return pad_op(x, self.padding, mode="constant", value=0.0,
                      data_format=self.data_format)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.output_sizes = output_sizes
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes, self.strides,
                      self.paddings, self.dilations)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.kernel_sizes = kernel_sizes
        self.strides = strides
        self.paddings = paddings
        self.dilations = dilations

    def forward(self, x):
        return F.unfold(x, self.kernel_sizes, self.strides, self.paddings,
                        self.dilations)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, training=self.training)


class Silu(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.silu(x)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW input
    (reference: nn/layer/activation.py Softmax2D)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError("Softmax2D expects 3D/4D input")
        return F.softmax(x, axis=-3)


class SpectralNorm(Layer):
    """Standalone spectral-norm layer: returns weight / sigma_max via power
    iteration (reference: nn/layer/norm.py SpectralNorm)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.epsilon = epsilon
        self._weight_shape = list(weight_shape)
        h = self._weight_shape[dim]
        w = int(np.prod(self._weight_shape)) // h
        from .initializer import Normal

        self.weight_u = self.create_parameter(
            [h], default_initializer=Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, x):
        import jax.numpy as jnp

        from ..core.tensor import Tensor
        from ..ops._helpers import ensure_tensor

        x = ensure_tensor(x)
        perm = [self.dim] + [i for i in range(x.ndim) if i != self.dim]
        w_mat = x._value.transpose(perm).reshape(x.shape[self.dim], -1)
        u = self.weight_u._value
        v = self.weight_v._value
        for _ in range(self.power_iters):
            v = w_mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.epsilon)
            u = w_mat @ v
            u = u / (jnp.linalg.norm(u) + self.epsilon)
        self.weight_u._replace_value(u)
        self.weight_v._replace_value(v)
        sigma = u @ (w_mat @ v)
        return Tensor._from_value(x._value / sigma)
