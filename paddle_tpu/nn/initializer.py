"""Weight initializers.

Reference: python/paddle/nn/initializer/ (Constant, Normal, Uniform,
XavierNormal/Uniform, KaimingNormal/Uniform, TruncatedNormal, Assign,
Orthogonal, Dirac). Each returns a concrete jax array for a (shape, dtype).
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import generator
from ..core.dtype import convert_dtype

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "calculate_gain", "set_global_initializer",
]

_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError

    @staticmethod
    def _fans(shape):
        shape = tuple(shape)
        if len(shape) < 2:
            f = int(np.prod(shape)) if shape else 1
            return f, f
        receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
        # paddle convention: weight shape [in, out] for Linear,
        # [out_c, in_c, k, k] for conv
        if len(shape) == 2:
            fan_in, fan_out = shape[0], shape[1]
        else:
            fan_in, fan_out = shape[1] * receptive, shape[0] * receptive
        return fan_in, fan_out


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, convert_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        k = generator.next_key()
        return self.mean + self.std * jax.random.normal(
            k, tuple(shape), convert_dtype(dtype)
        )


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0, a: float = -2.0, b: float = 2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype="float32"):
        k = generator.next_key()
        return self.mean + self.std * jax.random.truncated_normal(
            k, self.a, self.b, tuple(shape), convert_dtype(dtype)
        )


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        k = generator.next_key()
        return jax.random.uniform(
            k, tuple(shape), convert_dtype(dtype), self.low, self.high
        )


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = self._fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        k = generator.next_key()
        return std * jax.random.normal(k, tuple(shape), convert_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = self._fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        k = generator.next_key()
        return jax.random.uniform(k, tuple(shape), convert_dtype(dtype), -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = self._fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        k = generator.next_key()
        return std * jax.random.normal(k, tuple(shape), convert_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = self._fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        k = generator.next_key()
        return jax.random.uniform(k, tuple(shape), convert_dtype(dtype), -limit, limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        from ..core.tensor import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(v, dtype=convert_dtype(dtype))
        if tuple(arr.shape) != tuple(shape):
            arr = arr.reshape(tuple(shape))
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain: float = 1.0):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        k = generator.next_key()
        return self.gain * jax.nn.initializers.orthogonal()(
            k, tuple(shape), convert_dtype(dtype)
        )


def calculate_gain(nonlinearity: str, param=None) -> float:
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = param if param is not None else 0.01
        return math.sqrt(2.0 / (1 + a * a))
    if nonlinearity == "selu":
        return 3.0 / 4
    raise ValueError(f"unknown nonlinearity {nonlinearity}")


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs
    (reference: nn/initializer/Bilinear — initializer.py BilinearInitializer).
    Weight shape [C_out, C_in, k, k]: each k x k slice gets the bilinear
    interpolation stencil."""

    def __call__(self, shape, dtype="float32"):
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D weight")
        k = shape[-1]
        if shape[-2] != k:
            raise ValueError("Bilinear initializer expects square kernels")
        f = math.ceil(k / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        og = np.ogrid[:k, :k]
        filt = (1 - abs(og[0] / f - c)) * (1 - abs(og[1] / f - c))
        w = np.zeros(shape, dtype="float64")
        w[..., :, :] = filt
        return jnp.asarray(w, convert_dtype(dtype))


class Dirac(Initializer):
    """Identity-preserving conv init (reference: nn/initializer/dirac.py):
    out-channel i passes through in-channel i (mod groups) at the kernel
    center; all else zero."""

    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        if len(shape) < 3:
            raise ValueError("Dirac initializer expects a 3-5D conv weight")
        out_c, in_c = shape[0], shape[1]
        if out_c % self.groups != 0:
            raise ValueError("out_channels must be divisible by groups")
        w = np.zeros(shape, dtype="float64")
        per = out_c // self.groups
        center = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(min(per, in_c)):
                w[(g * per + i, i) + center] = 1.0
        return jnp.asarray(w, convert_dtype(dtype))
