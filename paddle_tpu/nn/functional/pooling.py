"""Pooling functional ops.

Reference: python/paddle/nn/functional/pooling.py over phi pool kernels.
lax.reduce_window maps pooling straight onto the VPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from ...ops._helpers import defprim, ensure_tensor
from .conv import _ntuple

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d",
]


def _window(kernel, stride, padding, n, channels_first, ceil_mode):
    dims = (1, 1) + kernel if channels_first else (1,) + kernel + (1,)
    strides = (1, 1) + stride if channels_first else (1,) + stride + (1,)
    if isinstance(padding, str):
        pads = padding.upper()
    else:
        p = _ntuple(padding, n) if not isinstance(padding, (list, tuple)) or len(padding) != 2 * n else None
        if p is not None:
            pairs = tuple((pi, pi) for pi in p)
        else:
            pairs = tuple((int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n))
        z = ((0, 0), (0, 0)) if channels_first else ((0, 0),)
        pads = ((0, 0), (0, 0)) + pairs if channels_first else ((0, 0),) + pairs + ((0, 0),)
    return dims, strides, pads


def _pool_fwd(x, *, kind, dims, strides, pads, exclusive, ceil_mode):
    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, dims, strides, pads)
    # avg
    ones = jnp.ones_like(x)
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides, pads)
    if exclusive and pads != "VALID":
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, pads)
        return s / cnt
    denom = float(np.prod([d for d in dims]))
    return s / denom


defprim("pool_p", _pool_fwd)


def _pool(x, kind, kernel_size, stride, padding, n, data_format, exclusive=True,
          ceil_mode=False):
    x = ensure_tensor(x)
    channels_first = data_format.startswith("NC")
    kernel = _ntuple(kernel_size, n)
    stride = _ntuple(stride if stride is not None else kernel_size, n)
    dims, strides, pads = _window(kernel, stride, padding, n, channels_first, ceil_mode)
    return apply(
        "pool_p", x, kind=kind, dims=dims, strides=strides, pads=pads,
        exclusive=bool(exclusive), ceil_mode=bool(ceil_mode),
    )


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NCW" if data_format == "NCL" else "NWC"
    if return_mask:
        return _pool_with_mask(x, kernel_size, stride, padding, 1, df,
                               ceil_mode)
    return _pool(x, "max", kernel_size, stride, padding, 1, df, ceil_mode=ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    if return_mask:
        return _pool_with_mask(x, kernel_size, stride, padding, 2,
                               data_format, ceil_mode)
    return _pool(x, "max", kernel_size, stride, padding, 2, data_format,
                 ceil_mode=ceil_mode)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    if return_mask:
        return _pool_with_mask(x, kernel_size, stride, padding, 3,
                               data_format, ceil_mode)
    return _pool(x, "max", kernel_size, stride, padding, 3, data_format,
                 ceil_mode=ceil_mode)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    df = "NCW" if data_format == "NCL" else "NWC"
    return _pool(x, "avg", kernel_size, stride, padding, 1, df, exclusive, ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, "avg", kernel_size, stride, padding, 2, data_format,
                 exclusive, ceil_mode)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, "avg", kernel_size, stride, padding, 3, data_format,
                 exclusive, ceil_mode)


def _adaptive_pool_fwd(x, *, kind, out_sizes, channels_first, n):
    spatial_off = 2 if channels_first else 1
    out = x
    for i, os in enumerate(out_sizes):
        ax = spatial_off + i
        in_size = out.shape[ax]
        # split into os nearly-equal windows (paddle adaptive semantics:
        # start = floor(i*in/out), end = ceil((i+1)*in/out))
        starts = [int(np.floor(j * in_size / os)) for j in range(os)]
        ends = [int(np.ceil((j + 1) * in_size / os)) for j in range(os)]
        if len(set(np.array(ends) - np.array(starts))) == 1:
            w = ends[0] - starts[0]
            stride = starts[1] - starts[0] if os > 1 else 1
            windows = [1] * out.ndim
            strides = [1] * out.ndim
            windows[ax] = w
            strides[ax] = stride
            if kind == "max":
                out = jax.lax.reduce_window(
                    out, -jnp.inf, jax.lax.max, tuple(windows), tuple(strides), "VALID"
                )
            else:
                out = (
                    jax.lax.reduce_window(
                        out, 0.0, jax.lax.add, tuple(windows), tuple(strides), "VALID"
                    )
                    / w
                )
        else:
            pieces = []
            for s, e in zip(starts, ends):
                sl = [slice(None)] * out.ndim
                sl[ax] = slice(s, e)
                seg = out[tuple(sl)]
                red = jnp.max(seg, axis=ax, keepdims=True) if kind == "max" else jnp.mean(
                    seg, axis=ax, keepdims=True
                )
                pieces.append(red)
            out = jnp.concatenate(pieces, axis=ax)
    return out


defprim("adaptive_pool_p", _adaptive_pool_fwd)


def _adaptive(x, kind, output_size, n, data_format):
    x = ensure_tensor(x)
    channels_first = data_format.startswith("NC")
    if isinstance(output_size, (int, np.integer)):
        out_sizes = (int(output_size),) * n
    else:
        spatial_off = 2 if channels_first else 1
        out_sizes = tuple(
            int(o) if o is not None else x.shape[spatial_off + i]
            for i, o in enumerate(output_size)
        )
    return apply(
        "adaptive_pool_p", x, kind=kind, out_sizes=out_sizes,
        channels_first=channels_first, n=n,
    )


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, "avg", output_size, 1, "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, "avg", output_size, 2, data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, "avg", output_size, 3, data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, "max", output_size, 1, "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, "max", output_size, 2, "NCHW")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, "max", output_size, 3, "NCDHW")


def _max_pool_with_index_fwd(x, *, dims, strides, pads, channels_first):
    """Max pool returning (out, flat-spatial argmax indices) — the mask the
    reference's return_mask=True produces (consumed by max_unpool*)."""
    if channels_first:
        spatial = x.shape[2:]
        idx_shape = (1, 1) + tuple(spatial)
    else:
        spatial = x.shape[1:-1]
        idx_shape = (1,) + tuple(spatial) + (1,)
    flat_idx = jnp.arange(int(np.prod(spatial)), dtype=jnp.int32).reshape(
        idx_shape)
    flat_idx = jnp.broadcast_to(flat_idx, x.shape)

    def select(acc, cur):
        acc_v, acc_i = acc
        cur_v, cur_i = cur
        take_cur = cur_v > acc_v
        return (jnp.where(take_cur, cur_v, acc_v),
                jnp.where(take_cur, cur_i, acc_i))

    init_v = (jnp.asarray(-jnp.inf, x.dtype)
              if jnp.issubdtype(x.dtype, jnp.floating)
              else jnp.asarray(jnp.iinfo(x.dtype).min, x.dtype))
    out, idx = jax.lax.reduce_window(
        (x, flat_idx), (init_v, jnp.int32(-1)), select, dims, strides, pads)
    return out, idx


defprim("max_pool_index_p", _max_pool_with_index_fwd, multi_out=True)


def _pool_with_mask(x, kernel_size, stride, padding, n, data_format,
                    ceil_mode):
    x = ensure_tensor(x)
    channels_first = data_format.startswith("NC")
    kernel = _ntuple(kernel_size, n)
    stride = _ntuple(stride if stride is not None else kernel_size, n)
    dims, strides, pads = _window(kernel, stride, padding, n, channels_first,
                                  ceil_mode)
    return apply("max_pool_index_p", x, dims=dims, strides=strides,
                 pads=pads, channels_first=bool(channels_first))
