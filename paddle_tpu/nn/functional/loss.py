"""Loss functional ops.

Reference: python/paddle/nn/functional/loss.py over phi
softmax_with_cross_entropy etc. cross_entropy keeps the reference's
combined softmax+CE semantics (soft/hard labels, ignore_index, weights) —
the log-softmax fusion is numerically stable and XLA-fused on TPU.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from ...ops._helpers import binary_args, defprim, ensure_tensor

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "mse_loss", "l1_loss",
    "nll_loss", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "kl_div", "smooth_l1_loss", "margin_ranking_loss", "square_error_cost",
    "sigmoid_focal_loss", "hinge_embedding_loss", "cosine_embedding_loss",
    "triplet_margin_loss", "soft_margin_loss", "multi_label_soft_margin_loss",
    "log_loss", "npair_loss",
]


def _reduce_loss(loss, reduction):
    from ...ops import math as m

    if reduction == "mean":
        return m.mean(loss)
    if reduction == "sum":
        return m.sum(loss)
    return loss


def _hard_ce_general(logits, label, *, axis, ignore_index, use_softmax):
    axis = axis % logits.ndim
    logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(
        jnp.maximum(logits, 1e-30)
    )
    lbl = label.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    nll = -jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis)
    nll = jnp.squeeze(nll, axis)
    return jnp.where(valid, nll, 0.0)


defprim("hard_ce_p", _hard_ce_general)
defprim(
    "soft_ce_p",
    lambda logits, label, *, axis, use_softmax: -jnp.sum(
        label
        * (
            jax.nn.log_softmax(logits, axis=axis)
            if use_softmax
            else jnp.log(jnp.maximum(logits, 1e-30))
        ),
        axis=axis,
    ),
)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """Reference: functional/loss.py cross_entropy (soft+hard paths,
    ignore_index, per-class weight, label smoothing)."""
    input, label = ensure_tensor(input), ensure_tensor(label)
    from ...ops import math as m

    if label_smoothing > 0.0:
        n_classes = input.shape[axis]
        if not soft_label:
            from ...ops.creation import one_hot

            if label.ndim == input.ndim and label.shape[axis] == 1:
                from ...ops.manipulation import squeeze

                label = squeeze(label, axis)
            label = one_hot(label, n_classes)
            soft_label = True
        from .common import label_smooth

        label = label_smooth(label, epsilon=label_smoothing)

    if soft_label:
        loss = apply(
            "soft_ce_p", input, label.astype(input.dtype), axis=int(axis),
            use_softmax=bool(use_softmax),
        )
    else:
        if label.ndim == input.ndim and label.shape[axis] == 1:
            from ...ops.manipulation import squeeze

            label = squeeze(label, axis)
        loss = apply(
            "hard_ce_p", input, label, axis=int(axis),
            ignore_index=int(ignore_index), use_softmax=bool(use_softmax),
        )
        if weight is not None:
            w = ensure_tensor(weight)
            from ...ops.manipulation import gather

            wsel = gather(w, label.flatten() if label.ndim > 1 else label, 0)
            if label.ndim > 1:
                from ...ops.manipulation import reshape

                wsel = reshape(wsel, label.shape)
            loss = m.multiply(loss, wsel.astype(loss.dtype))
            if reduction == "mean":
                return m.divide(m.sum(loss), m.sum(wsel))
        elif reduction == "mean":
            # reference mean with ignore_index: sum(loss) / count(valid)
            # (loss.py:3066 "denominator: count sample num with
            # class_index != ignore_index")
            from ...ops.comparison import not_equal

            valid = not_equal(label, ignore_index)
            denom = m.sum(valid.astype(loss.dtype))
            denom = m.maximum(denom, ensure_tensor(1.0, dtype=loss.dtype))
            return m.divide(m.sum(loss), denom)
    return _reduce_loss(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    loss = cross_entropy(
        logits, label, soft_label=soft_label, ignore_index=ignore_index,
        reduction="none", axis=axis,
    )
    from ...ops.manipulation import unsqueeze

    loss = unsqueeze(loss, axis)
    if return_softmax:
        from ...ops.activation import softmax

        return loss, softmax(logits, axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    input, label = binary_args(input, label)
    from ...ops import math as m

    return _reduce_loss(m.square(m.subtract(input, label)), reduction)


def square_error_cost(input, label):
    input, label = binary_args(input, label)
    from ...ops import math as m

    return m.square(m.subtract(input, label))


def l1_loss(input, label, reduction="mean", name=None):
    input, label = binary_args(input, label)
    from ...ops import math as m

    return _reduce_loss(m.abs(m.subtract(input, label)), reduction)


def _nll_fwd(logp, label, *, ignore_index):
    lbl = label.astype(jnp.int32)
    valid = lbl != ignore_index
    safe = jnp.where(valid, lbl, 0)
    nll = -jnp.take_along_axis(logp, safe[:, None], axis=1)[:, 0]
    return jnp.where(valid, nll, 0.0)


defprim("nll_p", _nll_fwd)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    input, label = ensure_tensor(input), ensure_tensor(label)
    orig_shape = None
    if input.ndim > 2:
        # N,C,d1..dk → N*prod(d),C
        from ...ops.manipulation import moveaxis, reshape

        c = input.shape[1]
        orig_shape = label.shape
        input = reshape(moveaxis(input, 1, -1), [-1, c])
        label = reshape(label, [-1])
    loss = apply("nll_p", input, label, ignore_index=int(ignore_index))
    from ...ops import math as m

    if weight is not None:
        from ...ops.manipulation import gather

        w = gather(ensure_tensor(weight), label, 0).astype(loss.dtype)
        loss = m.multiply(loss, w)
        if reduction == "mean":
            return m.divide(m.sum(loss), m.sum(w))
    if orig_shape is not None and reduction == "none":
        from ...ops.manipulation import reshape

        loss = reshape(loss, list(orig_shape))
    return _reduce_loss(loss, reduction)


defprim(
    "bce_p",
    lambda x, y: -(y * jnp.log(jnp.maximum(x, 1e-12))
                   + (1 - y) * jnp.log(jnp.maximum(1 - x, 1e-12))),
)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    input, label = binary_args(input, label)
    loss = apply("bce_p", input, label)
    from ...ops import math as m

    if weight is not None:
        loss = m.multiply(loss, ensure_tensor(weight))
    return _reduce_loss(loss, reduction)


defprim(
    "bce_logits_p",
    lambda x, y: jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x))),
)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    logit, label = binary_args(logit, label)
    from ...ops import math as m

    if pos_weight is not None:
        pw = ensure_tensor(pos_weight)
        loss = apply("bce_logits_posw_p", logit, label, pw)
    else:
        loss = apply("bce_logits_p", logit, label)
    if weight is not None:
        loss = m.multiply(loss, ensure_tensor(weight))
    return _reduce_loss(loss, reduction)


defprim(
    "bce_logits_posw_p",
    lambda x, y, pw: (1 - y) * x
    + (1 + (pw - 1) * y) * (jnp.log1p(jnp.exp(-jnp.abs(x))) + jnp.maximum(-x, 0)),
)


defprim(
    "kl_div_p",
    lambda x, y: y * (jnp.log(jnp.maximum(y, 1e-12)) - x),
)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    input, label = binary_args(input, label)
    if log_target:
        loss = apply("kl_div_logt_p", input, label)
    else:
        loss = apply("kl_div_p", input, label)
    if reduction == "batchmean":
        from ...ops import math as m

        return m.divide(m.sum(loss), float(input.shape[0]))
    return _reduce_loss(loss, reduction)


defprim("kl_div_logt_p", lambda x, y: jnp.exp(y) * (y - x))


defprim(
    "smooth_l1_p",
    lambda x, y, *, delta: jnp.where(
        jnp.abs(x - y) < delta,
        0.5 * (x - y) ** 2 / delta,
        jnp.abs(x - y) - 0.5 * delta,
    ),
)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    input, label = binary_args(input, label)
    loss = apply("smooth_l1_p", input, label, delta=float(delta))
    return _reduce_loss(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    input, other = binary_args(input, other)
    label = ensure_tensor(label)
    from ...ops import math as m

    loss = m.clip(
        m.add(m.multiply(m.neg(label), m.subtract(input, other)), margin), min=0.0
    )
    return _reduce_loss(loss, reduction)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    logit, label = binary_args(logit, label)
    loss = apply("focal_p", logit, label, alpha=float(alpha), gamma=float(gamma))
    if normalizer is not None:
        from ...ops import math as m

        loss = m.divide(loss, ensure_tensor(normalizer))
    return _reduce_loss(loss, reduction)


def _focal_fwd(x, y, *, alpha, gamma):
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * y + (1 - p) * (1 - y)
    a_t = alpha * y + (1 - alpha) * (1 - y)
    return a_t * ce * jnp.power(1 - p_t, gamma)


defprim("focal_p", _focal_fwd)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    input, label = binary_args(input, label)
    from ...ops import math as m

    from ...ops.manipulation import where

    loss = where(
        ensure_tensor(label) == 1.0, input, m.clip(m.subtract(float(margin), input), min=0.0)
    )
    return _reduce_loss(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    from .common import cosine_similarity
    from ...ops import math as m
    from ...ops.manipulation import where

    sim = cosine_similarity(input1, input2, axis=-1, eps=1e-12)
    label = ensure_tensor(label)
    loss = where(
        label == 1.0, m.subtract(1.0, sim), m.clip(m.subtract(sim, float(margin)), min=0.0)
    )
    return _reduce_loss(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    from ...ops import math as m
    from ...ops.linalg import norm

    input, positive = binary_args(input, positive)
    negative = ensure_tensor(negative)
    d_pos = norm(m.subtract(input, positive), p=p, axis=-1)
    d_neg = norm(m.subtract(input, negative), p=p, axis=-1)
    if swap:
        d_neg2 = norm(m.subtract(positive, negative), p=p, axis=-1)
        d_neg = m.minimum(d_neg, d_neg2)
    loss = m.clip(m.add(m.subtract(d_pos, d_neg), float(margin)), min=0.0)
    return _reduce_loss(loss, reduction)


def soft_margin_loss(input, label, reduction="mean", name=None):
    input, label = binary_args(input, label)
    loss = apply("soft_margin_p", input, label)
    return _reduce_loss(loss, reduction)


defprim("soft_margin_p", lambda x, y: jnp.log1p(jnp.exp(-y * x)))


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    input, label = binary_args(input, label)
    from ...ops import math as m

    loss = apply("ml_soft_margin_p", input, label)
    if weight is not None:
        loss = m.multiply(loss, ensure_tensor(weight))
    loss = m.mean(loss, axis=-1)
    return _reduce_loss(loss, reduction)


defprim(
    "ml_soft_margin_p",
    lambda x, y: -(
        y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x)
    ),
)


def log_loss(input, label, epsilon=1e-4, name=None):
    input, label = binary_args(input, label)
    return apply("log_loss_p", input, label, eps=float(epsilon))


defprim(
    "log_loss_p",
    lambda x, y, *, eps: -y * jnp.log(x + eps) - (1 - y) * jnp.log(1 - x + eps),
)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    from ...ops import math as m
    from ...ops.manipulation import reshape

    anchor, positive = binary_args(anchor, positive)
    labels = ensure_tensor(labels)
    batch = anchor.shape[0]
    sim = m.matmul(anchor, positive, transpose_y=True)
    lbl = reshape(labels, [batch, 1])
    from ...ops.comparison import equal

    target = equal(lbl, reshape(labels, [1, batch])).astype(anchor.dtype)
    target = m.divide(target, m.sum(target, axis=1, keepdim=True))
    ce = cross_entropy(sim, target, soft_label=True, reduction="mean")
    reg = m.scale(
        m.add(m.sum(m.square(anchor)), m.sum(m.square(positive))),
        l2_reg / anchor.shape[0],
    )
    return m.add(ce, reg)
