"""Vision-geometry functionals.

Reference: python/paddle/nn/functional/vision.py — affine_grid (inverse-
warp sampling grids), grid_sample (bilinear/nearest with zeros/border/
reflection padding), temporal_shift (TSM channel shift), plus
gather_tree (beam backtrace, nn/decode.py-adjacent op).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from ...ops._helpers import defprim, ensure_tensor

__all__ = ["affine_grid", "grid_sample", "temporal_shift", "gather_tree"]


def _affine_grid_fwd(theta, *, out_shape, align_corners):
    n, c, h, w = out_shape

    def linspace(size):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, size)
        step = 2.0 / size
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, size)

    ys = linspace(h)
    xs = linspace(w)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [H*W, 3]
    grid = jnp.einsum("hk,nrk->nhr", base, theta.astype(jnp.float32))
    return grid.reshape(n, h, w, 2)


defprim("affine_grid_p", _affine_grid_fwd)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N, 2, 3] -> sampling grid [N, H, W, 2]
    (reference: vision.py affine_grid)."""
    theta = ensure_tensor(theta)
    if hasattr(out_shape, "_value"):
        out_shape = [int(v) for v in np.asarray(out_shape._value)]
    return apply("affine_grid_p", theta, out_shape=tuple(int(v) for v in out_shape),
                 align_corners=bool(align_corners))


def _unnormalize(coord, size, align_corners):
    if align_corners:
        return (coord + 1.0) / 2.0 * (size - 1)
    return ((coord + 1.0) * size - 1.0) / 2.0


def _reflect(x, lo, hi):
    rng = hi - lo
    if rng <= 0:
        return jnp.zeros_like(x)
    double = 2 * rng
    x = jnp.mod(x - lo, double)
    x = jnp.where(x > rng, double - x, x)
    return x + lo


def _grid_sample_fwd(x, grid, *, mode, padding_mode, align_corners):
    n, c, h, w = x.shape
    gx = _unnormalize(grid[..., 0].astype(jnp.float32), w, align_corners)
    gy = _unnormalize(grid[..., 1].astype(jnp.float32), h, align_corners)

    if padding_mode == "border":
        gx = jnp.clip(gx, 0, w - 1)
        gy = jnp.clip(gy, 0, h - 1)
    elif padding_mode == "reflection":
        if align_corners:
            gx = _reflect(gx, 0, w - 1)
            gy = _reflect(gy, 0, h - 1)
        else:
            gx = jnp.clip(_reflect(gx, -0.5, w - 0.5), 0, w - 1)
            gy = jnp.clip(_reflect(gy, -0.5, h - 0.5), 0, h - 1)

    def sample(ix, iy):
        ok = (ix >= 0) & (ix < w) & (iy >= 0) & (iy < h)
        ixc = jnp.clip(ix, 0, w - 1)
        iyc = jnp.clip(iy, 0, h - 1)
        vals = x[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [N, Hg, Wg, C]
        return jnp.where(ok[..., None], vals, 0.0)

    if mode == "nearest":
        out = sample(jnp.round(gx).astype(jnp.int32),
                     jnp.round(gy).astype(jnp.int32))
    else:  # bilinear
        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = gx - x0
        wy = gy - y0
        out = (
            sample(x0, y0) * ((1 - wx) * (1 - wy))[..., None]
            + sample(x1, y0) * (wx * (1 - wy))[..., None]
            + sample(x0, y1) * ((1 - wx) * wy)[..., None]
            + sample(x1, y1) * (wx * wy)[..., None]
        )
    return out.transpose(0, 3, 1, 2).astype(x.dtype)  # [N, C, Hg, Wg]


defprim("grid_sample_p", _grid_sample_fwd)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Reference: vision.py grid_sample — x [N,C,H,W], grid [N,Hg,Wg,2]."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"unsupported mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unsupported padding_mode {padding_mode!r}")
    return apply("grid_sample_p", ensure_tensor(x), ensure_tensor(grid),
                 mode=mode, padding_mode=padding_mode,
                 align_corners=bool(align_corners))


def _temporal_shift_fwd(x, *, seg_num, shift_ratio):
    nt, c, h, w = x.shape
    n = nt // seg_num
    v = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    back = jnp.concatenate(
        [v[:, 1:, :fold], jnp.zeros_like(v[:, :1, :fold])], axis=1)
    fwd = jnp.concatenate(
        [jnp.zeros_like(v[:, :1, fold:2 * fold]), v[:, :-1, fold:2 * fold]],
        axis=1)
    rest = v[:, :, 2 * fold:]
    return jnp.concatenate([back, fwd, rest], axis=2).reshape(nt, c, h, w)


defprim("temporal_shift_p", _temporal_shift_fwd)


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None,
                   data_format="NCHW"):
    """TSM shift (reference: vision.py temporal_shift): first chunk shifts
    backward in time, second forward, rest untouched."""
    if data_format not in ("NCHW", "NHWC"):
        raise ValueError(f"unsupported data_format {data_format!r}")
    x = ensure_tensor(x)
    if data_format == "NHWC":
        from ...ops.manipulation import transpose

        out = apply("temporal_shift_p", transpose(x, [0, 3, 1, 2]),
                    seg_num=int(seg_num), shift_ratio=float(shift_ratio))
        return transpose(out, [0, 2, 3, 1])
    return apply("temporal_shift_p", x, seg_num=int(seg_num),
                 shift_ratio=float(shift_ratio))


def gather_tree(ids, parents):
    """Beam-search backtrace (reference: tensor/manipulation.py gather_tree;
    op behind BeamSearchDecoder.finalize). ids/parents: [T, B, beam]."""
    ids_v = np.asarray(ensure_tensor(ids)._value)
    par_v = np.asarray(ensure_tensor(parents)._value)
    T, b, beam = ids_v.shape
    out = np.zeros_like(ids_v)
    beam_idx = np.tile(np.arange(beam)[None, :], (b, 1))
    for t in range(T - 1, -1, -1):
        out[t] = np.take_along_axis(ids_v[t], beam_idx, axis=1)
        beam_idx = np.take_along_axis(par_v[t], beam_idx, axis=1)
    return Tensor._from_value(jnp.asarray(out))
