"""Pooling surface completion.

Reference: python/paddle/nn/functional/pooling.py — max_unpool1d/2d/3d
(scatter by recorded argmax indices), lp_pool1d/2d (p-norm windows),
fractional_max_pool2d/3d (pseudo-random window boundaries, Graham 2014).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from ...ops._helpers import defprim, ensure_tensor

__all__ = [
    "max_unpool1d", "max_unpool2d", "max_unpool3d", "lp_pool1d", "lp_pool2d",
    "fractional_max_pool2d", "fractional_max_pool3d",
]


def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


# ---------------------------------------------------------------------------
# max_unpool — scatter values back to the argmax positions
# ---------------------------------------------------------------------------
def _max_unpool_nd(x, indices, *, out_spatial):
    """x/indices: [N, C, *spatial_in]; indices index the FLAT output
    spatial volume per (n, c) like the reference's max_pool return_mask."""
    n, c = x.shape[0], x.shape[1]
    in_flat = int(np.prod(x.shape[2:]))
    out_flat = int(np.prod(out_spatial))
    xv = x.reshape(n, c, in_flat)
    iv = indices.reshape(n, c, in_flat).astype(jnp.int32)
    out = jnp.zeros((n, c, out_flat), x.dtype)
    out = jax.vmap(jax.vmap(
        lambda o, idx, val: o.at[idx].set(val)
    ))(out, iv, xv)
    return out.reshape((n, c) + tuple(out_spatial))


defprim("max_unpool_p", _max_unpool_nd)


def _unpool(x, indices, kernel_size, stride, padding, output_size, nd,
            data_format):
    if data_format not in ("NCL", "NCHW", "NCDHW"):
        raise ValueError(f"unsupported data_format {data_format!r}")
    x = ensure_tensor(x)
    indices = ensure_tensor(indices)
    k = _pair(kernel_size, nd)
    s = _pair(stride if stride is not None else kernel_size, nd)
    p = _pair(padding, nd)
    if output_size is None:
        out_spatial = tuple(
            (x.shape[2 + i] - 1) * s[i] - 2 * p[i] + k[i] for i in range(nd))
    else:
        out_spatial = tuple(output_size[-nd:])
    return apply("max_unpool_p", x, indices, out_spatial=out_spatial)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, output_size, 1,
                   data_format)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, output_size, 2,
                   data_format)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _unpool(x, indices, kernel_size, stride, padding, output_size, 3,
                   data_format)


# ---------------------------------------------------------------------------
# lp_pool — (sum |x|^p)^(1/p) over windows
# ---------------------------------------------------------------------------
def _lp_pool(x, kernel, stride, padding, *, p, ceil_mode, nd):
    spatial = x.shape[2:]
    dims = (1, 1) + kernel
    strides = (1, 1) + stride
    pads = [(0, 0), (0, 0)]
    for i in range(nd):
        lo = hi = padding[i]
        size = spatial[i] + lo + hi
        if ceil_mode:
            out = -(-(size - kernel[i]) // stride[i]) + 1
            need = (out - 1) * stride[i] + kernel[i] - size
            hi += max(0, need)
        pads.append((lo, hi))
    xp = jnp.pad(x.astype(jnp.float32), pads)
    if p == float("inf"):
        return jax.lax.reduce_window(
            xp, -jnp.inf, jax.lax.max, dims, strides, "VALID").astype(x.dtype)
    summed = jax.lax.reduce_window(
        jnp.abs(xp) ** p, 0.0, jax.lax.add, dims, strides, "VALID")
    return (summed ** (1.0 / p)).astype(x.dtype)


defprim("lp_pool_p", lambda x, *, kernel, stride, padding, p, ceil_mode, nd:
        _lp_pool(x, kernel, stride, padding, p=p, ceil_mode=ceil_mode,
                 nd=nd))


def _lp_pool_call(x, norm_type, kernel_size, stride, padding, ceil_mode,
                  data_format, nd, channels_last_fmt):
    from ...ops.manipulation import transpose

    x = ensure_tensor(x)
    k = _pair(kernel_size, nd)
    s = _pair(stride if stride is not None else kernel_size, nd)
    pad = _pair(padding, nd)
    if data_format == channels_last_fmt:
        # channels-last: pool over the middle spatial dims
        perm_in = [0, nd + 1] + list(range(1, nd + 1))
        perm_out = [0] + list(range(2, nd + 2)) + [1]
        out = apply("lp_pool_p", transpose(x, perm_in), kernel=k, stride=s,
                    padding=pad, p=float(norm_type),
                    ceil_mode=bool(ceil_mode), nd=nd)
        return transpose(out, perm_out)
    return apply("lp_pool_p", x, kernel=k, stride=s, padding=pad,
                 p=float(norm_type), ceil_mode=bool(ceil_mode), nd=nd)


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    return _lp_pool_call(x, norm_type, kernel_size, stride, padding,
                         ceil_mode, data_format, 1, "NLC")


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    return _lp_pool_call(x, norm_type, kernel_size, stride, padding,
                         ceil_mode, data_format, 2, "NHWC")


# ---------------------------------------------------------------------------
# fractional max pool (Graham 2014 pseudo-random sequences)
# ---------------------------------------------------------------------------
def _frac_boundaries(in_size, out_size, u):
    """alpha = in/out; index i -> ceil(alpha*(i+u)) - ceil(alpha*u)."""
    alpha = in_size / out_size
    i = np.arange(out_size + 1)
    b = np.ceil(alpha * (i + u)).astype(int) - int(np.ceil(alpha * u))
    b[-1] = in_size
    return b


def _frac_window(bounds_d, i, k, size):
    """(lo, hi) of fractional window i along one axis — the single source
    of the clamp rules shared by the mask and no-mask paths."""
    lo = int(bounds_d[i])
    hi = int(bounds_d[i + 1]) if k is None else min(lo + k, size)
    return lo, max(hi, lo + 1)


def _fractional_pool(x, output_size, kernel_size, u, nd):
    x = ensure_tensor(x)
    spatial = x.shape[2:]
    out_spatial = _pair(output_size, nd)
    bounds = [
        _frac_boundaries(spatial[i], out_spatial[i], u[i]) for i in range(nd)
    ]
    xv = x._value

    def pool_axis(v, axis, b, k, size):
        slices = []
        for i in range(len(b) - 1):
            lo, hi = _frac_window(b, i, k, size)
            slices.append(jnp.max(
                jax.lax.slice_in_dim(v, lo, hi, axis=axis), axis=axis,
                keepdims=True))
        return jnp.concatenate(slices, axis=axis)

    ks = _pair(kernel_size, nd) if kernel_size is not None else [None] * nd
    for i in range(nd):
        xv = pool_axis(xv, 2 + i, bounds[i], ks[i], spatial[i])
    return Tensor._from_value(xv)


def _fractional_pool_with_mask(x, output_size, kernel_size, u, nd):
    """Max + argmax per fractional window; mask holds indices into the
    flattened input spatial dims (reference return_mask semantics)."""
    import itertools

    x = ensure_tensor(x)
    spatial = list(x.shape[2:])
    out_spatial = _pair(output_size, nd)
    bounds = [
        _frac_boundaries(spatial[i], out_spatial[i], u[i]) for i in range(nd)
    ]
    ks = _pair(kernel_size, nd) if kernel_size is not None else [None] * nd
    xv = x._value
    n, c = xv.shape[0], xv.shape[1]
    maxs, idxs = [], []
    for cell in itertools.product(*[range(o) for o in out_spatial]):
        los, his = [], []
        for d, i in enumerate(cell):
            lo, hi = _frac_window(bounds[d], i, ks[d], spatial[d])
            los.append(lo)
            his.append(hi)
        win = xv[(slice(None), slice(None))
                 + tuple(slice(l, h) for l, h in zip(los, his))]
        flat = win.reshape(n, c, -1)
        maxs.append(jnp.max(flat, -1))
        coords = jnp.unravel_index(
            jnp.argmax(flat, -1), [h - l for l, h in zip(los, his)])
        flat_idx = coords[0] + los[0]
        for d in range(1, nd):
            flat_idx = flat_idx * spatial[d] + (coords[d] + los[d])
        idxs.append(flat_idx)
    out = jnp.stack(maxs, -1).reshape(n, c, *out_spatial)
    mask = jnp.stack(idxs, -1).reshape(n, c, *out_spatial).astype(jnp.int32)
    return Tensor._from_value(out), Tensor._from_value(mask)


def fractional_max_pool2d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    """Reference: nn/functional/pooling.py fractional_max_pool2d."""
    from ...core import generator

    if random_u is None:
        key = generator.next_key("local_seed")
        u = float(jax.random.uniform(key, (), minval=1e-4, maxval=1.0 - 1e-4))
    else:
        u = float(random_u)
    if return_mask:
        return _fractional_pool_with_mask(x, output_size, kernel_size,
                                          (u, u), 2)
    return _fractional_pool(x, output_size, kernel_size, (u, u), 2)


def fractional_max_pool3d(x, output_size, kernel_size=None,
                          random_u=None, return_mask=False, name=None):
    from ...core import generator

    if random_u is None:
        key = generator.next_key("local_seed")
        u = float(jax.random.uniform(key, (), minval=1e-4, maxval=1.0 - 1e-4))
    else:
        u = float(random_u)
    if return_mask:
        return _fractional_pool_with_mask(x, output_size, kernel_size,
                                          (u, u, u), 3)
    return _fractional_pool(x, output_size, kernel_size, (u, u, u), 3)
