"""Normalization functional ops.

Reference: python/paddle/nn/functional/norm.py over phi layer_norm /
batch_norm / group_norm kernels; rms_norm parity with
incubate.nn.functional.fused_rms_norm. All forms reduce in float32 and cast
back (bf16-safe on TPU), matching the reference kernels' accumulation dtype.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from ...ops._helpers import defprim, ensure_tensor

__all__ = [
    "layer_norm", "batch_norm", "instance_norm", "group_norm", "rms_norm",
    "normalize", "local_response_norm",
]


def _layer_norm_fwd(x, w, b, *, begin_axis, eps):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    axes = tuple(range(begin_axis, x.ndim))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    shape = [1] * begin_axis + list(x.shape[begin_axis:])
    y = y * w.astype(jnp.float32).reshape(shape) + b.astype(jnp.float32).reshape(shape)
    return y.astype(dtype)


defprim("layer_norm_p", _layer_norm_fwd)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    x = ensure_tensor(x)
    if isinstance(normalized_shape, (int, np.integer)):
        normalized_shape = [int(normalized_shape)]
    begin = x.ndim - len(normalized_shape)
    from ...ops.creation import ones, zeros

    w = ensure_tensor(weight) if weight is not None else ones(normalized_shape, x.dtype)
    b = ensure_tensor(bias) if bias is not None else zeros(normalized_shape, x.dtype)
    return apply("layer_norm_p", x, w, b, begin_axis=begin, eps=float(epsilon))


def _rms_norm_fwd(x, w, *, eps):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(ms + eps) * w.astype(jnp.float32)
    return y.astype(dtype)


defprim("rms_norm_p", _rms_norm_fwd)


def _use_pallas_rms(x) -> bool:
    # mirror of ops/pallas/rms_norm.use_pallas_rms_norm, duplicated so the
    # XLA fallback path never imports the pallas stack
    from ...core.flags import get_flag

    if not get_flag("use_pallas_rms_norm"):
        return False
    if jax.default_backend() != "tpu" and not get_flag("pallas_force_interpret"):
        return False
    hidden = x.shape[-1]
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    return hidden % 128 == 0 and rows % 8 == 0


def rms_norm(x, weight, epsilon=1e-6, name=None):
    """RMSNorm (reference: paddle.incubate.nn.functional.fused_rms_norm,
    phi/kernels/gpu/rms_norm_kernel.cu). Pallas fused kernel on TPU when the
    hidden dim is lane-aligned; XLA composition otherwise."""
    x = ensure_tensor(x)
    w = ensure_tensor(weight)
    if _use_pallas_rms(x):
        from ...ops.pallas import rms_norm as _  # registers the primitive

        return apply("rms_norm_pallas_p", x, w, eps=float(epsilon))
    return apply("rms_norm_p", x, w, eps=float(epsilon))


def _batch_norm_train_fwd(x, w, b, *, eps, ch_axis):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    mean = jnp.mean(xf, axis=axes)
    var = jnp.var(xf, axis=axes)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    y = (xf - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + eps)
    y = y * w.astype(jnp.float32).reshape(shape) + b.astype(jnp.float32).reshape(shape)
    return y.astype(dtype), mean, var


defprim("batch_norm_train_p", _batch_norm_train_fwd, multi_out=True)


def _batch_norm_infer_fwd(x, w, b, rm, rv, *, eps, ch_axis):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]
    y = (xf - rm.astype(jnp.float32).reshape(shape)) * jax.lax.rsqrt(
        rv.astype(jnp.float32).reshape(shape) + eps
    )
    y = y * w.astype(jnp.float32).reshape(shape) + b.astype(jnp.float32).reshape(shape)
    return y.astype(dtype)


defprim("batch_norm_infer_p", _batch_norm_infer_fwd)


def batch_norm(x, running_mean, running_var, weight, bias, training=False,
               momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None,
               name=None):
    """Functional batch_norm; updates running stats in-place when training
    (reference: nn/functional/norm.py batch_norm → phi batch_norm kernel
    which outputs new mean/var)."""
    x = ensure_tensor(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    if x.ndim == 2:
        ch_axis = 1
    use_stats = use_global_stats if use_global_stats is not None else not training
    w, b = ensure_tensor(weight), ensure_tensor(bias)
    if use_stats:
        return apply(
            "batch_norm_infer_p", x, w, b, ensure_tensor(running_mean),
            ensure_tensor(running_var), eps=float(epsilon), ch_axis=ch_axis,
        )
    y, batch_mean, batch_var = apply(
        "batch_norm_train_p", x, w, b, eps=float(epsilon), ch_axis=ch_axis
    )
    # running-stat update (no grad)
    rm, rv = ensure_tensor(running_mean), ensure_tensor(running_var)
    m = float(momentum)
    n = x.size // x.shape[ch_axis]
    unbias = n / max(n - 1, 1)
    rm._replace_value(
        (rm._value.astype(jnp.float32) * m + batch_mean._value * (1 - m)).astype(rm._value.dtype)
    )
    rv._replace_value(
        (rv._value.astype(jnp.float32) * m + batch_var._value * unbias * (1 - m)).astype(
            rv._value.dtype
        )
    )
    return y


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    x = ensure_tensor(x)
    from ...ops.creation import ones, zeros

    c = x.shape[1] if data_format.startswith("NC") else x.shape[-1]
    w = ensure_tensor(weight) if weight is not None else ones([c], x.dtype)
    b = ensure_tensor(bias) if bias is not None else zeros([c], x.dtype)
    return apply(
        "instance_norm_p", x, w, b, eps=float(eps),
        channels_first=data_format.startswith("NC"),
    )


def _instance_norm_fwd(x, w, b, *, eps, channels_first):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    if channels_first:
        axes = tuple(range(2, x.ndim))
        shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    else:
        axes = tuple(range(1, x.ndim - 1))
        shape = [1] * (x.ndim - 1) + [x.shape[-1]]
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * w.astype(jnp.float32).reshape(shape) + b.astype(jnp.float32).reshape(shape)
    return y.astype(dtype)


defprim("instance_norm_p", _instance_norm_fwd)


def _group_norm_fwd(x, w, b, *, groups, eps, channels_first):
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    if channels_first:
        c_ax = 1
    else:
        c_ax = x.ndim - 1
        xf = jnp.moveaxis(xf, -1, 1)
    n, c = xf.shape[0], xf.shape[1]
    rest = xf.shape[2:]
    g = xf.reshape(n, groups, c // groups, *rest)
    axes = tuple(range(2, g.ndim))
    mean = jnp.mean(g, axis=axes, keepdims=True)
    var = jnp.var(g, axis=axes, keepdims=True)
    y = ((g - mean) * jax.lax.rsqrt(var + eps)).reshape(n, c, *rest)
    shape = [1, c] + [1] * len(rest)
    y = y * w.astype(jnp.float32).reshape(shape) + b.astype(jnp.float32).reshape(shape)
    if not channels_first:
        y = jnp.moveaxis(y, 1, -1)
    return y.astype(dtype)


defprim("group_norm_p", _group_norm_fwd)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = ensure_tensor(x)
    channels_first = data_format.startswith("NC")
    c = x.shape[1] if channels_first else x.shape[-1]
    from ...ops.creation import ones, zeros

    w = ensure_tensor(weight) if weight is not None else ones([c], x.dtype)
    b = ensure_tensor(bias) if bias is not None else zeros([c], x.dtype)
    return apply(
        "group_norm_p", x, w, b, groups=int(num_groups), eps=float(epsilon),
        channels_first=channels_first,
    )


defprim(
    "l2_normalize_p",
    lambda x, *, axis, eps, p: x
    / jnp.maximum(
        jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True), 1.0 / p),
        eps,
    ),
)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = ensure_tensor(x)
    return apply(
        "l2_normalize_p", x, axis=int(axis) % x.ndim, eps=float(epsilon), p=float(p)
    )


def _lrn_fwd(x, *, size, alpha, beta, k, channels_first):
    ch_axis = 1 if channels_first else x.ndim - 1
    sq = jnp.square(x)
    c = x.shape[ch_axis]
    half = size // 2
    pads = [(0, 0)] * x.ndim
    pads[ch_axis] = (half, size - half - 1)
    sq = jnp.pad(sq, pads)
    windows = [1] * x.ndim
    windows[ch_axis] = size
    s = jax.lax.reduce_window(sq, 0.0, jax.lax.add, tuple(windows), (1,) * x.ndim, "VALID")
    return x / jnp.power(k + alpha * s, beta)


defprim("lrn_p", _lrn_fwd)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    return apply(
        "lrn_p", ensure_tensor(x), size=int(size), alpha=float(alpha),
        beta=float(beta), k=float(k), channels_first=data_format.startswith("NC"),
    )
