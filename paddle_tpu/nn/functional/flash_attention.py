"""paddle.nn.functional.flash_attention submodule parity.

Reference: python/paddle/nn/functional/flash_attention.py (flash_attention
:198, flash_attn_unpadded :602, scaled_dot_product_attention :991).
"""
from .attention import (  # noqa: F401
    flash_attention, scaled_dot_product_attention, sdp_kernel,
)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen flash attention over packed [total_tokens, H, D] tensors.

    Reference: nn/functional/flash_attention.py:602 (flash_attn_unpadded
    over phi/kernels/gpu/flash_attn_kernel.cu varlen kernels). TPU path:
    one Pallas kernel with in-kernel cu_seqlens (segment-id) masking —
    cu_seqlens are data, so ONE compile serves every segment layout with
    the same packed shape (ops/pallas/flash_attention_varlen.py). GQA
    (H != H_kv), bottom-right-aligned causal masking, and in-kernel
    attention dropout (counter RNG; masks regenerate identically in the
    backward kernels) are all supported. ``fixed_seed_offset`` pins the
    dropout seed for reproducibility; otherwise the 'local_seed'
    generator stream advances per call (mpu/random.py semantics)."""
    import jax
    import jax.numpy as jnp

    from ...core import generator
    from ...core.tensor import Tensor, apply
    from ...ops._helpers import ensure_tensor

    q = ensure_tensor(query)
    k = ensure_tensor(key)
    v = ensure_tensor(value)

    from ...ops.pallas import flash_attention_varlen  # noqa: F401 (registers prim)

    cu_q_t = ensure_tensor(cu_seqlens_q)
    cu_k_t = ensure_tensor(cu_seqlens_k)
    p = float(dropout) if training else 0.0
    if p >= 1.0:
        raise ValueError("flash_attn_unpadded: dropout must be < 1.0, "
                         f"got {dropout}")
    if p > 0.0:
        if fixed_seed_offset is not None:
            seed = Tensor._from_value(
                jnp.asarray([int(fixed_seed_offset)], jnp.int32))
        else:
            key_bits = jax.lax.bitcast_convert_type(
                jax.random.key_data(
                    generator.next_key(rng_name or "local_seed")),
                jnp.int32).ravel()
            seed = Tensor._from_value(key_bits[:1] ^ key_bits[-1:])
        out, _lse = apply("flash_attn_varlen_p", q, k, v, cu_q_t, cu_k_t,
                          seed, causal=bool(causal), scale=float(scale),
                          n_seqs=int(cu_q_t.shape[0]) - 1, dropout_rate=p)
    else:
        out, _lse = apply("flash_attn_varlen_p", q, k, v, cu_q_t, cu_k_t,
                          causal=bool(causal), scale=float(scale),
                          n_seqs=int(cu_q_t.shape[0]) - 1)
    return out, None
