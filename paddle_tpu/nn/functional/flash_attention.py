"""paddle.nn.functional.flash_attention submodule parity.

Reference: python/paddle/nn/functional/flash_attention.py (flash_attention
:198, flash_attn_unpadded :602, scaled_dot_product_attention :991).
"""
from .attention import (  # noqa: F401
    flash_attention, scaled_dot_product_attention, sdp_kernel,
)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen flash attention over packed [total_tokens, H, D] tensors.

    Reference: nn/functional/flash_attention.py:602 (flash_attn_unpadded
    over phi/kernels/gpu/flash_attn_kernel.cu varlen kernels). TPU path:
    one Pallas kernel with in-kernel cu_seqlens (segment-id) masking —
    cu_seqlens are data, so ONE compile serves every segment layout with
    the same packed shape (ops/pallas/flash_attention_varlen.py). GQA
    (H != H_kv) and bottom-right-aligned causal masking are supported;
    dropout inside the kernel is not (dropout > 0 falls back to the
    per-segment dense path)."""
    from ...core.tensor import apply
    from ...ops._helpers import ensure_tensor

    q = ensure_tensor(query)
    k = ensure_tensor(key)
    v = ensure_tensor(value)
    if dropout and training:
        # dropout needs per-element rng inside the kernel; keep the exact
        # dense fallback for this rare training configuration. sdpa always
        # divides by sqrt(D), so pre-scale q to honor the user's scale.
        import math as _math

        from ...ops.manipulation import concat, squeeze, unsqueeze
        from ...ops.math import scale as _scale_op

        import numpy as _np

        q = _scale_op(q, float(scale) * _math.sqrt(q.shape[-1]))
        cu_q = [int(i) for i in ensure_tensor(cu_seqlens_q).tolist()]
        cu_k = [int(i) for i in ensure_tensor(cu_seqlens_k).tolist()]
        outs = []
        for i in range(len(cu_q) - 1):
            len_q = cu_q[i + 1] - cu_q[i]
            len_k = cu_k[i + 1] - cu_k[i]
            mask = None
            if causal:
                # BOTTOM-RIGHT-aligned causal mask, matching the Pallas
                # varlen kernel and the reference varlen contract: query
                # row r attends keys c <= r + (len_k - len_q). sdpa's
                # is_causal is TOP-LEFT aligned, which diverges whenever
                # len_k != len_q.
                r = _np.arange(len_q)[:, None]
                c = _np.arange(len_k)[None, :]
                allow = c <= r + (len_k - len_q)
                # finite large-negative (not -inf): a fully-masked query
                # row (len_k < len_q) must softmax to uniform, not NaN —
                # same choice as _sdpa_xla's causal branch
                mask = ensure_tensor(_np.where(
                    allow, 0.0,
                    _np.finfo(_np.float32).min).astype("float32"))
            o = scaled_dot_product_attention(
                unsqueeze(q[cu_q[i]: cu_q[i + 1]], 0),
                unsqueeze(k[cu_k[i]: cu_k[i + 1]], 0),
                unsqueeze(v[cu_k[i]: cu_k[i + 1]], 0),
                attn_mask=mask,
                dropout_p=dropout, training=training)
            outs.append(squeeze(o, 0))
        return concat(outs, axis=0), None

    from ...ops.pallas import flash_attention_varlen  # noqa: F401 (registers prim)

    cu_q_t = ensure_tensor(cu_seqlens_q)
    cu_k_t = ensure_tensor(cu_seqlens_k)
    out, _lse = apply("flash_attn_varlen_p", q, k, v, cu_q_t, cu_k_t,
                      causal=bool(causal), scale=float(scale),
                      n_seqs=int(cu_q_t.shape[0]) - 1)
    return out, None
