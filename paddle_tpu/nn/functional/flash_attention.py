"""paddle.nn.functional.flash_attention submodule parity.

Reference: python/paddle/nn/functional/flash_attention.py (flash_attention
:198, flash_attn_unpadded :602, scaled_dot_product_attention :991).
"""
from .attention import (  # noqa: F401
    flash_attention, scaled_dot_product_attention, sdp_kernel,
)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen flash attention. The TPU path currently buckets to the padded
    dense form (XLA static shapes); a Pallas varlen kernel is the planned
    fast path."""
    import jax.numpy as jnp

    from ...core.tensor import Tensor
    from ...ops._helpers import ensure_tensor

    q = ensure_tensor(query)
    k = ensure_tensor(key)
    v = ensure_tensor(value)
    cu_q = [int(i) for i in ensure_tensor(cu_seqlens_q).tolist()]
    cu_k = [int(i) for i in ensure_tensor(cu_seqlens_k).tolist()]
    outs = []
    for i in range(len(cu_q) - 1):
        qs = q[cu_q[i] : cu_q[i + 1]]
        ks = k[cu_k[i] : cu_k[i + 1]]
        vs = v[cu_k[i] : cu_k[i + 1]]
        from ...ops.manipulation import unsqueeze, squeeze

        o = scaled_dot_product_attention(
            unsqueeze(qs, 0), unsqueeze(ks, 0), unsqueeze(vs, 0),
            dropout_p=dropout, is_causal=causal, training=training,
        )
        outs.append(squeeze(o, 0))
    from ...ops.manipulation import concat

    return concat(outs, axis=0), None
