"""Convolution functional ops.

Reference: python/paddle/nn/functional/conv.py over phi conv kernels
(gpudnn). TPU design: lax.conv_general_dilated — XLA lowers convs onto the
MXU directly; NHWC is the TPU-preferred layout and both NCHW/NHWC data
formats are supported (XLA inserts transposes for NCHW).
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from ...ops._helpers import defprim, ensure_tensor

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
           "conv3d_transpose"]


def _ntuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _pad_spec(padding, n, data_format):
    """Normalize paddle padding spec → lax pairs or string."""
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, (int, np.integer)):
        return tuple((int(padding), int(padding)) for _ in range(n))
    padding = list(padding)
    if len(padding) == n and not isinstance(padding[0], (list, tuple)):
        return tuple((int(p), int(p)) for p in padding)
    if len(padding) == 2 * n:
        return tuple(
            (int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)
        )
    # paddle also allows [[0,0],[0,0],[ph,ph],[pw,pw]] including batch/channel
    pairs = [tuple(int(x) for x in p) for p in padding]
    if len(pairs) == n + 2:
        if data_format.startswith("NC"):
            pairs = pairs[2:]
        else:
            pairs = pairs[1:-1]
    return tuple(pairs)


def _conv_fwd(x, w, *, strides, padding, dilations, groups, dn, n):
    return jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=padding,
        rhs_dilation=dilations,
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None,
    )


defprim("conv_p", _conv_fwd)


def _conv(x, weight, bias, stride, padding, dilation, groups, data_format, n):
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    strides = _ntuple(stride, n)
    dilations = _ntuple(dilation, n)
    pad = _pad_spec(padding, n, data_format)
    spatial = "DHW"[3 - n :]
    if data_format.startswith("NC"):
        lhs = "NC" + spatial
        out = "NC" + spatial
    else:
        lhs = "N" + spatial + "C"
        out = "N" + spatial + "C"
    rhs = "OI" + spatial  # paddle weight layout [out_c, in_c/groups, *k]
    y = apply(
        "conv_p", x, weight,
        strides=strides, padding=pad, dilations=dilations, groups=int(groups),
        dn=(lhs, rhs, out), n=n,
    )
    if bias is not None:
        bias = ensure_tensor(bias)
        if data_format.startswith("NC"):
            shape = [1, bias.shape[0]] + [1] * n
        else:
            shape = [1] * (n + 1) + [bias.shape[0]]
        from ...ops.manipulation import reshape
        from ...ops.math import add

        y = add(y, reshape(bias, shape))
    return y


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups,
                 "NCW" if data_format == "NCL" else "NWC", 1)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 2)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, data_format, 3)


def _conv_transpose_fwd(x, w, *, strides, padding, output_padding, dilations,
                        groups, dn, n):
    # paddle weight layout for transpose conv: [in_c, out_c/groups, *k]
    if groups > 1:
        # grouped transposed conv via per-group vmap-free concat
        in_per = x.shape[dn[0].index("C")] // groups
        outs = []
        xs = jnp.split(x, groups, axis=dn[0].index("C"))
        ws = jnp.split(w, groups, axis=0)
        for xg, wg in zip(xs, ws):
            outs.append(
                _conv_transpose_fwd(
                    xg, wg, strides=strides, padding=padding,
                    output_padding=output_padding, dilations=dilations,
                    groups=1, dn=dn, n=n,
                )
            )
        return jnp.concatenate(outs, axis=dn[2].index("C"))
    # paddle transpose-conv weight layout is [in_c, out_c/groups, *k]; with
    # transpose_kernel=True lax expects exactly the forward-conv kernel
    # ("OIHW" where O = this op's input channels), i.e. paddle's layout as-is.
    out = jax.lax.conv_transpose(
        x,
        w,
        strides=strides,
        padding=padding,
        rhs_dilation=dilations,
        dimension_numbers=dn,
        transpose_kernel=True,
    )
    if any(output_padding):
        pads = [(0, 0)] * out.ndim
        spatial_axes = [i for i, c in enumerate(dn[2]) if c not in "NC"]
        for ax, op_ in zip(spatial_axes, output_padding):
            pads[ax] = (0, op_)
        out = jnp.pad(out, pads)
    return out


defprim("conv_transpose_p", _conv_transpose_fwd)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, data_format, n, output_size=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    strides = _ntuple(stride, n)
    dilations = _ntuple(dilation, n)
    out_pad = _ntuple(output_padding, n)
    spatial = "DHW"[3 - n :]
    if data_format.startswith("NC"):
        lhs = "NC" + spatial
    else:
        lhs = "N" + spatial + "C"
    dn = (lhs, "OI" + spatial, lhs)
    pad = _pad_spec(padding, n, data_format)
    if isinstance(pad, tuple):
        # lax.conv_transpose interprets padding on the *output*; convert the
        # paddle "input padding" convention: out_pad_lo = k - 1 - p
        k = weight.shape[2:]
        pad = tuple(
            (
                dilations[i] * (k[i] - 1) - pad[i][0],
                dilations[i] * (k[i] - 1) - pad[i][1],
            )
            for i in range(n)
        )
    y = apply(
        "conv_transpose_p", x, weight,
        strides=strides, padding=pad, output_padding=out_pad,
        dilations=dilations, groups=int(groups), dn=dn, n=n,
    )
    if bias is not None:
        bias = ensure_tensor(bias)
        from ...ops.manipulation import reshape
        from ...ops.math import add

        if data_format.startswith("NC"):
            shape = [1, bias.shape[0]] + [1] * n
        else:
            shape = [1] * (n + 1) + [bias.shape[0]]
        y = add(y, reshape(bias, shape))
    return y


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups,
                           "NCW" if data_format == "NCL" else "NWC", 1, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCHW",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 2, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCDHW",
                     name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, data_format, 3, output_size)
