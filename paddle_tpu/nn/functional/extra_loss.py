"""Loss-function surface completion.

Reference: python/paddle/nn/functional/loss.py — ctc_loss, rnnt_loss,
hsigmoid_loss, poisson_nll_loss, gaussian_nll_loss, multi_margin_loss,
triplet_margin_with_distance_loss, dice_loss, adaptive_log_softmax_with_loss
(nn/layer AdaptiveLogSoftmaxWithLoss), margin_cross_entropy, and
distance.py pairwise_distance.

CTC/RNNT are log-space alpha recursions under `lax.scan` — one compiled
while-loop on TPU, differentiated by jax (the adjoint of the recursion IS
the standard beta-pass gradient).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Tensor, apply
from ...ops._helpers import defprim, ensure_tensor

__all__ = [
    "ctc_loss", "rnnt_loss", "hsigmoid_loss", "poisson_nll_loss",
    "gaussian_nll_loss", "multi_margin_loss",
    "triplet_margin_with_distance_loss", "dice_loss", "pairwise_distance",
    "margin_cross_entropy", "class_center_sample",
    "adaptive_log_softmax_with_loss", "sequence_mask",
]

_NEG_INF = -1e30


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------
def _ctc_nll(logits, labels, input_lengths, label_lengths, *, blank):
    """logits [T, B, C] unnormalized; labels [B, L]; returns nll [B]."""
    T, B, C = logits.shape
    L = labels.shape[1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    # extended sequence: blank l1 blank l2 ... lL blank (length S = 2L+1)
    S = 2 * L + 1
    ext = jnp.full((B, S), blank, dtype=jnp.int32)
    ext = ext.at[:, 1::2].set(labels.astype(jnp.int32))
    ext_valid = jnp.arange(S)[None, :] < (2 * label_lengths[:, None] + 1)

    # can we skip from s-2 to s? only when ext[s] != blank and != ext[s-2]
    ext_prev2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (ext != blank) & (ext != ext_prev2)

    alpha0 = jnp.full((B, S), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, blank])
    alpha0 = alpha0.at[:, 1].set(
        jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0])
    # a length-0 label has no position 1
    alpha0 = jnp.where(ext_valid, alpha0, _NEG_INF)

    def step(alpha, t):
        stay = alpha
        prev1 = jnp.pad(alpha, ((0, 0), (1, 0)),
                        constant_values=_NEG_INF)[:, :S]
        prev2 = jnp.pad(alpha, ((0, 0), (2, 0)),
                        constant_values=_NEG_INF)[:, :S]
        prev2 = jnp.where(can_skip, prev2, _NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2)
        emit = jnp.take_along_axis(logp[t], ext, axis=1)
        new_alpha = merged + emit
        new_alpha = jnp.where(ext_valid, new_alpha, _NEG_INF)
        # frozen past each sequence's input length
        alive = (t < input_lengths)[:, None]
        new_alpha = jnp.where(alive, new_alpha, alpha)
        return new_alpha, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    s_last = 2 * label_lengths  # final blank position
    final_blank = jnp.take_along_axis(alpha, s_last[:, None], axis=1)[:, 0]
    final_label = jnp.take_along_axis(
        alpha, jnp.maximum(s_last - 1, 0)[:, None], axis=1)[:, 0]
    final_label = jnp.where(label_lengths > 0, final_label, _NEG_INF)
    return -jnp.logaddexp(final_blank, final_label)


defprim("ctc_loss_p", _ctc_nll)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Reference: nn/functional/loss.py ctc_loss — log_probs [T, B, C]
    unnormalized (log_softmax applied internally), labels [B, L] padded."""
    logits = ensure_tensor(log_probs)
    in_lens = ensure_tensor(input_lengths)
    lab_lens = ensure_tensor(label_lengths)
    nll = apply("ctc_loss_p", logits, ensure_tensor(labels),
                in_lens, lab_lens, blank=int(blank))
    from ...ops import math as m

    if norm_by_times:
        # warpctc norm_by_times normalizes only the GRADIENT by the number
        # of time steps; the reported loss value stays unscaled. Value-
        # preserving trick: forward value = nll, backward flows through
        # nll/T only.
        scaled = m.divide(nll, in_lens.astype("float32"))
        nll = m.add(scaled, m.subtract(nll, scaled).detach())
    if reduction == "mean":
        # reference mean divides each sample by its label length first
        return m.mean(m.divide(
            nll, m.maximum(lab_lens.astype("float32"),
                           ensure_tensor(1.0))))
    if reduction == "sum":
        return m.sum(nll)
    return nll


# ---------------------------------------------------------------------------
# RNN-T
# ---------------------------------------------------------------------------
def _rnnt_alpha_nll(blank_lp, emit_lp, input_lengths, label_lengths):
    """Transducer forward pass given blank/emit log-probs."""
    B, T, U1 = blank_lp.shape

    def step_t(alpha_prev, t):
        # alpha along u for fixed t: alpha[t, u] = logaddexp(
        #   alpha[t-1, u] + blank(t-1, u), alpha[t, u-1] + emit(t, u-1))
        from_blank = alpha_prev + blank_lp[:, t - 1, :]  # [B, U+1]

        def step_u(carry, u):
            # carry: alpha[t, u-1]
            val = jnp.logaddexp(from_blank[:, u],
                                carry + emit_lp[:, t, u - 1])
            return val, val

        first = from_blank[:, 0]
        _, rest = jax.lax.scan(step_u, first, jnp.arange(1, U1))
        alpha_t = jnp.concatenate([first[:, None], rest.T], axis=1)
        alive = (t < input_lengths)[:, None]
        alpha_t = jnp.where(alive, alpha_t, alpha_prev)
        return alpha_t, None

    # t = 0 row: only emissions advance u
    def init_u(carry, u):
        val = carry + emit_lp[:, 0, u - 1]
        return val, val

    _, rest0 = jax.lax.scan(init_u, jnp.zeros((B,)), jnp.arange(1, U1))
    alpha0 = jnp.concatenate([jnp.zeros((B, 1)), rest0.T], axis=1)
    u_ok = jnp.arange(U1)[None, :] <= label_lengths[:, None]
    alpha0 = jnp.where(u_ok, alpha0, _NEG_INF)

    alpha_T, _ = jax.lax.scan(step_t, alpha0, jnp.arange(1, T))
    # final: alpha[T-1, U] + blank(T-1, U)
    t_last = jnp.clip(input_lengths - 1, 0, T - 1)
    final = jnp.take_along_axis(
        alpha_T, label_lengths[:, None], axis=1)[:, 0]
    final_blank = jnp.take_along_axis(
        blank_lp[jnp.arange(B), t_last], label_lengths[:, None], axis=1
    )[:, 0]
    return -(final + final_blank)


def _rnnt_nll(logits, labels, input_lengths, label_lengths, *, blank,
              fastemit_lambda):
    """logits [B, T, U+1, V]; labels [B, U]; transducer alpha recursion.

    FastEmit (arXiv:2010.11148): emission-arc gradients scaled by
    (1 + λ). Implemented as loss + λ·loss_emit where loss_emit shares the
    value of loss but stops gradients through the blank arcs, so only the
    emission terms receive the extra λ gradient weight."""
    T = logits.shape[1]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    blank_lp = logp[..., blank]                      # [B, T, U+1]
    lab = labels.astype(jnp.int32)                   # [B, U]
    emit_lp = jnp.take_along_axis(
        logp[:, :, :-1, :], lab[:, None, :, None].repeat(T, 1), axis=3
    )[..., 0]                                        # [B, T, U]
    nll = _rnnt_alpha_nll(blank_lp, emit_lp, input_lengths, label_lengths)
    if fastemit_lambda > 0.0:
        nll_emit = _rnnt_alpha_nll(jax.lax.stop_gradient(blank_lp), emit_lp,
                                   input_lengths, label_lengths)
        nll = nll + fastemit_lambda * nll_emit - jax.lax.stop_gradient(
            fastemit_lambda * nll_emit)  # value unchanged, grads scaled
    return nll


defprim("rnnt_loss_p", _rnnt_nll)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """Reference: nn/functional/loss.py rnnt_loss — input [B, T, U+1, V]
    joint-network logits."""
    nll = apply("rnnt_loss_p", ensure_tensor(input), ensure_tensor(label),
                ensure_tensor(input_lengths), ensure_tensor(label_lengths),
                blank=int(blank), fastemit_lambda=float(fastemit_lambda))
    from ...ops import math as m

    if reduction == "mean":
        return m.mean(nll)
    if reduction == "sum":
        return m.sum(nll)
    return nll


# ---------------------------------------------------------------------------
# assorted losses
# ---------------------------------------------------------------------------
def _hsigmoid_fwd(x, lab, w, b, *, num_classes, use_bias):
    """Default complete binary tree: internal nodes 0..num_classes-2; leaf
    for class c sits at heap node (c + num_classes - 1)."""
    x = x.astype(jnp.float32)
    lab = lab.reshape(-1).astype(jnp.int32)
    w = w.astype(jnp.float32)
    depth = int(np.ceil(np.log2(max(num_classes, 2))))
    total = jnp.zeros(x.shape[0], jnp.float32)
    node = lab + num_classes - 1
    for _ in range(depth):
        parent = (node - 1) // 2
        is_right = (node % 2 == 0).astype(jnp.float32)  # right child
        valid = (node > 0).astype(jnp.float32)
        pw = w[jnp.clip(parent, 0, w.shape[0] - 1)]
        logit = jnp.sum(x * pw, axis=-1)
        if use_bias:
            logit = logit + b.reshape(-1)[jnp.clip(parent, 0,
                                                   w.shape[0] - 1)]
        # sigmoid cross entropy: target 1 for right branch
        ll = jnp.logaddexp(0.0, logit) - is_right * logit
        total = total + ll * valid
        node = parent
    return total[:, None]


defprim("hsigmoid_loss_p", _hsigmoid_fwd)


def _hsigmoid_custom_fwd(x, w, b, pt, pc, *, use_bias):
    """Custom-tree mode: path_table [N, L] holds the internal-node row of
    each step (< 0 = padding), path_code [N, L] the 0/1 branch label.
    Loss_i = sum_j SCE(x_i . w[pt_ij] + b[pt_ij], pc_ij) over valid steps
    (reference MatrixBitCodeFunctor, phi/kernels/cpu/hsigmoid_loss_kernel)."""
    x = x.astype(jnp.float32)
    w = w.astype(jnp.float32)
    pt = pt.astype(jnp.int32)
    pcf = pc.astype(jnp.float32)
    valid = (pt >= 0).astype(jnp.float32)
    idx = jnp.clip(pt, 0, w.shape[0] - 1)            # [N, L]
    logit = jnp.einsum("nd,nld->nl", x, w[idx])
    if use_bias:
        logit = logit + b.reshape(-1)[idx]
    ll = jnp.logaddexp(0.0, logit) - pcf * logit
    return jnp.sum(ll * valid, axis=-1)[:, None]


defprim("hsigmoid_custom_p", _hsigmoid_custom_fwd)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference: nn/functional/loss.py
    hsigmoid_loss). Default mode walks a complete binary tree from the
    label's leaf; custom mode takes explicit per-sample path_table
    (internal-node rows, < 0 padded) and path_code (0/1 branch labels)."""
    x = ensure_tensor(input)
    w = ensure_tensor(weight)
    b = ensure_tensor(bias) if bias is not None else w
    if path_table is not None or path_code is not None:
        if path_table is None or path_code is None:
            raise ValueError(
                "custom-tree hsigmoid needs BOTH path_table and path_code")
        return apply("hsigmoid_custom_p", x, w, b,
                     ensure_tensor(path_table), ensure_tensor(path_code),
                     use_bias=bias is not None)
    return apply("hsigmoid_loss_p", x, ensure_tensor(label), w, b,
                 num_classes=int(num_classes), use_bias=bias is not None)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    """Reference: nn/functional/loss.py poisson_nll_loss."""
    x = ensure_tensor(input)
    t = ensure_tensor(label)
    from ...ops import math as m

    if log_input:
        loss = m.exp(x) - t * x
    else:
        loss = x - t * m.log(x + ensure_tensor(epsilon))
    if full:
        import jax.numpy as _jnp

        tv = t._value
        stirling = tv * _jnp.log(_jnp.maximum(tv, 1.0)) - tv + \
            0.5 * _jnp.log(2 * _jnp.pi * _jnp.maximum(tv, 1.0))
        stirling = _jnp.where(tv > 1, stirling, 0.0)
        loss = loss + Tensor._from_value(stirling.astype(loss._value.dtype))
    return _reduce(loss, reduction)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """Reference: nn/functional/loss.py gaussian_nll_loss."""
    from ...ops import math as m

    x = ensure_tensor(input)
    t = ensure_tensor(label)
    var = m.maximum(ensure_tensor(variance), ensure_tensor(epsilon))
    loss = 0.5 * (m.log(var) + m.square(t - x) / var)
    if full:
        loss = loss + 0.5 * float(np.log(2 * np.pi))
    return _reduce(loss, reduction)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """Reference: nn/functional/loss.py multi_margin_loss."""
    x = ensure_tensor(input)._value.astype(jnp.float32)  # [N, C]
    lab = ensure_tensor(label)._value.reshape(-1).astype(jnp.int32)
    n, c = x.shape
    x_y = x[jnp.arange(n), lab][:, None]
    margins = jnp.maximum(0.0, margin - x_y + x) ** p
    if weight is not None:
        w = ensure_tensor(weight)._value.astype(jnp.float32)
        margins = margins * w[lab][:, None]
    margins = margins.at[jnp.arange(n), lab].set(0.0)
    loss = Tensor._from_value(jnp.sum(margins, axis=1) / c)
    return _reduce(loss, reduction)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """Reference: nn/functional/loss.py triplet_margin_with_distance_loss."""
    from ...ops import math as m

    if distance_function is None:
        distance_function = lambda a, b: pairwise_distance(a, b)
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        d_neg = m.minimum(d_neg, distance_function(positive, negative))
    loss = m.maximum(d_pos - d_neg + ensure_tensor(float(margin)),
                     ensure_tensor(0.0))
    return _reduce(loss, reduction)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Reference: nn/functional/loss.py dice_loss — input [..., C] probs,
    label [..., 1] ids."""
    from ...ops.creation import one_hot
    from ...ops.manipulation import squeeze
    from ...ops import math as m

    input = ensure_tensor(input)
    label = ensure_tensor(label)
    label = squeeze(label, -1)
    label = one_hot(label, input.shape[-1]).astype(input.dtype)
    reduce_dims = list(range(1, input.ndim))
    inse = m.sum(input * label, axis=reduce_dims)
    dice_denominator = m.sum(input, axis=reduce_dims) + m.sum(
        label, axis=reduce_dims)
    dice_score = 1 - inse * 2 / (dice_denominator + ensure_tensor(epsilon))
    return m.mean(dice_score)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """Reference: nn/functional/distance.py pairwise_distance."""
    x = ensure_tensor(x)
    y = ensure_tensor(y)
    return apply("pairwise_distance_p", x, y, p=float(p),
                 eps=float(epsilon), keepdim=bool(keepdim))


defprim(
    "pairwise_distance_p",
    lambda x, y, *, p, eps, keepdim: jnp.linalg.norm(
        x - y + eps, ord=p, axis=-1, keepdims=keepdim),
)


def _margin_ce_fwd(x, lab, *, margin1, margin2, margin3, scale):
    x = x.astype(jnp.float32)
    lab = lab.reshape(-1).astype(jnp.int32)
    n = x.shape[0]
    theta = jnp.arccos(jnp.clip(x[jnp.arange(n), lab], -1.0 + 1e-7,
                                1.0 - 1e-7))
    target_logit = jnp.cos(margin1 * theta + margin2) - margin3
    logits_m = x.at[jnp.arange(n), lab].set(target_logit) * scale
    logp = jax.nn.log_softmax(logits_m, axis=-1)
    nll = -logp[jnp.arange(n), lab]
    return nll[:, None], jax.nn.softmax(logits_m, axis=-1)


defprim("margin_ce_p", _margin_ce_fwd, multi_out=True)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-family margin softmax (reference: nn/functional/loss.py
    margin_cross_entropy; single-rank path — the TP path shards the class
    dim via the mp mesh axis instead of a process group)."""
    loss, softmax_out = apply(
        "margin_ce_p", ensure_tensor(logits), ensure_tensor(label),
        margin1=float(margin1), margin2=float(margin2),
        margin3=float(margin3), scale=float(scale))
    loss = _reduce(loss, reduction)
    if return_softmax:
        return loss, softmax_out
    return loss


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers (reference: nn/functional/common.py
    class_center_sample). Positive classes always kept; negatives sampled
    uniformly to reach num_samples."""
    from ...core import generator

    lab = np.asarray(ensure_tensor(label)._value).reshape(-1)
    pos = np.unique(lab)
    if len(pos) >= num_samples:
        sampled = np.sort(pos)
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        key = generator.next_key("local_seed")
        perm = np.asarray(jax.random.permutation(key, rest.shape[0]))
        extra = rest[perm[: num_samples - len(pos)]]
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones(num_classes, dtype=np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor._from_value(jnp.asarray(remap[lab])),
            Tensor._from_value(jnp.asarray(sampled)))


def _adaptive_lsm_fwd(x, lab, hw, hb, *tails, cutoffs, use_bias):
    x = x.astype(jnp.float32)
    lab = lab.reshape(-1).astype(jnp.int32)
    shortlist = cutoffs[0]
    head_logits = x @ hw.astype(jnp.float32)
    if use_bias:
        head_logits = head_logits + hb.astype(jnp.float32)
    head_logp = jax.nn.log_softmax(head_logits, axis=-1)

    out = jnp.zeros(x.shape[0], jnp.float32)
    in_short = lab < shortlist
    safe_short = jnp.clip(lab, 0, shortlist - 1)
    out = jnp.where(
        in_short,
        jnp.take_along_axis(head_logp, safe_short[:, None], axis=1)[:, 0],
        out)
    low = shortlist
    n_clusters = len(tails) // 2
    for i in range(n_clusters):
        high = cutoffs[i + 1] if i + 1 < len(cutoffs) else cutoffs[-1]
        w_down = tails[2 * i].astype(jnp.float32)
        w_out = tails[2 * i + 1].astype(jnp.float32)
        cluster_lp = head_logp[:, shortlist + i]
        tail_logp = jax.nn.log_softmax((x @ w_down) @ w_out, axis=-1)
        in_cluster = (lab >= low) & (lab < high)
        safe_idx = jnp.clip(lab - low, 0, tail_logp.shape[1] - 1)
        lp = cluster_lp + jnp.take_along_axis(
            tail_logp, safe_idx[:, None], axis=1)[:, 0]
        out = jnp.where(in_cluster, lp, out)
        low = high
    return out, -out.mean()


defprim("adaptive_lsm_p", _adaptive_lsm_fwd, multi_out=True)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Reference: nn/functional/loss.py adaptive_log_softmax_with_loss —
    hierarchical softmax over frequency-sorted clusters. Returns
    (per-sample logprob output, mean nll loss)."""
    x = ensure_tensor(input)
    hw = ensure_tensor(head_weight)
    hb = ensure_tensor(head_bias) if head_bias is not None else hw
    tails = []
    for pair in tail_weights:
        tails.append(ensure_tensor(pair[0]))
        tails.append(ensure_tensor(pair[1]))
    return apply("adaptive_lsm_p", x, ensure_tensor(label), hw, hb, *tails,
                 cutoffs=tuple(int(c) for c in cutoffs),
                 use_bias=head_bias is not None)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Reference: nn/functional/common.py sequence_mask —
    mask[i, ..., j] = j < x[i, ...]."""
    from ...core.dtype import convert_dtype

    x = ensure_tensor(x)
    lens = x._value
    if maxlen is None:
        maxlen = int(np.asarray(lens).max())
    mask = jnp.arange(int(maxlen))[None, :] < lens.reshape(-1, 1)
    mask = mask.reshape(tuple(lens.shape) + (int(maxlen),))
    return Tensor._from_value(mask.astype(convert_dtype(dtype)))


def _reduce(loss, reduction):
    from ...ops import math as m

    if reduction == "mean":
        return m.mean(loss)
    if reduction == "sum":
        return m.sum(loss)
    return loss
