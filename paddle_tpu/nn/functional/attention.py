"""Attention functional ops.

Reference: python/paddle/nn/functional/flash_attention.py:198 (flash_attention),
:602 (flash_attn_unpadded), :991 (scaled_dot_product_attention) over the
flashattn lib (phi/kernels/gpu/flash_attn_kernel.cu:35).

TPU design: a Pallas flash-attention kernel (ops/pallas/flash_attention.py)
is the fast path on real TPU; a reference XLA composition (fused by the
compiler, fp32 softmax accumulation) is the fallback and the numerics
oracle. Layout is paddle's [batch, seqlen, num_heads, head_dim].
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.flags import get_flag
from ...core.tensor import Tensor, apply
from ...ops._helpers import defprim, ensure_tensor

__all__ = ["scaled_dot_product_attention", "flash_attention", "sdp_kernel"]

def _attn_dropout(probs, key, dropout_p):
    # reference semantics: dropout on the attention WEIGHTS (softmax output),
    # not the output activations (flash_attention.py:991 attn_dropout)
    if dropout_p > 0.0:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          jnp.zeros((), probs.dtype))
    return probs


def _sdpa_xla(q, k, v, key, *, causal, scale, dropout_p):
    # q,k,v: [B, S, H, D] (paddle layout); kv heads may be fewer (GQA)
    qh, kh = q.shape[2], k.shape[2]
    if kh != qh:
        rep = qh // kh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bshd,bthd->bhst", q, k) * scale
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((s, t), bool), t - s)
        logits = jnp.where(mask, logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    probs = _attn_dropout(probs, key, dropout_p)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def _sdpa_mask_xla(q, k, v, mask, key, *, scale, dropout_p):
    qh, kh = q.shape[2], k.shape[2]
    if kh != qh:
        rep = qh // kh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bshd,bthd->bhst", q, k) * scale
    logits = logits + mask.astype(logits.dtype)
    # safe softmax: a row whose keys are ALL masked to -inf outputs exact
    # zeros instead of NaN — the same convention as the Pallas flash
    # kernel's l==0 finalize, so the two routes agree at every Sk
    lf = logits.astype(jnp.float32)
    row_max = jnp.max(lf, axis=-1, keepdims=True)
    dead = row_max == -jnp.inf
    e = jnp.exp(lf - jnp.where(dead, 0.0, row_max))
    denom = jnp.sum(e, axis=-1, keepdims=True)
    probs = jnp.where(dead, 0.0, e / jnp.where(dead, 1.0, denom))
    probs = probs.astype(q.dtype)
    probs = _attn_dropout(probs, key, dropout_p)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


defprim("sdpa_p", _sdpa_xla)
defprim("sdpa_mask_p", _sdpa_mask_xla)


# Masked-SDPA routing crossover, MEASURED on v5e (2026-07-31, fwd+bwd
# carry-chained, 7/8 keys live): S=512 xla 7.65ms vs flash 7.94; S=1024
# 11.80 vs 11.37; S=2048 12.16 vs 11.27; S=4096 14.61 vs 13.00. Below
# this the XLA composition's fused S^2 path is faster; at/above it the
# flash kernel wins AND avoids the O(S^2) probs buffer XLA materializes
# for backward (mandatory at long context).
_MASK_FLASH_MIN_SK = 1024


def _use_pallas(q, k):
    if not get_flag("use_pallas_flash_attention"):
        return False
    if (jax.default_backend() != "tpu"
            and not get_flag("pallas_force_interpret")):
        return False
    # lane-aligned seqlens, MXU-friendly head dim, divisible GQA groups
    return (q.shape[-1] % 64 == 0 and q.shape[1] % 128 == 0
            and k.shape[1] % 128 == 0 and q.shape[2] % k.shape[2] == 0)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """paddle.nn.functional.scaled_dot_product_attention parity
    (flash_attention.py:991). Input layout [B, S, H, D]. Dropout applies to
    the attention weights, matching the reference; the Pallas kernel
    regenerates the dropout mask in-kernel from a counter RNG, so a nonzero
    rate stays on the flash path (the masked path is still XLA)."""
    from ...core import generator

    q, k, v = ensure_tensor(query), ensure_tensor(key), ensure_tensor(value)
    scale = 1.0 / float(np.sqrt(q.shape[-1]))
    p = float(dropout_p) if training else 0.0
    rng = Tensor._from_value(generator.next_key("local_seed"))
    if attn_mask is not None:
        m = ensure_tensor(attn_mask)
        if (_use_pallas(q, k) and p < 1.0 and m.ndim == 4
                and m.shape[1] == 1 and m.shape[2] == 1
                and m.shape[3] == k.shape[1]
                and m.shape[0] in (1, q.shape[0])
                and m.stop_gradient  # flash takes no bias grad; a
                # TRAINABLE additive bias must stay on the XLA path
                and k.shape[1] >= _MASK_FLASH_MIN_SK):
            # [B, 1, 1, Sk] additive padding mask: stays on the flash
            # path as a per-key logit bias instead of the XLA fallback
            from ...ops.pallas.flash_attention import flash_attention_fused

            # a batch-1 mask stays batch-1: the kernel's index map pins
            # it to row 0 rather than materializing B copies
            bias = m.reshape([m.shape[0], m.shape[3]]).astype("float32")
            bias.stop_gradient = True
            # causal=False: the sdpa_mask_p fallback gives the mask
            # precedence over is_causal — both paths must agree
            return flash_attention_fused(
                q, k, v, causal=False, scale=scale,
                dropout_p=p, rng=rng, key_bias=bias)
        out = apply("sdpa_mask_p", q, k, v, m, rng,
                    scale=scale, dropout_p=p)
    elif _use_pallas(q, k) and p < 1.0:
        # p == 1.0 would need 1/(1-p) rescale in-kernel; the XLA path
        # already produces the exact all-zero output for it
        from ...ops.pallas.flash_attention import flash_attention_fused

        out = flash_attention_fused(q, k, v, causal=bool(is_causal),
                                    scale=scale, dropout_p=p, rng=rng)
    else:
        out = apply("sdpa_p", q, k, v, rng, causal=bool(is_causal),
                    scale=scale, dropout_p=p)
    return out


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """paddle.nn.functional.flash_attention.flash_attention parity
    (flash_attention.py:198). Returns (out, softmax_lse-placeholder)."""
    out = scaled_dot_product_attention(
        query, key, value, None, dropout, causal, training
    )
    return out, None


class sdp_kernel:
    """Context manager parity with paddle's kernel-dispatch selector."""

    def __init__(self, enable_flash=True, enable_math=True, enable_mem_efficient=True):
        self.enable_flash = enable_flash
        self._prev = None

    def __enter__(self):
        from ...core import flags

        self._prev = flags.get_flag("use_pallas_flash_attention")
        flags.set_flags({"use_pallas_flash_attention": self.enable_flash})
        return self

    def __exit__(self, *exc):
        from ...core import flags

        flags.set_flags({"use_pallas_flash_attention": self._prev})
        return False
