"""paddle.nn.functional parity surface.

Reference: python/paddle/nn/functional/__init__.py — activation, common,
conv, pooling, norm, loss, input, attention, vision ops.
"""
from __future__ import annotations

# activations live in the ops layer (same functions)
from ...ops.activation import (  # noqa: F401
    relu, relu6, relu_, leaky_relu, elu, selu, celu, gelu, silu, swish, mish,
    sigmoid, hardsigmoid, hardswish, hardtanh, hardshrink, softshrink,
    tanhshrink, softplus, softsign, log_sigmoid, softmax, log_softmax, prelu,
    glu, maxout, thresholded_relu, rrelu, gumbel_softmax,
)
from ...ops.math import tanh  # noqa: F401
from ...ops.manipulation import pad  # noqa: F401

from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from . import flash_attention  # noqa: F401
