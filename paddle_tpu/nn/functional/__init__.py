"""paddle.nn.functional parity surface.

Reference: python/paddle/nn/functional/__init__.py — activation, common,
conv, pooling, norm, loss, input, attention, vision ops.
"""
from __future__ import annotations

# activations live in the ops layer (same functions)
from ...ops.activation import (  # noqa: F401
    relu, relu6, relu_, leaky_relu, elu, selu, celu, gelu, silu, swish, mish,
    sigmoid, hardsigmoid, hardswish, hardtanh, hardshrink, softshrink,
    tanhshrink, softplus, softsign, log_sigmoid, softmax, log_softmax, prelu,
    glu, maxout, thresholded_relu, rrelu, gumbel_softmax,
)
from ...ops.math import tanh  # noqa: F401
from ...ops.manipulation import pad  # noqa: F401

from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from . import flash_attention  # noqa: F401

from .extra_loss import *  # noqa: F401,F403
from .extra_pooling import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403

# inplace activation variants (reference: functional/activation.py *_ ops)
from ...ops.math import _make_inplace as _mi

elu_ = _mi(elu)
hardtanh_ = _mi(hardtanh)
leaky_relu_ = _mi(leaky_relu)
softmax_ = _mi(softmax)
tanh_ = _mi(tanh)
thresholded_relu_ = _mi(thresholded_relu)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """Reference: functional/common.py feature_alpha_dropout — alpha
    dropout over whole channel maps (axis 1)."""
    import jax
    import jax.numpy as jnp

    from ...core import generator
    from ...core.tensor import Tensor
    from ...ops._helpers import ensure_tensor

    x = ensure_tensor(x)
    if not training or p == 0.0:
        return x
    alpha_p = -1.7580993408473766
    key = generator.next_key("local_seed")
    shape = (x.shape[0], x.shape[1]) + (1,) * (x.ndim - 2)
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    a = (1.0 / ((1 - p) * (1 + p * alpha_p ** 2)) ** 0.5)
    b = -a * alpha_p * p
    v = jnp.where(keep, x._value, alpha_p)
    return Tensor._from_value((a * v + b).astype(x._value.dtype))


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """Reference: functional/sparse_attention.py (CUDA block-sparse DSA).
    The TPU path computes the same masked attention from the CSR pattern —
    correctness surface; a Pallas block-sparse kernel is the perf path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ...core.tensor import Tensor
    from ...ops._helpers import ensure_tensor

    q = ensure_tensor(query)._value.astype(jnp.float32)  # [B, H, S, D]
    k = ensure_tensor(key)._value.astype(jnp.float32)
    v = ensure_tensor(value)._value.astype(jnp.float32)
    offs = np.asarray(ensure_tensor(sparse_csr_offset)._value)   # [B, H, S+1]
    cols = np.asarray(ensure_tensor(sparse_csr_columns)._value)  # [B, H, nnz]
    b, h, s, d = q.shape
    # vectorized CSR -> dense mask: expand row ids by per-row counts and
    # scatter once (no per-element Python loop)
    mask = np.full((b, h, s, s), -1e9, dtype=np.float32)
    counts = np.diff(offs, axis=-1)                     # [B, H, S]
    bi, hi = np.meshgrid(np.arange(b), np.arange(h), indexing="ij")
    bi = np.repeat(bi.reshape(b, h, 1), s, axis=2)
    hi = np.repeat(hi.reshape(b, h, 1), s, axis=2)
    rows = np.broadcast_to(np.arange(s)[None, None, :], (b, h, s))
    flat_counts = counts.reshape(-1)
    rep_b = np.repeat(bi.reshape(-1), flat_counts)
    rep_h = np.repeat(hi.reshape(-1), flat_counts)
    rep_r = np.repeat(rows.reshape(-1), flat_counts)
    nnz_per_bh = offs[..., -1]                          # [B, H]
    col_vals = np.concatenate([
        cols[i, j, : nnz_per_bh[i, j]] for i in range(b) for j in range(h)
    ]) if b * h > 1 else cols[0, 0, : nnz_per_bh[0, 0]]
    mask[rep_b, rep_h, rep_r, col_vals] = 0.0
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d) + mask
    if key_padding_mask is not None:
        kpm = ensure_tensor(key_padding_mask)._value.astype(jnp.float32)
        scores = scores + kpm[:, None, None, :]    # [B, S] additive (0/-inf)
    if attn_mask is not None:
        am = ensure_tensor(attn_mask)._value.astype(jnp.float32)
        scores = scores + am[None, None]           # [S, S] additive
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return Tensor._from_value(out.astype(ensure_tensor(query)._value.dtype))


def flash_attention_with_sparse_mask(query, key, value,
                                     attn_mask_start_row_indices=None,
                                     attn_mask_start_row=0, dropout_p=0.0,
                                     is_causal=True, training=True,
                                     name=None):
    """Reference: functional/flash_attention.py flash_attention_with_sparse_mask
    — causal attention where row i additionally masks keys before
    start_row_indices[i]. Composed as an additive mask over the SDPA path."""
    import jax.numpy as jnp

    from ...core.tensor import Tensor
    from ...ops._helpers import ensure_tensor
    from .attention import scaled_dot_product_attention

    q = ensure_tensor(query)
    if attn_mask_start_row_indices is None:
        return scaled_dot_product_attention(q, key, value, None, dropout_p,
                                            is_causal, training)
    sr = ensure_tensor(attn_mask_start_row_indices)._value  # [B, H, S]
    s = q.shape[1]
    rows = jnp.arange(s)[:, None]
    keys = jnp.arange(s)[None, :]
    causal = jnp.where(rows >= keys, 0.0, -1e9)
    # sr[j] is the query ROW from which key-column j becomes masked:
    # mask[i, j] = -inf when i >= sr[j] (reference sparse-mask layout)
    start = sr[:, :, None, :]  # [B, H, 1, S] over key columns
    sparse = jnp.where(rows[None, None] < start, 0.0, -1e9)
    mask = jnp.maximum(causal[None, None] + sparse, -1e9)
    return scaled_dot_product_attention(
        q, key, value, Tensor._from_value(mask.astype(jnp.float32)),
        dropout_p, False, training)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         fixed_seed_offset=None, rng_name="", training=True,
                         name=None):
    """Reference: functional/flash_attention.py flash_attn_qkvpacked —
    qkv [B, S, 3, H, D]."""
    from ...ops.manipulation import unbind
    from .flash_attention import flash_attention

    q, k, v = unbind(qkv, 2)
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax, training=training)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, max_seqlen_q,
                                max_seqlen_k, scale=None, dropout=0.0,
                                causal=False, return_softmax=False,
                                fixed_seed_offset=None, rng_name="",
                                varlen_padded=True, training=True, name=None):
    """Reference: flash_attn_varlen_qkvpacked — packed varlen
    qkv [T, 3, H, D]."""
    from ...ops.manipulation import unbind
    from .flash_attention import flash_attn_unpadded

    q, k, v = unbind(qkv, 1)
    return flash_attn_unpadded(q, k, v, cu_seqlens_q, cu_seqlens_k,
                               max_seqlen_q, max_seqlen_k,
                               scale=scale, dropout=dropout, causal=causal,
                               return_softmax=return_softmax,
                               training=training)
