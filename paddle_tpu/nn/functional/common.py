"""Common functional ops: linear, dropout, embedding, interpolate, etc.

Reference: python/paddle/nn/functional/common.py + input.py (embedding,
one_hot) + phi kernels (dropout with Philox seeds → threefry keys here).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core import generator
from ...core.tensor import Tensor, apply
from ...ops._helpers import defprim, ensure_tensor

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout", "embedding",
    "one_hot", "label_smooth", "cosine_similarity", "bilinear", "interpolate",
    "upsample", "unfold", "fold", "pixel_shuffle", "pixel_unshuffle",
    "channel_shuffle", "zeropad2d",
]


def _linear_fwd(x, w, b):
    y = jnp.matmul(x, w)
    return y + b


def _linear_nobias_fwd(x, w):
    return jnp.matmul(x, w)


defprim("linear_p", _linear_fwd)
defprim("linear_nobias_p", _linear_nobias_fwd)


def linear(x, weight, bias=None, name=None):
    """y = xW + b; weight shape [in, out] (reference: functional/common.py
    linear → phi matmul+add; fused on TPU by XLA)."""
    x, weight = ensure_tensor(x), ensure_tensor(weight)
    if bias is None:
        return apply("linear_nobias_p", x, weight)
    return apply("linear_p", x, weight, ensure_tensor(bias))


defprim(
    "dropout_p",
    lambda x, key, *, p, upscale: _dropout_fwd(x, key, p, upscale),
)


def _dropout_fwd(x, key, p, upscale):
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if upscale:
        return jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))
    return jnp.where(keep, x, jnp.zeros((), x.dtype))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = ensure_tensor(x)
    p = float(p)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            from ...ops.math import scale

            return scale(x, 1.0 - p)
        return x
    if p == 1.0:
        from ...ops.math import multiply
        from ...ops.creation import zeros_like

        return multiply(x, zeros_like(x))
    key = Tensor._from_value(generator.next_key("local_seed"))
    if axis is not None:
        ax = (axis,) if isinstance(axis, int) else tuple(axis)
        return apply(
            "dropout_axis_p", x, key, p=p, upscale=(mode == "upscale_in_train"),
            axis=ax,
        )
    return apply("dropout_p", x, key, p=p, upscale=(mode == "upscale_in_train"))


def _dropout_axis_fwd(x, key, *, p, upscale, axis):
    shape = tuple(x.shape[i] if i in axis else 1 for i in range(x.ndim))
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    if upscale:
        return jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))
    return jnp.where(keep, x, jnp.zeros((), x.dtype))


defprim("dropout_axis_p", _dropout_axis_fwd)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return ensure_tensor(x)
    x = ensure_tensor(x)
    key = Tensor._from_value(generator.next_key("local_seed"))
    return apply("alpha_dropout_p", x, key, p=float(p))


def _alpha_dropout_fwd(x, key, *, p):
    alpha = 1.6732632423543772
    scale_ = 1.0507009873554805
    alpha_p = -alpha * scale_
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    a = ((1 - p) * (1 + p * alpha_p**2)) ** -0.5
    b = -a * alpha_p * p
    return a * jnp.where(keep, x, jnp.full((), alpha_p, x.dtype)) + b


defprim("alpha_dropout_p", _alpha_dropout_fwd)


def _embedding_fwd(w, ids, *, padding_idx):
    out = jnp.take(w, ids.astype(jnp.int32), axis=0)
    if padding_idx is not None:
        mask = (ids == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros((), out.dtype), out)
    return out


def _embedding_vjp(grads_out, saved, *, padding_idx):
    (g,) = grads_out
    w_shape, w_dtype, ids = saved
    if padding_idx is not None:
        g = jnp.where((ids == padding_idx)[..., None], 0, g)
    gw = jnp.zeros(w_shape, jnp.float32 if w_dtype == jnp.bfloat16 else w_dtype)
    gw = gw.at[ids.astype(jnp.int32)].add(g.astype(gw.dtype))
    return (gw.astype(w_dtype), None)


defprim(
    "embedding_p",
    _embedding_fwd,
    vjp=_embedding_vjp,
    save=lambda ins, outs: (ins[0].shape, ins[0].dtype, ins[1]),
)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Reference: nn/functional/input.py embedding (note arg order: ids
    first). Grad scatter accumulates in f32 when weights are bf16."""
    ids, w = ensure_tensor(x), ensure_tensor(weight)
    from ...core.flags import get_flag

    if (get_flag("check_embedding_bounds")
            and isinstance(ids._value, jax.Array)
            and not isinstance(ids._value, jax.core.Tracer)
            and ids._value.size):
        # eager-mode bounds check (reference embedding kernels enforce
        # this, funcs/embedding_util.h); must skip tracers AND static-
        # capture ShapeDtypeStruct placeholders — under jit/capture the
        # gather keeps XLA's OOB fill semantics. Both extrema in one
        # device->host transfer.
        lo, hi = (int(e) for e in np.asarray(jnp.stack(
            [jnp.min(ids._value), jnp.max(ids._value)])))
        n = w.shape[0]
        if lo < 0 or hi >= n:
            raise ValueError(
                "Variable value (input) of OP(paddle.nn.functional."
                f"embedding) expected >= 0 and < {n}, but got "
                f"{lo if lo < 0 else hi}. Please check input value.")
    pi = None
    if padding_idx is not None:
        pi = int(padding_idx)
        if pi < 0:
            pi += w.shape[0]
    return apply("embedding_p", w, ids, padding_idx=pi)


def one_hot(x, num_classes, name=None):
    from ...ops.creation import one_hot as _oh

    return _oh(x, num_classes)


defprim(
    "label_smooth_p",
    lambda label, *, eps: label * (1.0 - eps) + eps / label.shape[-1],
)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = ensure_tensor(label)
    if prior_dist is not None:
        pd = ensure_tensor(prior_dist)
        from ...ops.math import add, scale, multiply

        return add(scale(label, 1 - epsilon), scale(pd, epsilon))
    return apply("label_smooth_p", label, eps=float(epsilon))


defprim(
    "cosine_similarity_p",
    lambda x1, x2, *, axis, eps: jnp.sum(x1 * x2, axis=axis)
    / jnp.maximum(
        jnp.linalg.norm(x1, axis=axis) * jnp.linalg.norm(x2, axis=axis), eps
    ),
)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    from ...ops._helpers import binary_args

    x1, x2 = binary_args(x1, x2)
    return apply("cosine_similarity_p", x1, x2, axis=int(axis), eps=float(eps))


def bilinear(x1, x2, weight, bias=None, name=None):
    x1, x2, weight = ensure_tensor(x1), ensure_tensor(x2), ensure_tensor(weight)
    if bias is None:
        return apply("bilinear_nobias_p", x1, x2, weight)
    return apply("bilinear_p", x1, x2, weight, ensure_tensor(bias))


defprim(
    "bilinear_nobias_p",
    lambda x1, x2, w: jnp.einsum("bi,oij,bj->bo", x1, w, x2),
)
defprim(
    "bilinear_p",
    lambda x1, x2, w, b: jnp.einsum("bi,oij,bj->bo", x1, w, x2) + b,
)


# ---------------------------------------------------------------------------
# interpolate / upsample
# ---------------------------------------------------------------------------
def _interp_fwd(x, *, size, mode, align_corners, channels_first):
    if channels_first:
        spatial = x.shape[2:]
        n_sp = len(spatial)
        moved = jnp.moveaxis(x, 1, -1)  # N, *sp, C
    else:
        spatial = x.shape[1:-1]
        n_sp = len(spatial)
        moved = x
    jmode = {
        "nearest": "nearest",
        "bilinear": "linear",
        "linear": "linear",
        "trilinear": "linear",
        "bicubic": "cubic",
        "area": "linear",
    }[mode]
    out_shape = (moved.shape[0],) + tuple(size) + (moved.shape[-1],)
    if align_corners and jmode != "nearest":
        # jax.image.resize has no align_corners; emulate via scale_and_translate
        out = _align_corners_resize(moved, tuple(size), jmode)
    else:
        out = jax.image.resize(moved, out_shape, method=jmode)
    if channels_first:
        out = jnp.moveaxis(out, -1, 1)
    return out


def _align_corners_resize(x, size, method):
    # x: N, *sp, C
    n_sp = len(size)
    spatial = x.shape[1 : 1 + n_sp]
    scale = jnp.array(
        [(o - 1) / (i - 1) if i > 1 else 1.0 for i, o in zip(spatial, size)],
        jnp.float32,
    )
    translate = jnp.zeros((n_sp,), jnp.float32) + 0.5 * (1 - scale) * 0
    # align_corners maps pixel centers: out coord j ↔ in coord j*(i-1)/(o-1)
    scale_ac = jnp.array(
        [(i - 1) / (o - 1) if o > 1 else 0.0 for i, o in zip(spatial, size)],
        jnp.float32,
    )
    # use scale_and_translate: out = resize with scale = 1/scale_ac
    inv = jnp.where(scale_ac > 0, 1.0 / jnp.maximum(scale_ac, 1e-12), 1.0)
    translate = 0.5 * (inv - 1)
    out_shape = (x.shape[0],) + tuple(size) + (x.shape[-1],)
    return jax.image.scale_and_translate(
        x, out_shape, list(range(1, 1 + n_sp)), inv, translate,
        method={"linear": "linear", "cubic": "cubic"}[method],
    )


defprim("interpolate_p", _interp_fwd)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format=None, name=None):
    x = ensure_tensor(x)
    n_sp = x.ndim - 2
    if data_format is None:
        data_format = {1: "NCW", 2: "NCHW", 3: "NCDHW"}[n_sp]
    channels_first = data_format.startswith("NC")
    spatial = x.shape[2:] if channels_first else x.shape[1:-1]
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * n_sp
        if isinstance(scale_factor, Tensor):
            scale_factor = scale_factor.tolist()
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    else:
        if isinstance(size, Tensor):
            size = size.tolist()
        size = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in size]
    return apply(
        "interpolate_p", x, size=tuple(size), mode=mode,
        align_corners=bool(align_corners), channels_first=channels_first,
    )


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format=None, name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def _unfold_fwd(x, *, k, s, p, d):
    n, c = x.shape[0], x.shape[1]
    patches = jax.lax.conv_general_dilated_patches(
        x, filter_shape=k, window_strides=s,
        padding=tuple((pi, pi) for pi in p), rhs_dilation=d,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    # patches: [N, C*kh*kw, oh, ow] → [N, C*kh*kw, L]
    return patches.reshape(n, patches.shape[1], -1)


defprim("unfold_p", _unfold_fwd)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from .conv import _ntuple

    return apply(
        "unfold_p", ensure_tensor(x), k=_ntuple(kernel_sizes, 2),
        s=_ntuple(strides, 2), p=_ntuple(paddings, 2), d=_ntuple(dilations, 2),
    )


def _fold_fwd(x, *, output_sizes, k, s, p, d):
    n, ckk, L = x.shape
    c = ckk // (k[0] * k[1])
    oh = (output_sizes[0] + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
    ow = (output_sizes[1] + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
    cols = x.reshape(n, c, k[0], k[1], oh, ow)
    out = jnp.zeros((n, c, output_sizes[0] + 2 * p[0], output_sizes[1] + 2 * p[1]), x.dtype)
    for i in range(k[0]):
        for j in range(k[1]):
            hi = i * d[0]
            wj = j * d[1]
            out = out.at[:, :, hi : hi + oh * s[0] : s[0], wj : wj + ow * s[1] : s[1]].add(
                cols[:, :, i, j]
            )
    if p[0] or p[1]:
        out = out[:, :, p[0] : out.shape[2] - p[0], p[1] : out.shape[3] - p[1]]
    return out


defprim("fold_p", _fold_fwd)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    from .conv import _ntuple

    return apply(
        "fold_p", ensure_tensor(x), output_sizes=_ntuple(output_sizes, 2),
        k=_ntuple(kernel_sizes, 2), s=_ntuple(strides, 2), p=_ntuple(paddings, 2),
        d=_ntuple(dilations, 2),
    )


def _pixel_shuffle_fwd(x, *, factor, channels_first):
    if not channels_first:
        x = jnp.moveaxis(x, -1, 1)
    n, c, h, w = x.shape
    r = factor
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3).reshape(n, c // (r * r), h * r, w * r)
    if not channels_first:
        out = jnp.moveaxis(out, 1, -1)
    return out


defprim("pixel_shuffle_p", _pixel_shuffle_fwd)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    return apply(
        "pixel_shuffle_p", ensure_tensor(x), factor=int(upscale_factor),
        channels_first=data_format.startswith("NC"),
    )


def _pixel_unshuffle_fwd(x, *, factor, channels_first):
    if not channels_first:
        x = jnp.moveaxis(x, -1, 1)
    n, c, h, w = x.shape
    r = factor
    out = x.reshape(n, c, h // r, r, w // r, r)
    out = out.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r, h // r, w // r)
    if not channels_first:
        out = jnp.moveaxis(out, 1, -1)
    return out


defprim("pixel_unshuffle_p", _pixel_unshuffle_fwd)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    return apply(
        "pixel_unshuffle_p", ensure_tensor(x), factor=int(downscale_factor),
        channels_first=data_format.startswith("NC"),
    )


def _channel_shuffle_fwd(x, *, groups, channels_first):
    if not channels_first:
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    rest = x.shape[2:]
    out = x.reshape(n, groups, c // groups, *rest)
    out = jnp.swapaxes(out, 1, 2).reshape(n, c, *rest)
    if not channels_first:
        out = jnp.moveaxis(out, 1, -1)
    return out


defprim("channel_shuffle_p", _channel_shuffle_fwd)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    return apply(
        "channel_shuffle_p", ensure_tensor(x), groups=int(groups),
        channels_first=data_format.startswith("NC"),
    )


def zeropad2d(x, padding, data_format="NCHW", name=None):
    from ...ops.manipulation import pad as _pad

    return _pad(x, padding, mode="constant", value=0.0, data_format=data_format)
