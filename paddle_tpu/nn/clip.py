"""Gradient clipping.

Reference: python/paddle/nn/clip.py (ClipGradByValue, ClipGradByNorm,
ClipGradByGlobalNorm — applied by the optimizer before the update step).
Global-norm clip computes the norm in float32 across all grads (one fused
XLA reduction on TPU).
"""
from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def _dygraph_clip(self, params_grads):
        raise NotImplementedError

    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or getattr(p, "need_clip", True) is False:
                out.append((p, g))
                continue
            out.append((p, Tensor._from_value(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            gv = g._value
            norm = jnp.sqrt(jnp.sum(jnp.square(gv.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor._from_value((gv.astype(jnp.float32) * scale).astype(gv.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _dygraph_clip(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None:
                continue
            sq.append(jnp.sum(jnp.square(g._value.astype(jnp.float32))))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            gv = g._value
            out.append(
                (p, Tensor._from_value((gv.astype(jnp.float32) * scale).astype(gv.dtype)))
            )
        return out


# functional forms (paddle.nn.utils.clip_grad_norm_)
def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p._grad_value for p in parameters if p._grad_value is not None]
    if not grads:
        return Tensor._from_value(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g.astype(jnp.float32)), norm_type)) for g in grads),
            1.0 / norm_type,
        )
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p._grad_value is not None:
            p._grad_value = (p._grad_value.astype(jnp.float32) * clip_coef).astype(
                p._grad_value.dtype
            )
    return Tensor._from_value(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p._grad_value is not None:
            p._grad_value = jnp.clip(p._grad_value, -clip_value, clip_value)
