"""Dynamic decoding (beam search).

Reference: python/paddle/nn/decode.py — Decoder protocol (initialize/step/
finalize :42), BeamSearchDecoder (:153; OutputWrapper/StateWrapper
namedtuples, tile_beam_merge_with_batch :241, gather_tree finalize :630),
dynamic_decode loop (:994).

The decode loop is host-driven (data-dependent termination); each step's
math is framework ops, so one jit-compiled cell step per token on TPU.
"""
from __future__ import annotations

import collections

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..ops._helpers import ensure_tensor
from .layer import Layer

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """Abstract decode protocol (reference decode.py:42)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Reference: decode.py:153."""

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)

    # -- beam layout helpers ------------------------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] by repeating each batch row beam times
        (reference :241)."""
        x = ensure_tensor(x)
        v = jnp.repeat(x._value[:, None], beam_size, axis=1)
        return Tensor._from_value(v.reshape((-1,) + x._value.shape[1:]))

    def _split(self, v):
        return v.reshape((-1, self.beam_size) + v.shape[1:])

    def _merge(self, v):
        return v.reshape((-1,) + v.shape[2:])

    def initialize(self, initial_cell_states):
        import jax

        cell_states = jax.tree_util.tree_map(
            lambda t: self.tile_beam_merge_with_batch(t, self.beam_size),
            initial_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        flat = jax.tree_util.tree_leaves(
            cell_states, is_leaf=lambda t: isinstance(t, Tensor))
        batch_beam = flat[0].shape[0]
        b = batch_beam // self.beam_size
        # only beam 0 is live initially so duplicated beams don't tie
        log_probs = jnp.tile(
            jnp.array([0.0] + [-1e9] * (self.beam_size - 1), jnp.float32),
            (b, 1)).reshape(-1)
        finished = jnp.zeros((batch_beam,), bool)
        lengths = jnp.zeros((batch_beam,), jnp.int64)
        init_ids = Tensor._from_value(
            jnp.full((batch_beam,), self.start_token, jnp.int64))
        init_inputs = (self.embedding_fn(init_ids)
                       if self.embedding_fn is not None else init_ids)
        state = self.StateWrapper(cell_states,
                                  Tensor._from_value(log_probs),
                                  Tensor._from_value(finished),
                                  Tensor._from_value(lengths))
        return init_inputs, state, Tensor._from_value(finished)

    def step(self, time, inputs, states, **kwargs):
        import jax

        cell_out, next_cell_states = self.cell(inputs, states.cell_states,
                                               **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = ensure_tensor(cell_out)._value.astype(jnp.float32)
        vocab = logits.shape[-1]
        logp = jax.nn.log_softmax(logits, axis=-1)     # [B*beam, V]

        prev_lp = states.log_probs._value
        finished = states.finished._value
        lengths = states.lengths._value

        # finished beams only extend with end_token at zero cost
        end_mask = jnp.full((vocab,), -1e9).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[:, None], end_mask[None, :], logp)
        total = prev_lp[:, None] + step_lp                   # [B*beam, V]

        b = total.shape[0] // self.beam_size
        flat = self._split(total).reshape(b, self.beam_size * vocab)
        top_lp, top_idx = jax.lax.top_k(flat, self.beam_size)  # [B, beam]
        parent = top_idx // vocab                              # beam index
        token = (top_idx % vocab).astype(jnp.int64)

        # gather beam-aligned state rows through parent indices
        gather_rows = (jnp.arange(b)[:, None] * self.beam_size
                       + parent).reshape(-1)

        def regather(t):
            t = ensure_tensor(t)
            return Tensor._from_value(t._value[gather_rows])

        next_cell_states = jax.tree_util.tree_map(
            regather, next_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        new_finished = finished[gather_rows] | (
            token.reshape(-1) == self.end_token)
        new_lengths = lengths[gather_rows] + jnp.where(
            finished[gather_rows], 0, 1)

        out = self.OutputWrapper(
            Tensor._from_value(top_lp.reshape(-1)),
            Tensor._from_value(token.reshape(-1)),
            Tensor._from_value(parent.reshape(-1).astype(jnp.int64)),
        )
        next_state = self.StateWrapper(
            next_cell_states,
            Tensor._from_value(top_lp.reshape(-1)),
            Tensor._from_value(new_finished),
            Tensor._from_value(new_lengths),
        )
        ids = Tensor._from_value(token.reshape(-1))
        next_inputs = (self.embedding_fn(ids)
                       if self.embedding_fn is not None else ids)
        return out, next_state, next_inputs, Tensor._from_value(new_finished)

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrack parent pointers via the gather_tree op (reference
        finalize :630)."""
        from .functional.vision import gather_tree

        pred = np.asarray(outputs.predicted_ids._value)   # [T, B*beam]
        parents = np.asarray(outputs.parent_ids._value)
        T = pred.shape[0]
        b = pred.shape[1] // self.beam_size
        out = gather_tree(
            Tensor._from_value(jnp.asarray(
                pred.reshape(T, b, self.beam_size))),
            Tensor._from_value(jnp.asarray(
                parents.reshape(T, b, self.beam_size))),
        )
        # [T, B, beam] -> [B, T, beam] time-minor like the reference
        return Tensor._from_value(out._value.transpose(1, 0, 2)), final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Repeatedly decoder.step() until all beams finish or max_step_num
    (reference: decode.py:994)."""
    from ..ops.manipulation import stack

    inputs, states, finished = decoder.initialize(inits)
    step_outputs = []
    final_states = states
    t = 0
    while max_step_num is None or t < int(max_step_num):
        out, states, inputs, finished = decoder.step(t, inputs, states,
                                                     **kwargs)
        step_outputs.append(out)
        final_states = states
        t += 1
        if bool(np.asarray(ensure_tensor(finished)._value).all()):
            break
    if not step_outputs:
        raise ValueError("dynamic_decode ran zero steps (max_step_num=0?)")

    # stack the per-step namedtuples field-wise: [T, ...]
    first = step_outputs[0]
    if isinstance(first, tuple) and hasattr(first, "_fields"):
        outputs = type(first)(*[
            stack([getattr(o, f) for o in step_outputs], axis=0)
            for f in first._fields
        ])
    else:
        outputs = stack(step_outputs, axis=0)

    if hasattr(decoder, "finalize"):
        final_outputs, final_states = decoder.finalize(
            outputs, final_states, getattr(final_states, "lengths", None))
    else:
        final_outputs = outputs
    if output_time_major and isinstance(final_outputs, Tensor):
        from ..ops.manipulation import transpose

        perm = [1, 0] + list(range(2, final_outputs.ndim))
        final_outputs = transpose(final_outputs, perm)
    if return_length:
        return final_outputs, final_states, getattr(final_states, "lengths",
                                                    None)
    return final_outputs, final_states
