"""``paddle.linalg`` namespace — re-exports the linear-algebra op surface.

Reference: python/paddle/linalg.py (a pure re-export module over
paddle/tensor/linalg.py); here the implementations live in
``paddle_tpu.ops.linalg``.
"""
from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, eig, eigh, eigvals,
    eigvalsh, householder_product, inv, lstsq, lu, matrix_norm, matrix_power,
    matrix_rank, multi_dot, norm, pinv, qr, slogdet, solve, svd,
    triangular_solve, vector_norm,
)
from .ops.math import matmul  # noqa: F401

__all__ = [
    "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det", "eig",
    "eigh", "eigvals", "eigvalsh", "householder_product", "inv", "lstsq",
    "lu", "matmul", "matrix_norm", "matrix_power", "matrix_rank", "multi_dot",
    "norm", "pinv", "qr", "slogdet", "solve", "svd", "triangular_solve",
    "vector_norm",
]


# long-tail linalg ops live in ops.extras (single registration point);
# re-export them on the paddle.linalg namespace like the reference
from .ops.extras import (  # noqa: E402,F401
    cholesky_inverse, lu_unpack, matrix_exp, ormqr, pca_lowrank, svd_lowrank,
)

__all__ += [
    "cholesky_inverse", "lu_unpack", "matrix_exp", "ormqr", "pca_lowrank",
    "svd_lowrank", "fp8_fp8_half_gemm_fused",
]


def fp8_fp8_half_gemm_fused(x, y, bias=None, transpose_x=False,
                            transpose_y=False, scale=1.0,
                            output_dtype="float16", act="identity",
                            name=None):
    """fp8 x fp8 -> half GEMM (reference: tensor/linalg.py
    fp8_fp8_half_gemm_fused, cuBLASLt fp8 path). On TPU v5e the MXU has no
    fp8 mode; inputs are computed in bf16 with the same scale/act epilogue
    and cast to the requested half dtype."""
    import jax.numpy as jnp

    from .core.tensor import apply
    from .ops._helpers import ensure_tensor

    return apply("fp8_gemm_p", ensure_tensor(x), ensure_tensor(y),
                 ensure_tensor(bias) if bias is not None else ensure_tensor(0.0),
                 use_bias=bias is not None, tx=bool(transpose_x),
                 ty=bool(transpose_y), scale=float(scale),
                 out_dtype=str(output_dtype), act=str(act))


def _register_fp8_prim():
    import jax
    import jax.numpy as jnp

    from .ops._helpers import defprim

    def fwd(x, y, b, *, use_bias, tx, ty, scale, out_dtype, act):
        xb = x.astype(jnp.bfloat16)
        yb = y.astype(jnp.bfloat16)
        if tx:
            xb = jnp.swapaxes(xb, -1, -2)
        if ty:
            yb = jnp.swapaxes(yb, -1, -2)
        out = jnp.matmul(xb, yb,
                         preferred_element_type=jnp.float32) * scale
        if use_bias:
            out = out + b.astype(jnp.float32)
        if act == "gelu":
            out = jax.nn.gelu(out)
        elif act == "relu":
            out = jnp.maximum(out, 0)
        dt = jnp.bfloat16 if out_dtype == "bfloat16" else jnp.float16
        return out.astype(dt)

    defprim("fp8_gemm_p", fwd)


_register_fp8_prim()
