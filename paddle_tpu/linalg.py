"""``paddle.linalg`` namespace — re-exports the linear-algebra op surface.

Reference: python/paddle/linalg.py (a pure re-export module over
paddle/tensor/linalg.py); here the implementations live in
``paddle_tpu.ops.linalg``.
"""
from .ops.linalg import (  # noqa: F401
    cholesky, cholesky_solve, cond, corrcoef, cov, det, eig, eigh, eigvals,
    eigvalsh, householder_product, inv, lstsq, lu, matrix_norm, matrix_power,
    matrix_rank, multi_dot, norm, pinv, qr, slogdet, solve, svd,
    triangular_solve, vector_norm,
)
from .ops.math import matmul  # noqa: F401

__all__ = [
    "cholesky", "cholesky_solve", "cond", "corrcoef", "cov", "det", "eig",
    "eigh", "eigvals", "eigvalsh", "householder_product", "inv", "lstsq",
    "lu", "matmul", "matrix_norm", "matrix_power", "matrix_rank", "multi_dot",
    "norm", "pinv", "qr", "slogdet", "solve", "svd", "triangular_solve",
    "vector_norm",
]
