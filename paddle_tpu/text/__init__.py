"""``paddle.text`` parity package (reference: python/paddle/text/__init__.py)."""
from .datasets import (
    Conll05st,
    Imdb,
    Imikolov,
    Movielens,
    UCIHousing,
    WMT14,
    WMT16,
)
from .viterbi_decode import ViterbiDecoder, viterbi_decode

__all__ = [
    "Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing", "WMT14",
    "WMT16", "ViterbiDecoder", "viterbi_decode",
]
