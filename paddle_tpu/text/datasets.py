"""Text datasets (reference: python/paddle/text/datasets/ — uci_housing.py:51,
imdb.py:39, imikolov.py, conll05.py, movielens.py, wmt14.py, wmt16.py).

This build runs with zero network egress, so ``download=True`` raises a
clear error; every dataset accepts ``data_file`` pointing at a local copy in
the reference's archive format and parses it the same way."""
from __future__ import annotations

import collections
import os
import re
import tarfile

import numpy as np

from ..io.dataloader import Dataset

__all__ = ["UCIHousing", "Imdb", "Imikolov", "Conll05st", "Movielens",
           "WMT14", "WMT16"]


def _require_file(data_file, download, name):
    if data_file is not None and os.path.exists(data_file):
        return data_file
    if download:
        raise RuntimeError(
            f"{name}: automatic download is unavailable in this environment "
            f"(no network egress). Pass data_file= pointing at a local copy."
        )
    raise ValueError(
        f"{name}: data_file must be set to an existing local file when "
        f"download is False; got {data_file!r}"
    )


class UCIHousing(Dataset):
    """Boston housing regression (reference uci_housing.py:51): whitespace
    table of 14 columns; 80/20 train/test split, features normalized by
    train-split min/max/avg."""

    def __init__(self, data_file=None, mode="train", download=True):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', but got {mode}")
        self.mode = mode.lower()
        self.data_file = _require_file(data_file, download, "UCIHousing")
        self._load_data()

    def _load_data(self, feature_num=14, ratio=0.8):
        data = np.loadtxt(self.data_file).astype("float32")
        data = data.reshape(-1, feature_num)
        maxs, mins, avgs = (
            data.max(axis=0), data.min(axis=0), data.sum(axis=0) / data.shape[0]
        )
        for i in range(feature_num - 1):
            data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
        offset = int(data.shape[0] * ratio)
        self.data = data[:offset] if self.mode == "train" else data[offset:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """IMDB sentiment (reference imdb.py:39): aclImdb tar with
    train|test/pos|neg/*.txt; builds a frequency-cutoff word dict."""

    def __init__(self, data_file=None, mode="train", cutoff=150, download=True):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', but got {mode}")
        self.mode = mode.lower()
        self.data_file = _require_file(data_file, download, "Imdb")
        # one decompression pass: bucket documents by (split, polarity),
        # then build the vocab and annotation lists from the buckets
        buckets = self._scan_archive()
        self.word_idx = self._build_work_dict(buckets, cutoff)
        self._load_anno(buckets)

    def _scan_archive(self):
        pattern = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        buckets = {}
        with tarfile.open(self.data_file) as tf:
            for member in tf:
                m = pattern.match(member.name)
                if m:
                    text = tf.extractfile(member).read().decode("latin-1")
                    buckets.setdefault(m.groups(), []).append(text.lower().split())
        return buckets

    def _build_work_dict(self, buckets, cutoff):
        word_freq = collections.Counter()
        for docs in buckets.values():
            for doc in docs:
                word_freq.update(doc)
        word_freq = {k: v for k, v in word_freq.items() if v > cutoff}
        dictionary = sorted(word_freq.items(), key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(dictionary)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self, buckets):
        unk = self.word_idx["<unk>"]
        self.docs, self.labels = [], []
        for label, polarity in ((0, "pos"), (1, "neg")):
            for doc in buckets.get((self.mode, polarity), []):
                self.docs.append([self.word_idx.get(w, unk) for w in doc])
                self.labels.append(label)

    def __getitem__(self, idx):
        return np.asarray(self.docs[idx]), np.asarray([self.labels[idx]])

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB n-gram dataset (reference imikolov.py): simple-examples tar,
    data/ptb.{train,valid}.txt; data_type NGRAM (windows of size N) or SEQ."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=-1,
                 mode="train", min_word_freq=50, download=True):
        if mode.lower() not in ("train", "test"):
            raise ValueError(f"mode should be 'train' or 'test', but got {mode}")
        if data_type.upper() not in ("NGRAM", "SEQ"):
            raise ValueError(f"data_type should be 'NGRAM' or 'SEQ', got {data_type}")
        self.mode = mode.lower()
        self.data_type = data_type.upper()
        self.window_size = window_size
        self.min_word_freq = min_word_freq
        self.data_file = _require_file(data_file, download, "Imikolov")
        self.word_idx = self._build_dict()
        self._load_anno()

    def _read(self, suffix):
        with tarfile.open(self.data_file) as tf:
            for member in tf.getmembers():
                if member.name.endswith(suffix):
                    content = tf.extractfile(member).read().decode()
                    return [l.strip().split() for l in content.splitlines()]
        raise ValueError(f"no member ending with {suffix} in {self.data_file}")

    def _build_dict(self):
        freq = collections.Counter()
        for line in self._read("ptb.train.txt"):
            freq.update(line)
            freq["<s>"] += 1
            freq["<e>"] += 1
        freq = {k: v for k, v in freq.items() if v >= self.min_word_freq}
        freq.pop("<unk>", None)
        items = sorted(freq.items(), key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(items)}
        word_idx["<unk>"] = len(word_idx)
        return word_idx

    def _load_anno(self):
        suffix = "ptb.train.txt" if self.mode == "train" else "ptb.valid.txt"
        unk = self.word_idx["<unk>"]
        self.data = []
        for line in self._read(suffix):
            if self.data_type == "NGRAM":
                if self.window_size <= 0:
                    raise ValueError("window_size must be positive for NGRAM")
                ids = [self.word_idx.get(w, unk) for w in ["<s>"] + line + ["<e>"]]
                for i in range(self.window_size - 1, len(ids)):
                    self.data.append(tuple(ids[i - self.window_size + 1 : i + 1]))
            else:
                ids = [self.word_idx.get(w, unk) for w in line]
                src = [self.word_idx["<s>"]] + ids
                trg = ids + [self.word_idx["<e>"]]
                self.data.append((src, trg))

    def __getitem__(self, idx):
        return tuple(np.asarray(x) for x in self.data[idx])

    def __len__(self):
        return len(self.data)


class _LocalOnlyDataset(Dataset):
    """Shared shell for corpora whose archives must be supplied locally."""

    _NAME = "dataset"

    def __init__(self, data_file=None, mode="train", download=True, **kwargs):
        self.mode = mode
        self.data_file = _require_file(data_file, download, self._NAME)
        self.data = self._parse(**kwargs)

    def _parse(self, **kwargs):
        raise NotImplementedError

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Conll05st(_LocalOnlyDataset):
    """CoNLL-2005 SRL (reference conll05.py). Parses the conll05st test
    archive's wordpos/targets propositions into (sentence, predicate, labels)
    tuples of raw strings."""

    _NAME = "Conll05st"

    def _parse(self):
        sents = []
        with tarfile.open(self.data_file) as tf:
            words_member = next(
                (m for m in tf.getmembers() if m.name.endswith("words.txt")), None
            )
            props_member = next(
                (m for m in tf.getmembers() if m.name.endswith("props.txt")), None
            )
            if words_member is None or props_member is None:
                raise ValueError("archive must contain words.txt and props.txt")
            words = tf.extractfile(words_member).read().decode().splitlines()
            props = tf.extractfile(props_member).read().decode().splitlines()
        sent, lab = [], []
        for w, p in zip(words, props):
            if not w.strip():
                if sent:
                    sents.append((sent, lab))
                sent, lab = [], []
            else:
                sent.append(w.strip())
                lab.append(p.strip())
        if sent:
            sents.append((sent, lab))
        return sents


class Movielens(_LocalOnlyDataset):
    """MovieLens-1M ratings (reference movielens.py): ml-1m zip/tar with
    ratings.dat 'user::movie::rating::ts' lines."""

    _NAME = "Movielens"

    def _parse(self):
        rows = []
        opener = tarfile.open if tarfile.is_tarfile(self.data_file) else None
        if opener is None:
            import zipfile

            with zipfile.ZipFile(self.data_file) as zf:
                name = next(n for n in zf.namelist() if n.endswith("ratings.dat"))
                content = zf.read(name).decode("latin-1")
        else:
            with tarfile.open(self.data_file) as tf:
                member = next(
                    m for m in tf.getmembers() if m.name.endswith("ratings.dat")
                )
                content = tf.extractfile(member).read().decode("latin-1")
        for line in content.splitlines():
            parts = line.strip().split("::")
            if len(parts) == 4:
                u, m, r, _ = parts
                rows.append(
                    (np.asarray(int(u)), np.asarray(int(m)), np.asarray(float(r)))
                )
        return rows


class _ParallelCorpus(_LocalOnlyDataset):
    """Shared parser for WMT14/WMT16-style parallel corpora: tar containing
    ``*.src``/``*.trg`` (or train/test .en/.de) line-aligned files."""

    _SRC_SUFFIXES = (".src", ".en")
    _TRG_SUFFIXES = (".trg", ".de")

    def _parse(self):
        with tarfile.open(self.data_file) as tf:
            members = tf.getmembers()

            def find(suffixes):
                for m in members:
                    if self.mode in m.name and m.name.endswith(suffixes):
                        return tf.extractfile(m).read().decode().splitlines()
                for m in members:
                    if m.name.endswith(suffixes):
                        return tf.extractfile(m).read().decode().splitlines()
                raise ValueError(f"no member with suffix {suffixes}")

            src = find(self._SRC_SUFFIXES)
            trg = find(self._TRG_SUFFIXES)
        return list(zip(
            [l.strip().split() for l in src], [l.strip().split() for l in trg]
        ))


class WMT14(_ParallelCorpus):
    _NAME = "WMT14"


class WMT16(_ParallelCorpus):
    _NAME = "WMT16"
