"""Viterbi decoding (reference: python/paddle/text/viterbi_decode.py:31,110).

CRF max-sum decoding as one primitive: a lax.scan forward pass recording
argmax back-pointers, then a reversed scan to recover the best path —
compiles to a single XLA while-loop program on TPU."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply
from ..nn.layer import Layer
from ..ops._helpers import defprim, ensure_tensor

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi_fwd(potentials, transitions, lengths, *, include_bos_eos_tag):
    b, t_max, k = potentials.shape
    lengths = lengths.astype(jnp.int64)
    if include_bos_eos_tag:
        start_idx, stop_idx = k - 1, k - 2
        alpha = potentials[:, 0] + transitions[start_idx][None, :]
    else:
        alpha = potentials[:, 0]

    pot_tm = jnp.moveaxis(potentials, 1, 0)  # (T, B, K)

    def step(alpha, inp):
        t, pot_t = inp
        scores = alpha[:, :, None] + transitions[None, :, :]  # (B, Kprev, Knext)
        best_prev = jnp.argmax(scores, axis=1)                # (B, K)
        new_alpha = jnp.max(scores, axis=1) + pot_t
        mask = (t < lengths)[:, None]
        alpha = jnp.where(mask, new_alpha, alpha)
        return alpha, best_prev

    ts = jnp.arange(1, t_max)
    alpha, history = jax.lax.scan(step, alpha, (ts, pot_tm[1:]))
    # history: (T-1, B, K) back-pointers for transitions into step t

    if include_bos_eos_tag:
        final = alpha + transitions[:, stop_idx][None, :]
    else:
        final = alpha
    scores = jnp.max(final, axis=-1)
    last_tag = jnp.argmax(final, axis=-1)                     # (B,)

    def back(tag, inp):
        t, hist_t = inp                                       # t in [T-1, ..., 1]
        emit = jnp.where(t < lengths, tag, 0)                 # path[t]
        prev = jnp.take_along_axis(hist_t, tag[:, None], axis=-1)[:, 0]
        tag = jnp.where(t <= lengths - 1, prev, tag)
        return tag, emit

    tag, emits = jax.lax.scan(
        back, last_tag, (jnp.arange(1, t_max)[::-1], history[::-1])
    )
    # emits[i] = path at position T-1-i; first position = final tag state
    path = jnp.concatenate([tag[:, None], emits[::-1].T], axis=1)  # (B, T)
    path = jnp.where(jnp.arange(t_max)[None, :] < lengths[:, None], path, 0)
    return scores, path.astype(jnp.int64)


defprim("viterbi_decode_p", _viterbi_fwd, multi_out=True, nondiff=True)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    potentials = ensure_tensor(potentials)
    transition_params = ensure_tensor(transition_params)
    lengths = ensure_tensor(lengths)
    if potentials.ndim != 3:
        raise ValueError("potentials should be [batch, seq_len, num_tags]")
    if transition_params.ndim != 2:
        raise ValueError("transition_params should be [num_tags, num_tags]")
    return apply(
        "viterbi_decode_p", potentials, transition_params, lengths,
        include_bos_eos_tag=bool(include_bos_eos_tag),
    )


class ViterbiDecoder(Layer):
    """Layer form of viterbi_decode (reference viterbi_decode.py:110)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = ensure_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(
            potentials, self.transitions, lengths, self.include_bos_eos_tag
        )
