"""Audio functional ops (reference:
python/paddle/audio/functional/functional.py:24-340).

Filterbank/DCT construction is host-side table building (numpy); the tables
feed device matmuls in the feature layers."""
from __future__ import annotations

import math

import numpy as np

from ..core.tensor import Tensor
from ..ops._helpers import ensure_tensor

__all__ = [
    "hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
    "compute_fbank_matrix", "power_to_db", "create_dct",
]


def _hz_to_mel_np(freq, htk):
    freq = np.asarray(freq, dtype="float64")
    if htk:
        return 2595.0 * np.log10(1.0 + freq / 700.0)
    f_sp = 200.0 / 3
    mels = freq / f_sp
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(
        freq >= min_log_hz,
        min_log_mel + np.log(np.maximum(freq, 1e-10) / min_log_hz) / logstep,
        mels,
    )


def _mel_to_hz_np(mel, htk):
    mel = np.asarray(mel, dtype="float64")
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_sp = 200.0 / 3
    freqs = f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = min_log_hz / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(
        mel >= min_log_mel,
        min_log_hz * np.exp(logstep * (mel - min_log_mel)),
        freqs,
    )


def hz_to_mel(freq, htk=False):
    """Convert Hz to Mels (reference functional.py:24). Accepts float or
    Tensor; returns the same kind."""
    if isinstance(freq, Tensor):
        out = _hz_to_mel_np(np.asarray(freq._value), htk)
        return Tensor._from_value(out.astype(np.asarray(freq._value).dtype))
    return float(_hz_to_mel_np(freq, htk))


def mel_to_hz(mel, htk=False):
    if isinstance(mel, Tensor):
        out = _mel_to_hz_np(np.asarray(mel._value), htk)
        return Tensor._from_value(out.astype(np.asarray(mel._value).dtype))
    return float(_mel_to_hz_np(mel, htk))


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False, dtype="float32"):
    min_mel = _hz_to_mel_np(f_min, htk)
    max_mel = _hz_to_mel_np(f_max, htk)
    mels = np.linspace(min_mel, max_mel, n_mels)
    return Tensor._from_value(_mel_to_hz_np(mels, htk).astype(np.dtype(dtype)))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor._from_value(
        np.linspace(0, float(sr) / 2, 1 + n_fft // 2).astype(np.dtype(dtype))
    )


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None, htk=False,
                         norm="slaney", dtype="float32"):
    """Mel filterbank matrix of shape (n_mels, 1 + n_fft//2)
    (reference functional.py:188)."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = np.linspace(0, float(sr) / 2, 1 + n_fft // 2)
    mel_f = np.asarray(
        mel_frequencies(n_mels + 2, f_min, f_max, htk, "float64")._value
    )
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0.0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2 : n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    elif norm is not None and norm != 1:
        weights = weights / np.linalg.norm(weights, ord=norm, axis=-1, keepdims=True)
    return Tensor._from_value(weights.astype(np.dtype(dtype)))


def _power_to_db_fwd(m, *, ref_value, amin, top_db):
    import jax.numpy as jnp

    db = 10.0 * jnp.log10(jnp.maximum(m, amin)) - 10.0 * jnp.log10(
        jnp.maximum(amin, ref_value)
    )
    if top_db is not None:
        db = jnp.maximum(db, jnp.max(db) - top_db)
    return db


from ..ops._helpers import defprim as _defprim  # noqa: E402
from ..core.tensor import apply as _apply  # noqa: E402

_defprim("power_to_db_p", _power_to_db_fwd)


def power_to_db(magnitude, ref_value=1.0, amin=1e-10, top_db=None, name=None):
    """Power spectrogram → decibels (reference functional.py:261)."""
    if amin <= 0:
        raise ValueError("amin must be strictly positive")
    if ref_value <= 0:
        raise ValueError("ref_value must be strictly positive")
    if top_db is not None and top_db < 0:
        raise ValueError("top_db must be non-negative")
    x = ensure_tensor(magnitude)
    return _apply("power_to_db_p", x, ref_value=float(ref_value), amin=float(amin),
                  top_db=None if top_db is None else float(top_db))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II transform matrix of shape (n_mels, n_mfcc)
    (reference functional.py:305)."""
    n = np.arange(float(n_mels))
    k = np.arange(float(n_mfcc))[None, :]
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k) * 2.0
    if norm is None:
        dct *= 0.5
    elif norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2.0)
        dct *= math.sqrt(1.0 / (2.0 * n_mels))
    else:
        raise ValueError(f"Unsupported norm: {norm}")
    return Tensor._from_value(dct.astype(np.dtype(dtype)))
