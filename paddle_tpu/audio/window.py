"""Window functions (reference: python/paddle/audio/functional/window.py:335
get_window). The reference hand-builds each window in paddle ops; windows
are tiny host-side tables, so scipy.signal.windows supplies the numerics
and the result lands in a framework Tensor."""
from __future__ import annotations

import numpy as np
import scipy.signal.windows as _sw

from ..core.tensor import Tensor

__all__ = ["get_window"]

_WINDOWS = {
    "hamming": _sw.hamming,
    "hann": _sw.hann,
    "tukey": _sw.tukey,
    "kaiser": _sw.kaiser,
    "gaussian": _sw.gaussian,
    "exponential": _sw.exponential,
    "triang": _sw.triang,
    "bohman": _sw.bohman,
    "blackman": _sw.blackman,
    "cosine": _sw.cosine,
    "taylor": _sw.taylor,
    "bartlett": _sw.bartlett,
    "nuttall": _sw.nuttall,
    "general_gaussian": _sw.general_gaussian,
    "general_cosine": _sw.general_cosine,
    "general_hamming": _sw.general_hamming,
}


def get_window(window, win_length, fftbins=True, dtype="float64"):
    """Return a window of ``win_length`` samples. ``window`` is a name or a
    (name, *params) tuple; ``fftbins=True`` returns a periodic window for
    spectral analysis (reference window.py:335)."""
    sym = not fftbins
    if isinstance(window, (str,)):
        name, args = window, ()
    elif isinstance(window, tuple):
        if len(window) == 0:
            raise ValueError("window tuple must have at least one element")
        name, args = window[0], tuple(window[1:])
    elif isinstance(window, (int, float)):
        # scipy convention: a float means a kaiser beta
        name, args = "kaiser", (float(window),)
    else:
        raise ValueError(f"The window type {type(window)} is not supported")
    if name not in _WINDOWS:
        raise ValueError(f"Unknown window type: {name}")
    if name == "kaiser" and not args:
        raise ValueError("The 'kaiser' window needs a beta parameter")
    if name == "gaussian" and not args:
        raise ValueError("The 'gaussian' window needs a std parameter")
    w = _WINDOWS[name](int(win_length), *args, sym=sym)
    return Tensor._from_value(np.asarray(w, dtype=np.dtype(dtype)))
