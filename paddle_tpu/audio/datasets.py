"""paddle.audio.datasets parity.

Reference: python/paddle/audio/datasets/ — TESS and ESC50 audio
classification datasets (wav archives + metadata). Zero-egress build:
archives must be pre-placed under the dataset cache; ``synthetic=True``
generates deterministic waveforms so feature/training pipelines run in CI.
"""
from __future__ import annotations

import os

import numpy as np

from ..io.dataloader import Dataset

__all__ = ["TESS", "ESC50"]


class _SyntheticAudioDataset(Dataset):
    N_CLASSES = 2
    SAMPLE_RATE = 16000

    def __init__(self, mode="train", feat_type="raw", archive=None,
                 synthetic=True, n_synthetic=64, **feat_kwargs):
        if not synthetic:
            raise RuntimeError(
                f"{type(self).__name__}: audio archives are unavailable in "
                "this environment; place the files locally or use "
                "synthetic=True")
        from ..dataset.common import _synthetic_rng

        rng = _synthetic_rng(f"{type(self).__name__}-{mode}")
        self.mode = mode
        self.feat_type = feat_type
        self._feat_kwargs = feat_kwargs
        n = n_synthetic if mode == "train" else max(8, n_synthetic // 4)
        dur = self.SAMPLE_RATE  # 1s clips
        freqs = rng.uniform(100, 2000, size=n)
        self.labels = rng.integers(0, self.N_CLASSES, size=n)
        t = np.arange(dur, dtype=np.float32) / self.SAMPLE_RATE
        self.waveforms = np.stack([
            np.sin(2 * np.pi * f * t).astype("float32") for f in freqs
        ])

    def _features(self, wav):
        if self.feat_type == "raw":
            return wav
        from . import features as F
        import paddle_tpu as paddle

        x = paddle.to_tensor(wav[None, :])
        if self.feat_type == "mfcc":
            return F.MFCC(sr=self.SAMPLE_RATE,
                          **self._feat_kwargs)(x).numpy()[0]
        if self.feat_type == "spectrogram":
            return F.Spectrogram(**self._feat_kwargs)(x).numpy()[0]
        if self.feat_type == "melspectrogram":
            return F.MelSpectrogram(sr=self.SAMPLE_RATE,
                                    **self._feat_kwargs)(x).numpy()[0]
        raise ValueError(f"unknown feat_type {self.feat_type!r}")

    def __getitem__(self, idx):
        return self._features(self.waveforms[idx]), int(self.labels[idx])

    def __len__(self):
        return len(self.waveforms)


class TESS(_SyntheticAudioDataset):
    """Toronto emotional speech set (reference: audio/datasets/tess.py)."""

    N_CLASSES = 7


class ESC50(_SyntheticAudioDataset):
    """ESC-50 environmental sounds (reference: audio/datasets/esc50.py)."""

    N_CLASSES = 50
