"""Audio IO backends (reference: python/paddle/audio/backends/ —
wave_backend.py load/save/info, init_backend.py backend registry).

The built-in backend reads/writes 16-bit PCM WAV via the stdlib ``wave``
module — no third-party soundfile dependency."""
from __future__ import annotations

import wave as _wave

import numpy as np

from ..core.tensor import Tensor

__all__ = [
    "load", "save", "info", "list_available_backends", "get_current_backend",
    "set_backend",
]

_BACKENDS = ["wave_backend"]
_current = "wave_backend"


def list_available_backends():
    return list(_BACKENDS)


def get_current_backend():
    return _current


def set_backend(backend_name):
    global _current
    if backend_name not in _BACKENDS:
        raise NotImplementedError(
            f"backend {backend_name!r} is not available; choices: {_BACKENDS}"
        )
    _current = backend_name


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels, bits_per_sample,
                 encoding):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath):
    with _wave.open(str(filepath), "rb") as f:
        return AudioInfo(
            sample_rate=f.getframerate(),
            num_samples=f.getnframes(),
            num_channels=f.getnchannels(),
            bits_per_sample=f.getsampwidth() * 8,
            encoding="PCM_S",
        )


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Load a WAV file → (waveform Tensor [C, T] or [T, C], sample_rate)."""
    with _wave.open(str(filepath), "rb") as f:
        sr = f.getframerate()
        channels = f.getnchannels()
        width = f.getsampwidth()
        f.setpos(int(frame_offset))
        n = f.getnframes() - int(frame_offset) if num_frames < 0 else int(num_frames)
        raw = f.readframes(n)
    if width == 2:
        data = np.frombuffer(raw, dtype="<i2").astype("float32")
        scale = 32768.0
    elif width == 1:
        data = (np.frombuffer(raw, dtype="u1").astype("float32") - 128.0)
        scale = 128.0
    elif width == 4:
        data = np.frombuffer(raw, dtype="<i4").astype("float32")
        scale = 2147483648.0
    else:
        raise ValueError(f"Unsupported sample width: {width}")
    if normalize:
        data = data / scale
    data = data.reshape(-1, channels)
    wav = data.T if channels_first else data
    return Tensor._from_value(wav.copy()), sr


def save(filepath, src, sample_rate, channels_first=True, encoding="PCM_16",
         bits_per_sample=16):
    """Save a [C, T] (or [T, C]) waveform Tensor as 16-bit PCM WAV."""
    data = np.asarray(src._value if isinstance(src, Tensor) else src)
    if data.ndim == 1:
        # mono: orient per the declared layout so (T,) never becomes T channels
        data = data[None, :] if channels_first else data[:, None]
    if channels_first:
        data = data.T                      # (T, C)
    if bits_per_sample != 16:
        raise ValueError("wave backend only supports 16 bits_per_sample")
    pcm = np.clip(data, -1.0, 1.0 - 1.0 / 32768.0)
    pcm = (pcm * 32768.0).astype("<i2")
    with _wave.open(str(filepath), "wb") as f:
        f.setnchannels(data.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())
