"""``paddle.audio`` parity package (reference: python/paddle/audio/__init__.py)."""
from . import functional
from . import features
from . import backends
from .backends import load, save, info
from .window import get_window

# the reference exposes get_window under audio.functional as well
functional.get_window = get_window

__all__ = ["functional", "features", "backends", "load", "save", "info",
           "get_window"]
from . import datasets  # noqa: F401
