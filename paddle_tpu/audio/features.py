"""Audio feature layers (reference: python/paddle/audio/features/layers.py:
Spectrogram :24, MelSpectrogram :106, LogMelSpectrogram :206, MFCC :309).

Each layer precomputes its window / filterbank / DCT tables once at
construction and runs stft → |·|^p → fbank matmul → dB → DCT as one
differentiable device pipeline."""
from __future__ import annotations

import numpy as np

from .. import signal as _signal
from ..core.tensor import Tensor
from ..nn.layer import Layer
from ..ops._helpers import ensure_tensor
from . import functional as F
from .window import get_window

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None, window="hann",
                 power=2.0, center=True, pad_mode="reflect", dtype="float32"):
        super().__init__()
        if power <= 0:
            raise ValueError("power must be positive")
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = float(power)
        self.center = center
        self.pad_mode = pad_mode
        w = get_window(window, self.win_length, fftbins=True, dtype=dtype)
        self.register_buffer("fft_window", w)

    def forward(self, x):
        x = ensure_tensor(x)
        spec = _signal.stft(
            x, self.n_fft, hop_length=self.hop_length, win_length=self.win_length,
            window=self.fft_window, center=self.center, pad_mode=self.pad_mode,
        )
        return spec.abs() ** self.power


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(
            n_fft, hop_length, win_length, window, power, center, pad_mode, dtype
        )
        self.n_mels = n_mels
        fbank = F.compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype,
        )
        self.register_buffer("fbank_matrix", fbank)

    def forward(self, x):
        from ..ops.math import matmul

        spec = self._spectrogram(x)            # (..., n_fft//2+1, frames)
        return matmul(self.fbank_matrix, spec)  # (..., n_mels, frames)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center, pad_mode,
            n_mels, f_min, f_max, htk, norm, dtype,
        )
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return F.power_to_db(mel, ref_value=self.ref_value, amin=self.amin,
                             top_db=self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None, htk=False,
                 norm="slaney", ref_value=1.0, amin=1e-10, top_db=None,
                 dtype="float32"):
        super().__init__()
        if n_mfcc > n_mels:
            raise ValueError("n_mfcc cannot be larger than n_mels")
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center, pad_mode,
            n_mels, f_min, f_max, htk, norm, ref_value, amin, top_db, dtype,
        )
        self.register_buffer("dct_matrix", F.create_dct(n_mfcc, n_mels, dtype=dtype))

    def forward(self, x):
        from ..ops.math import matmul
        from ..ops.manipulation import swapaxes

        log_mel = self._log_melspectrogram(x)   # (..., n_mels, frames)
        # DCT over the mel axis: (..., frames, n_mels) @ (n_mels, n_mfcc)
        out = matmul(swapaxes(log_mel, -1, -2), self.dct_matrix)
        return swapaxes(out, -1, -2)            # (..., n_mfcc, frames)
