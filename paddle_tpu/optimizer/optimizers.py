"""Concrete optimizers: SGD, Momentum, Adam, AdamW, RMSProp, Adagrad, Adadelta,
Adamax, Lamb.

Reference: python/paddle/optimizer/{sgd,momentum,adam,adamw,rmsprop,...}.py →
phi optimizer kernels (sgd_kernel, adam_kernel, adamw_kernel with
multi_precision master weights). Updates are pure jax fns, jitted per shape.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Parameter
from .optimizer import Optimizer


@functools.lru_cache(maxsize=None)
def _jit(fn):
    return jax.jit(fn)


# ---------------------------------------------------------------------------
@jax.jit
def _sgd_update(p, g, lr):
    return p - lr * g.astype(p.dtype)


class SGD(Optimizer):
    _accum_names = ()
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)

    def _update_param(self, p, grad, lr):
        master = self._master(p)
        if master is not None:
            new_master = _sgd_update(master, grad.astype(jnp.float32), jnp.asarray(lr, jnp.float32))
            self._apply(p, None, new_master)
        else:
            self._apply(p, _sgd_update(p._value, grad, jnp.asarray(lr, p._value.dtype)))


@jax.jit
def _momentum_update(p, g, vel, lr, mu, use_nesterov):
    g = g.astype(vel.dtype)
    vel_new = mu * vel + g
    upd = jnp.where(use_nesterov, g + mu * vel_new, vel_new)
    return (p.astype(vel.dtype) - lr * upd).astype(p.dtype), vel_new


class Momentum(Optimizer):
    _accum_names = ("velocity",)
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _update_param(self, p, grad, lr):
        vel = self._accum("velocity", p)
        master = self._master(p)
        base = master if master is not None else p._value
        new_p, new_vel = _momentum_update(
            base, grad, vel, jnp.asarray(lr, jnp.float32), jnp.float32(self._momentum),
            jnp.bool_(self._use_nesterov),
        )
        self._set_accum("velocity", p, new_vel)
        if master is not None:
            self._apply(p, None, new_p.astype(jnp.float32))
        else:
            self._apply(p, new_p)


@jax.jit
def _adam_update(p32, g, m, v, lr, beta1, beta2, eps, t):
    g32 = g.astype(jnp.float32)
    m_new = beta1 * m + (1 - beta1) * g32
    v_new = beta2 * v + (1 - beta2) * g32 * g32
    mhat = m_new / (1 - jnp.power(beta1, t))
    vhat = v_new / (1 - jnp.power(beta2, t))
    p_new = p32 - lr * mhat / (jnp.sqrt(vhat) + eps)
    return p_new, m_new, v_new


class Adam(Optimizer):
    _accum_names = ("moment1", "moment2")
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _update_param(self, p, grad, lr):
        m = self._accum("moment1", p)
        v = self._accum("moment2", p)
        master = self._master(p)
        p32 = master if master is not None else p._value.astype(jnp.float32)
        t = self._step_num()
        p_new, m_new, v_new = _adam_update(
            p32, grad, m, v, jnp.asarray(lr, jnp.float32), jnp.float32(self._beta1),
            jnp.float32(self._beta2), jnp.float32(self._epsilon), t,
        )
        self._set_accum("moment1", p, m_new)
        self._set_accum("moment2", p, v_new)
        if master is not None:
            self._apply(p, None, p_new)
        else:
            self._apply(p, p_new.astype(p._value.dtype))


@jax.jit
def _adamw_update(p32, g, m, v, lr, beta1, beta2, eps, t, wd):
    g32 = g.astype(jnp.float32)
    # decoupled weight decay (adamw.py:493 semantics: p *= (1 - lr*coeff))
    p32 = p32 * (1.0 - lr * wd)
    m_new = beta1 * m + (1 - beta1) * g32
    v_new = beta2 * v + (1 - beta2) * g32 * g32
    mhat = m_new / (1 - jnp.power(beta1, t))
    vhat = v_new / (1 - jnp.power(beta2, t))
    p_new = p32 - lr * mhat / (jnp.sqrt(vhat) + eps)
    return p_new, m_new, v_new


class AdamW(Optimizer):
    _accum_names = ("moment1", "moment2")

    """Decoupled weight decay Adam (reference: optimizer/adamw.py — decay
    applied directly to params, excluded via apply_decay_param_fun)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._weight_decay = float(weight_decay)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _update_param(self, p, grad, lr):
        m = self._accum("moment1", p)
        v = self._accum("moment2", p)
        master = self._master(p)
        p32 = master if master is not None else p._value.astype(jnp.float32)
        wd = self._weight_decay
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(
            p.name
        ):
            wd = 0.0
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        t = self._step_num()
        p_new, m_new, v_new = _adamw_update(
            p32, grad, m, v, jnp.asarray(lr, jnp.float32), jnp.float32(self._beta1),
            jnp.float32(self._beta2), jnp.float32(self._epsilon), t,
            jnp.float32(wd),
        )
        self._set_accum("moment1", p, m_new)
        self._set_accum("moment2", p, v_new)
        if master is not None:
            self._apply(p, None, p_new)
        else:
            self._apply(p, p_new.astype(p._value.dtype))


@jax.jit
def _rmsprop_update(p32, g, mean_sq, mom, lr, rho, eps, momentum, centered, mean_g):
    g32 = g.astype(jnp.float32)
    ms_new = rho * mean_sq + (1 - rho) * g32 * g32
    mg_new = jnp.where(centered, rho * mean_g + (1 - rho) * g32, mean_g)
    denom = jnp.sqrt(ms_new - jnp.where(centered, mg_new * mg_new, 0.0) + eps)
    mom_new = momentum * mom + lr * g32 / denom
    return p32 - mom_new, ms_new, mom_new, mg_new


class RMSProp(Optimizer):
    _accum_names = ("mean_square", "momentum", "mean_grad")
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update_param(self, p, grad, lr):
        ms = self._accum("mean_square", p)
        mom = self._accum("momentum", p)
        mg = self._accum("mean_grad", p)
        master = self._master(p)
        p32 = master if master is not None else p._value.astype(jnp.float32)
        p_new, ms_new, mom_new, mg_new = _rmsprop_update(
            p32, grad, ms, mom, jnp.asarray(lr, jnp.float32), jnp.float32(self._rho),
            jnp.float32(self._epsilon), jnp.float32(self._momentum),
            jnp.bool_(self._centered), mg,
        )
        self._set_accum("mean_square", p, ms_new)
        self._set_accum("momentum", p, mom_new)
        self._set_accum("mean_grad", p, mg_new)
        if master is not None:
            self._apply(p, None, p_new)
        else:
            self._apply(p, p_new.astype(p._value.dtype))


@jax.jit
def _adagrad_update(p32, g, moment, lr, eps):
    g32 = g.astype(jnp.float32)
    m_new = moment + g32 * g32
    return p32 - lr * g32 / (jnp.sqrt(m_new) + eps), m_new


class Adagrad(Optimizer):
    _accum_names = ("moment",)
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, grad, lr):
        m = self._accum(
            "moment", p,
            init=jnp.full(p._value.shape, self._init_acc, jnp.float32),
        )
        master = self._master(p)
        p32 = master if master is not None else p._value.astype(jnp.float32)
        p_new, m_new = _adagrad_update(
            p32, grad, m, jnp.asarray(lr, jnp.float32), jnp.float32(self._epsilon)
        )
        self._set_accum("moment", p, m_new)
        if master is not None:
            self._apply(p, None, p_new)
        else:
            self._apply(p, p_new.astype(p._value.dtype))


@jax.jit
def _adadelta_update(p32, g, avg_sq_g, avg_sq_u, lr, rho, eps):
    g32 = g.astype(jnp.float32)
    avg_sq_g_new = rho * avg_sq_g + (1 - rho) * g32 * g32
    upd = jnp.sqrt(avg_sq_u + eps) / jnp.sqrt(avg_sq_g_new + eps) * g32
    avg_sq_u_new = rho * avg_sq_u + (1 - rho) * upd * upd
    return p32 - lr * upd, avg_sq_g_new, avg_sq_u_new


class Adadelta(Optimizer):
    _accum_names = ("avg_squared_grad", "avg_squared_update")
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon = epsilon
        self._rho = rho

    def _update_param(self, p, grad, lr):
        g1 = self._accum("avg_squared_grad", p)
        g2 = self._accum("avg_squared_update", p)
        master = self._master(p)
        p32 = master if master is not None else p._value.astype(jnp.float32)
        p_new, g1n, g2n = _adadelta_update(
            p32, grad, g1, g2, jnp.asarray(lr, jnp.float32), jnp.float32(self._rho),
            jnp.float32(self._epsilon),
        )
        self._set_accum("avg_squared_grad", p, g1n)
        self._set_accum("avg_squared_update", p, g2n)
        if master is not None:
            self._apply(p, None, p_new)
        else:
            self._apply(p, p_new.astype(p._value.dtype))


@jax.jit
def _adamax_update(p32, g, m, inf_norm, lr, beta1, beta2, eps, t):
    g32 = g.astype(jnp.float32)
    m_new = beta1 * m + (1 - beta1) * g32
    inf_new = jnp.maximum(beta2 * inf_norm, jnp.abs(g32))
    p_new = p32 - lr / (1 - jnp.power(beta1, t)) * m_new / (inf_new + eps)
    return p_new, m_new, inf_new


class Adamax(Optimizer):
    _accum_names = ("moment", "inf_norm")
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _update_param(self, p, grad, lr):
        m = self._accum("moment", p)
        inf = self._accum("inf_norm", p)
        master = self._master(p)
        p32 = master if master is not None else p._value.astype(jnp.float32)
        t = self._step_num()
        p_new, m_new, inf_new = _adamax_update(
            p32, grad, m, inf, jnp.asarray(lr, jnp.float32), jnp.float32(self._beta1),
            jnp.float32(self._beta2), jnp.float32(self._epsilon), t,
        )
        self._set_accum("moment", p, m_new)
        self._set_accum("inf_norm", p, inf_new)
        if master is not None:
            self._apply(p, None, p_new)
        else:
            self._apply(p, p_new.astype(p._value.dtype))


@jax.jit
def _lamb_update(p32, g, m, v, lr, beta1, beta2, eps, t, wd):
    g32 = g.astype(jnp.float32)
    m_new = beta1 * m + (1 - beta1) * g32
    v_new = beta2 * v + (1 - beta2) * g32 * g32
    mhat = m_new / (1 - jnp.power(beta1, t))
    vhat = v_new / (1 - jnp.power(beta2, t))
    r = mhat / (jnp.sqrt(vhat) + eps) + wd * p32
    w_norm = jnp.linalg.norm(p32)
    r_norm = jnp.linalg.norm(r)
    ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    return p32 - lr * ratio * r, m_new, v_new


class Lamb(Optimizer):
    _accum_names = ("moment1", "moment2")
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_param(self, p, grad, lr):
        m = self._accum("moment1", p)
        v = self._accum("moment2", p)
        master = self._master(p)
        p32 = master if master is not None else p._value.astype(jnp.float32)
        wd = self._wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        t = self._step_num()
        p_new, m_new, v_new = _lamb_update(
            p32, grad, m, v, jnp.asarray(lr, jnp.float32), jnp.float32(self._beta1),
            jnp.float32(self._beta2), jnp.float32(self._epsilon), t,
            jnp.float32(wd),
        )
        self._set_accum("moment1", p, m_new)
        self._set_accum("moment2", p, v_new)
        if master is not None:
            self._apply(p, None, p_new)
        else:
            self._apply(p, p_new.astype(p._value.dtype))
