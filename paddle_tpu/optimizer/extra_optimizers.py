"""Optimizer surface completion.

Reference: python/paddle/optimizer/ — asgd.py (ASGD with the d/y running
averages), radam.py (RAdam rectified moment schedule), rprop.py (sign-based
step adaptation), nadam.py (Nesterov Adam with mu-product schedule); LBFGS
re-exported from incubate.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["ASGD", "RAdam", "Rprop", "NAdam"]


class ASGD(Optimizer):
    """Reference: optimizer/asgd.py — averaged SGD. Keeps a window of n
    historical gradients (n=batch_num) as an accumulator [n, *shape] so the
    whole state lifts to functional form under jit capture; update uses
    d = d - y_old + g and the running mean d/n. The rolling write position
    is derived from the shared step counter (same for every param)."""

    _accum_names = ("d", "grad_window")

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        if batch_num <= 0:
            raise ValueError("batch_num must be positive")
        self._n = int(batch_num)

    def _ensure_accumulators(self):
        # grad_window is [n, *shape]; the base pre-creation would make it
        # p-shaped zeros, so create both accumulators with their real inits
        for p in self._parameter_list:
            if not getattr(p, "trainable", True):
                continue
            self._accum("d", p)
            self._accum("grad_window", p, init=jnp.zeros(
                (self._n,) + tuple(p._value.shape), jnp.float32))
            self._master(p)

    def _update_param(self, p, grad, lr):
        master = self._master(p)
        pv = (master if master is not None else p._value).astype(jnp.float32)
        g = grad.astype(jnp.float32)
        d = self._accum("d", p)
        window = self._accum(
            "grad_window", p,
            init=jnp.zeros((self._n,) + tuple(p._value.shape), jnp.float32))
        pos = jnp.mod(self._step_num().astype(jnp.int32) - 1, self._n)
        y_old = jax.lax.dynamic_index_in_dim(window, pos, 0, keepdims=False)
        d = d - y_old + g
        window = jax.lax.dynamic_update_index_in_dim(window, g, pos, 0)
        self._set_accum("d", p, d)
        self._set_accum("grad_window", p, window)
        new = pv - lr * d / self._n
        if master is not None:
            self._apply(p, None, new)
        else:
            self._apply(p, new.astype(p._value.dtype))


class RAdam(Optimizer):
    """Reference: optimizer/radam.py — rectified Adam (Liu et al. 2020)."""

    _accum_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _update_param(self, p, grad, lr):
        master = self._master(p)
        pv = (master if master is not None else p._value).astype(jnp.float32)
        g = grad.astype(jnp.float32)
        b1, b2 = self._beta1, self._beta2
        t = self._step_num()
        m = self._accum("moment1", p)
        v = self._accum("moment2", p)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        self._set_accum("moment1", p, m)
        self._set_accum("moment2", p, v)
        m_hat = m / (1 - b1 ** t)
        rho_inf = 2.0 / (1 - b2) - 1.0
        rho_t = rho_inf - 2.0 * t * (b2 ** t) / (1 - b2 ** t)
        tractable = rho_t > 5.0
        r = jnp.sqrt(jnp.maximum(
            ((rho_t - 4) * (rho_t - 2) * rho_inf)
            / jnp.maximum((rho_inf - 4) * (rho_inf - 2) * rho_t, 1e-12),
            0.0))
        v_hat = jnp.sqrt(v / (1 - b2 ** t)) + self._eps
        step_adapt = jnp.where(tractable, r * m_hat / v_hat, m_hat)
        new = pv - lr * step_adapt
        if master is not None:
            self._apply(p, None, new)
        else:
            self._apply(p, new.astype(p._value.dtype))


class Rprop(Optimizer):
    """Reference: optimizer/rprop.py — resilient backprop: per-weight step
    size grows when successive gradient signs agree, shrinks on sign flip
    (batch-mode only)."""

    _accum_names = ("prev_grad", "learning_rate_step")

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50.0),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas
        self._init_lr = learning_rate

    def _update_param(self, p, grad, lr):
        master = self._master(p)
        pv = (master if master is not None else p._value).astype(jnp.float32)
        g = grad.astype(jnp.float32)
        prev = self._accum("prev_grad", p)
        steps = self._accum("learning_rate_step", p)
        steps = jnp.where(steps == 0.0, self._init_lr, steps)
        sign = jnp.sign(prev * g)
        steps = jnp.clip(
            jnp.where(sign > 0, steps * self._eta_pos,
                      jnp.where(sign < 0, steps * self._eta_neg, steps)),
            self._lr_min, self._lr_max)
        # on sign flip the gradient is zeroed (no step) like the reference
        g_eff = jnp.where(sign < 0, 0.0, g)
        self._set_accum("prev_grad", p, g_eff)
        self._set_accum("learning_rate_step", p, steps)
        new = pv - steps * jnp.sign(g_eff)
        if master is not None:
            self._apply(p, None, new)
        else:
            self._apply(p, new.astype(p._value.dtype))


class NAdam(Optimizer):
    """Reference: optimizer/nadam.py — Adam with Nesterov momentum
    (mu-product schedule, Dozat 2016)."""

    _accum_names = ("moment1", "moment2", "mu_product")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _ensure_accumulators(self):
        for p in self._parameter_list:
            if not getattr(p, "trainable", True):
                continue
            self._accum("moment1", p)
            self._accum("moment2", p)
            self._accum("mu_product", p,
                        init=jnp.ones(p._value.shape, jnp.float32))
            self._master(p)

    def _update_param(self, p, grad, lr):
        master = self._master(p)
        pv = (master if master is not None else p._value).astype(jnp.float32)
        g = grad.astype(jnp.float32)
        b1, b2 = self._beta1, self._beta2
        t = self._step_num()
        mu_t = b1 * (1.0 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = b1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        # seeded to ones at creation; never use 0 as an init sentinel (the
        # product legitimately underflows toward 0 late in training)
        mu_prod_prev = self._accum(
            "mu_product", p, init=jnp.ones(p._value.shape, jnp.float32))
        mu_prod = mu_prod_prev * mu_t
        self._set_accum("mu_product", p, mu_prod)
        m = self._accum("moment1", p)
        v = self._accum("moment2", p)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        self._set_accum("moment1", p, m)
        self._set_accum("moment2", p, v)
        m_hat = mu_t1 * m / (1 - mu_prod * mu_t1) + \
            (1 - mu_t) * g / (1 - mu_prod)
        v_hat = v / (1 - b2 ** t)
        new = pv - lr * m_hat / (jnp.sqrt(v_hat) + self._eps)
        if master is not None:
            self._apply(p, None, new)
        else:
            self._apply(p, new.astype(p._value.dtype))
