"""paddle.optimizer parity surface. Reference: python/paddle/optimizer/."""
from .optimizer import Optimizer
from .optimizers import (
    SGD, Momentum, Adam, AdamW, RMSProp, Adagrad, Adadelta, Adamax, Lamb,
)
from . import lr
