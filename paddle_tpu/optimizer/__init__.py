"""paddle.optimizer parity surface. Reference: python/paddle/optimizer/."""
from .optimizer import Optimizer
from .optimizers import (
    SGD, Momentum, Adam, AdamW, RMSProp, Adagrad, Adadelta, Adamax, Lamb,
)
from . import lr

from .extra_optimizers import ASGD, RAdam, Rprop, NAdam  # noqa: F401
from ..incubate.optimizer.lbfgs import LBFGS  # noqa: F401
