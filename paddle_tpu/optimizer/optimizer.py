"""Optimizer base.

Reference: python/paddle/optimizer/optimizer.py (Optimizer — accumulators,
lr scheduling, grad clip, regularization, master weights for low-precision
params per adamw.py:493 multi_precision semantics).

TPU design: each parameter update is a pure jax function over
(param, grad, accumulators, hyperparams) jitted once per dtype/shape — the
multi-tensor-apply analog. Low-precision (bf16/fp16) params keep a float32
master copy when multi_precision=True.
"""
from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from ..autograd import no_grad


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        from . import lr as lr_mod

        if parameters is None:
            raise ValueError(
                "parameters must be provided (dygraph-style optimizer)"
            )
        self._parameter_list = list(parameters)
        self._param_groups: List[Dict[str, Any]] = []
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            groups = self._parameter_list
            self._parameter_list = []
            for g in groups:
                ps = list(g["params"])
                self._parameter_list.extend(ps)
                self._param_groups.append({**g, "params": ps})
        else:
            self._param_groups.append({"params": self._parameter_list})

        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        if isinstance(weight_decay, float) or isinstance(weight_decay, int):
            from ..regularizer import L2Decay

            self.regularization = L2Decay(float(weight_decay))
        else:
            self.regularization = weight_decay
        self._multi_precision = multi_precision
        self._accumulators: Dict[str, Dict[int, jax.Array]] = defaultdict(dict)
        self._master_weights: Dict[int, jax.Array] = {}
        self._step_count = 0
        # jit.to_static trace overrides: traced scalars standing in for the
        # python-side lr / step counter so compiled steps don't bake them in.
        self._lr_override = None
        self._step_override = None

    # ------------------------------------------------------------------
    def get_lr(self):
        from . import lr as lr_mod

        if self._lr_override is not None:
            return self._lr_override
        if isinstance(self._learning_rate, lr_mod.LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def _step_num(self):
        """1-based step index for bias correction (traced under capture)."""
        if self._step_override is not None:
            return self._step_override
        return jnp.float32(self._step_count + 1)

    def set_lr(self, value: float):
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ------------------------------------------------------------------
    def _accum(self, name: str, p: Parameter, init=None):
        store = self._accumulators[name]
        if id(p) not in store:
            store[id(p)] = (
                jnp.zeros(p._value.shape, jnp.float32) if init is None else init
            )
        return store[id(p)]

    def _set_accum(self, name: str, p: Parameter, value):
        self._accumulators[name][id(p)] = value

    # accumulator names per optimizer class (used by jit state lifting)
    _accum_names: tuple = ()

    def _ensure_accumulators(self):
        """Pre-create all accumulators/master weights so jit.to_static can
        lift them to functional state before the first step() runs."""
        for p in self._parameter_list:
            if not getattr(p, "trainable", True):
                continue
            for name in self._accum_names:
                self._accum(name, p)
            self._master(p)

    def _master(self, p: Parameter):
        if not self._multi_precision or p._value.dtype == jnp.float32:
            return None
        if id(p) not in self._master_weights:
            self._master_weights[id(p)] = p._value.astype(jnp.float32)
        return self._master_weights[id(p)]

    # ------------------------------------------------------------------
    def _params_grads(self):
        pg = []
        for p in self._parameter_list:
            if not p.trainable:
                continue
            g = None
            if p._grad_value is not None:
                g = Tensor._from_value(p._grad_value)
            pg.append((p, g))
        return pg

    @no_grad()
    def step(self):
        params_grads = self._params_grads()
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        for p, g in params_grads:
            if g is None:
                continue
            gv = g._value
            if self.regularization is not None and getattr(p, "regularizer", None) is None:
                gv = self.regularization._apply(p._value, gv)
            elif getattr(p, "regularizer", None) is not None:
                gv = p.regularizer._apply(p._value, gv)
            plr = lr * p.optimize_attr.get("learning_rate", 1.0)
            self._update_param(p, gv, plr)
        self._step_count += 1

    minimize_step = step

    def _update_param(self, p: Parameter, grad, lr: float):
        raise NotImplementedError

    def clear_grad(self, set_to_zero: bool = False):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    @no_grad()
    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        self.step()
        return None, None

    # ------------------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        sd: Dict[str, Any] = {}
        id2name = {id(p): (p.name or f"param_{i}") for i, p in enumerate(self._parameter_list)}
        for accum_name, store in self._accumulators.items():
            for pid, arr in store.items():
                sd[f"{id2name.get(pid, pid)}__{accum_name}"] = Tensor._from_value(arr)
        for pid, arr in self._master_weights.items():
            sd[f"{id2name.get(pid, pid)}__master"] = Tensor._from_value(arr)
        from . import lr as lr_mod

        if isinstance(self._learning_rate, lr_mod.LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        sd["__step__"] = self._step_count
        return sd

    def set_state_dict(self, state_dict: Dict[str, Any]):
        from . import lr as lr_mod

        name2id = {(p.name or f"param_{i}"): id(p) for i, p in enumerate(self._parameter_list)}
        for k, v in state_dict.items():
            if k == "LR_Scheduler":
                if isinstance(self._learning_rate, lr_mod.LRScheduler):
                    self._learning_rate.set_state_dict(v)
                continue
            if k == "__step__":
                self._step_count = int(v)
                continue
            pname, _, accum_name = k.rpartition("__")
            pid = name2id.get(pname)
            if pid is None:
                continue
            arr = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            if accum_name == "master":
                self._master_weights[pid] = arr
            else:
                self._accumulators[accum_name][pid] = arr

    load_state_dict = set_state_dict

    def _apply(self, p: Parameter, new_value, master=None):
        """Write back an updated value (and master copy)."""
        if master is not None:
            self._master_weights[id(p)] = master
            p._replace_value(master.astype(p._value.dtype))
        else:
            p._replace_value(new_value)
