"""Multinomial distribution (reference: python/paddle/distribution/multinomial.py)."""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution


def _multinomial_sample_fwd(key, probs, *, total_count, shape):
    # draw total_count categorical samples and histogram them (one-hot sum)
    k = probs.shape[-1]
    draws = jax.random.categorical(
        key, jnp.log(probs), axis=-1, shape=(total_count,) + shape
    )
    return jnp.sum(jax.nn.one_hot(draws, k, dtype=probs.dtype), axis=0)


_multinomial_sample = dprim("multinomial_sample", _multinomial_sample_fwd, nondiff=True)
_multinomial_log_prob = dprim(
    "multinomial_log_prob",
    lambda value, probs, *, total_count: jax.scipy.special.gammaln(total_count + 1.0)
    - jnp.sum(jax.scipy.special.gammaln(value + 1.0), axis=-1)
    + jnp.sum(jax.scipy.special.xlogy(value, probs), axis=-1),
)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        if int(total_count) < 1:
            raise ValueError("total_count should be greater than one.")
        self.total_count = int(total_count)
        (probs_t,) = broadcast_params(probs)
        self.probs = probs_t / probs_t.sum(axis=-1, keepdim=True)
        super().__init__(tuple(probs_t.shape[:-1]), tuple(probs_t.shape[-1:]))

    @property
    def mean(self):
        return self.probs * float(self.total_count)

    @property
    def variance(self):
        return self.probs * (1.0 - self.probs) * float(self.total_count)

    def sample(self, shape=()):
        full = to_shape_tuple(shape) + self.batch_shape
        return _multinomial_sample(
            key_tensor(), self.probs, total_count=self.total_count, shape=full
        )

    def log_prob(self, value):
        return _multinomial_log_prob(
            ensure_tensor(value), self.probs, total_count=float(self.total_count)
        )

    def entropy(self):
        # E[-log p(X)] with X ~ Multinomial: use the exact decomposition
        # -log n! + sum_i E[log x_i!] - n sum_i p_i log p_i is intractable in
        # closed form; follow the reference and Monte-Carlo-free bound via
        # per-category Binomial entropy is not provided — reference omits
        # entropy for Multinomial as well.
        raise NotImplementedError
