"""Value constraints (reference: python/paddle/distribution/constraint.py)."""
from __future__ import annotations

from ._ddefs import dprim, ensure_tensor, jnp


class Constraint:
    def __call__(self, value):
        raise NotImplementedError


class Real(Constraint):
    def __call__(self, value):
        value = ensure_tensor(value)
        return value == value


_range_check = dprim(
    "constraint_range",
    lambda v, lo, hi: (lo <= v) & (v <= hi),
)
_positive_check = dprim("constraint_positive", lambda v: v >= 0.0)
_simplex_check = dprim(
    "constraint_simplex",
    lambda v: jnp.all(v >= 0.0, axis=-1)
    & (jnp.abs(jnp.sum(v, axis=-1) - 1.0) < 1e-6),
)


class Range(Constraint):
    def __init__(self, lower, upper):
        self._lower = lower
        self._upper = upper

    def __call__(self, value):
        return _range_check(
            ensure_tensor(value), ensure_tensor(self._lower), ensure_tensor(self._upper)
        )


class Positive(Constraint):
    def __call__(self, value):
        return _positive_check(ensure_tensor(value))


class Simplex(Constraint):
    def __call__(self, value):
        return _simplex_check(ensure_tensor(value))


real = Real()
positive = Positive()
simplex = Simplex()
