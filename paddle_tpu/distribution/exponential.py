"""Exponential distribution (reference: python/paddle/distribution/exponential.py)."""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution
from .exponential_family import ExponentialFamily

_exp_std = dprim(
    "exp_std",
    lambda key, *, shape, dtype: jax.random.exponential(key, shape, jnp.dtype(dtype)),
    nondiff=True,
)
_exp_log_prob = dprim(
    "exp_log_prob", lambda value, rate: jnp.log(rate) - rate * value
)
_exp_cdf = dprim("exp_cdf", lambda value, rate: 1.0 - jnp.exp(-rate * value))
_exp_icdf = dprim("exp_icdf", lambda p, rate: -jnp.log1p(-p) / rate)


class Exponential(ExponentialFamily):
    def __init__(self, rate, name=None):
        (self.rate,) = broadcast_params(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return 1.0 / self.rate

    @property
    def variance(self):
        return 1.0 / (self.rate * self.rate)

    def rsample(self, shape=()):
        import numpy as np

        full = to_shape_tuple(shape) + self.batch_shape
        e = _exp_std(key_tensor(), shape=full, dtype=np.dtype(self.rate.dtype).name)
        return e / self.rate

    def log_prob(self, value):
        return _exp_log_prob(ensure_tensor(value), self.rate)

    def entropy(self):
        from ..ops.math import log

        return 1.0 - log(self.rate)

    def cdf(self, value):
        return _exp_cdf(ensure_tensor(value), self.rate)

    def icdf(self, value):
        return _exp_icdf(ensure_tensor(value), self.rate)

    @property
    def _natural_parameters(self):
        return (-self.rate,)

    def _log_normalizer(self, x):
        from ..ops.math import log

        return -log(-x)
