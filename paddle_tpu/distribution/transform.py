"""Bijective transforms (reference: python/paddle/distribution/transform.py:70-1330).

Each transform exposes forward / inverse / forward_log_det_jacobian /
inverse_log_det_jacobian / forward_shape / inverse_shape plus domain and
codomain variables, matching the reference class-by-class. Math runs through
framework primitives, so every transform is differentiable end to end.
"""
from __future__ import annotations

import functools
import math
import operator

from . import variable
from ._ddefs import dprim, ensure_tensor, jax, jnp
from .distribution import Distribution

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "IndependentTransform", "PowerTransform",
    "ReshapeTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
]


class _Type:
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class Transform:
    _type = _Type.INJECTION

    @property
    def _is_injective(self):
        return _Type.is_injective(self._type)

    @property
    def _domain(self):
        return variable.real

    @property
    def _codomain(self):
        return variable.real

    def __call__(self, input):
        if isinstance(input, Distribution):
            from .transformed_distribution import TransformedDistribution

            return TransformedDistribution(input, [self])
        return self.forward(ensure_tensor(input))

    def forward(self, x):
        return self._forward(ensure_tensor(x))

    def inverse(self, y):
        return self._inverse(ensure_tensor(y))

    def forward_log_det_jacobian(self, x):
        x = ensure_tensor(x)
        if hasattr(self, "_forward_log_det_jacobian"):
            return self._forward_log_det_jacobian(x)
        if hasattr(self, "_inverse_log_det_jacobian"):
            return -self._inverse_log_det_jacobian(self.forward(x))
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        y = ensure_tensor(y)
        if hasattr(self, "_inverse_log_det_jacobian"):
            return self._inverse_log_det_jacobian(y)
        if hasattr(self, "_forward_log_det_jacobian"):
            return -self._forward_log_det_jacobian(self.inverse(y))
        raise NotImplementedError

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)


class AbsTransform(Transform):
    """y = |x| (reference transform.py:374) — surjective, not injective."""

    _type = _Type.SURJECTION

    def _forward(self, x):
        from ..ops.math import abs as abs_

        return abs_(x)

    def _inverse(self, y):
        return y


class AffineTransform(Transform):
    """y = loc + scale * x (reference transform.py:447)."""

    _type = _Type.BIJECTION

    def __init__(self, loc, scale):
        self._loc = ensure_tensor(loc)
        self._scale = ensure_tensor(scale)

    @property
    def loc(self):
        return self._loc

    @property
    def scale(self):
        return self._scale

    def _forward(self, x):
        return self._loc + self._scale * x

    def _inverse(self, y):
        return (y - self._loc) / self._scale

    def _forward_log_det_jacobian(self, x):
        from ..ops.math import abs as abs_
        from ..ops.math import log
        from ..ops.creation import ones_like

        return log(abs_(self._scale * ones_like(x)))


class ChainTransform(Transform):
    """Composition t_n ∘ ... ∘ t_1 (reference transform.py:534)."""

    def __init__(self, transforms):
        if not all(isinstance(t, Transform) for t in transforms):
            raise TypeError("All elements of transforms should be Transform type.")
        self.transforms = tuple(transforms)

    @property
    def _is_injective(self):
        return all(t._is_injective for t in self.transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        x = ensure_tensor(x)
        value = 0.0
        event_rank = self._domain.event_rank
        for t in self.transforms:
            value = value + self._sum_rightmost(
                t.forward_log_det_jacobian(x), event_rank - t._domain.event_rank
            )
            x = t.forward(x)
            event_rank += t._codomain.event_rank - t._domain.event_rank
        return value

    def inverse_log_det_jacobian(self, y):
        return -self.forward_log_det_jacobian(self.inverse(ensure_tensor(y)))

    @staticmethod
    def _sum_rightmost(t, n):
        if n <= 0:
            return t
        from ..ops.math import sum as sum_

        return sum_(t, axis=tuple(range(t.ndim - n, t.ndim)))

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)

    @property
    def _domain(self):
        return self.transforms[0]._domain

    @property
    def _codomain(self):
        return self.transforms[-1]._codomain


class ExpTransform(Transform):
    """y = exp(x) (reference transform.py:659)."""

    _type = _Type.BIJECTION

    @property
    def _codomain(self):
        return variable.positive

    def _forward(self, x):
        from ..ops.math import exp

        return exp(x)

    def _inverse(self, y):
        from ..ops.math import log

        return log(y)

    def _forward_log_det_jacobian(self, x):
        return x

    def _inverse_log_det_jacobian(self, y):
        from ..ops.math import log

        return -log(y)


class IndependentTransform(Transform):
    """Reinterpret rightmost batch dims as event dims (reference transform.py:709)."""

    def __init__(self, base, reinterpreted_batch_rank):
        if not isinstance(base, Transform):
            raise TypeError("base should be a Transform instance")
        if reinterpreted_batch_rank <= 0:
            raise ValueError("reinterpreted_batch_rank should be positive")
        self._base = base
        self._reinterpreted_batch_rank = int(reinterpreted_batch_rank)

    @property
    def _is_injective(self):
        return self._base._is_injective

    @property
    def _domain(self):
        return variable.Independent(self._base._domain, self._reinterpreted_batch_rank)

    @property
    def _codomain(self):
        return variable.Independent(self._base._codomain, self._reinterpreted_batch_rank)

    def _forward(self, x):
        return self._base.forward(x)

    def _inverse(self, y):
        return self._base.inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = self._base.forward_log_det_jacobian(x)
        from ..ops.math import sum as sum_

        r = self._reinterpreted_batch_rank
        return sum_(ldj, axis=tuple(range(ldj.ndim - r, ldj.ndim)))

    def forward_shape(self, shape):
        return self._base.forward_shape(shape)

    def inverse_shape(self, shape):
        return self._base.inverse_shape(shape)


class PowerTransform(Transform):
    """y = x^power on the positive reals (reference transform.py:804)."""

    _type = _Type.BIJECTION

    def __init__(self, power):
        self._power = ensure_tensor(power)

    @property
    def power(self):
        return self._power

    @property
    def _domain(self):
        return variable.positive

    @property
    def _codomain(self):
        return variable.positive

    def _forward(self, x):
        from ..ops.math import pow as pow_

        return pow_(x, self._power)

    def _inverse(self, y):
        from ..ops.math import pow as pow_

        return pow_(y, 1.0 / self._power)

    def _forward_log_det_jacobian(self, x):
        from ..ops.math import abs as abs_
        from ..ops.math import log

        return log(abs_(self._power * x ** (self._power - 1.0)))

    def forward_shape(self, shape):
        return tuple(jnp.broadcast_shapes(tuple(shape), tuple(self._power.shape)))

    inverse_shape = forward_shape


class ReshapeTransform(Transform):
    """Reshape the event shape (reference transform.py:871)."""

    _type = _Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        in_event_shape, out_event_shape = tuple(in_event_shape), tuple(out_event_shape)
        if functools.reduce(operator.mul, in_event_shape, 1) != functools.reduce(
            operator.mul, out_event_shape, 1
        ):
            raise ValueError(
                f"The numel of in_event_shape should be same with out_event_shape, "
                f"but got {in_event_shape} and {out_event_shape}"
            )
        self._in_event_shape = in_event_shape
        self._out_event_shape = out_event_shape

    @property
    def in_event_shape(self):
        return self._in_event_shape

    @property
    def out_event_shape(self):
        return self._out_event_shape

    @property
    def _domain(self):
        return variable.Independent(variable.real, len(self._in_event_shape))

    @property
    def _codomain(self):
        return variable.Independent(variable.real, len(self._out_event_shape))

    def _forward(self, x):
        from ..ops.manipulation import reshape

        batch = tuple(x.shape)[: x.ndim - len(self._in_event_shape)]
        return reshape(x, batch + self._out_event_shape)

    def _inverse(self, y):
        from ..ops.manipulation import reshape

        batch = tuple(y.shape)[: y.ndim - len(self._out_event_shape)]
        return reshape(y, batch + self._in_event_shape)

    def _forward_log_det_jacobian(self, x):
        from ..ops.creation import zeros

        batch = tuple(x.shape)[: x.ndim - len(self._in_event_shape)]
        return zeros(batch if batch else [1], dtype=x.dtype)

    def forward_shape(self, shape):
        n = len(self._in_event_shape)
        if tuple(shape[len(shape) - n:]) != self._in_event_shape:
            raise ValueError("shape mismatch in ReshapeTransform.forward_shape")
        return tuple(shape[: len(shape) - n]) + self._out_event_shape

    def inverse_shape(self, shape):
        n = len(self._out_event_shape)
        if tuple(shape[len(shape) - n:]) != self._out_event_shape:
            raise ValueError("shape mismatch in ReshapeTransform.inverse_shape")
        return tuple(shape[: len(shape) - n]) + self._in_event_shape


_sigmoid_fldj = dprim(
    "sigmoid_fldj",
    lambda x: -jax.nn.softplus(-x) - jax.nn.softplus(x),
)


class SigmoidTransform(Transform):
    """y = sigmoid(x) (reference transform.py:997)."""

    _type = _Type.BIJECTION

    @property
    def _codomain(self):
        from .constraint import Range

        return variable.Variable(False, 0, Range(0.0, 1.0))

    def _forward(self, x):
        from ..ops.activation import sigmoid

        return sigmoid(x)

    def _inverse(self, y):
        from ..ops.math import log

        return log(y) - log(1.0 - y)

    def _forward_log_det_jacobian(self, x):
        return _sigmoid_fldj(x)


class SoftmaxTransform(Transform):
    """x → softmax-normalized simplex point (reference transform.py:1040).
    Not bijective: no log-det jacobian."""

    _type = _Type.OTHER

    @property
    def _domain(self):
        return variable.Independent(variable.real, 1)

    @property
    def _codomain(self):
        return variable.Variable(False, 1, None)

    def _forward(self, x):
        from ..ops.math import exp, max as max_, sum as sum_

        z = exp(x - max_(x, axis=-1, keepdim=True))
        return z / z.sum(axis=-1, keepdim=True)

    def _inverse(self, y):
        from ..ops.math import log

        return log(y)


class StackTransform(Transform):
    """Apply a sequence of transforms to slices along an axis
    (reference transform.py:1097)."""

    def __init__(self, transforms, axis=0):
        if not transforms or not all(isinstance(t, Transform) for t in transforms):
            raise TypeError("transforms should be a non-empty sequence of Transform")
        self._transforms = tuple(transforms)
        self._axis = int(axis)

    @property
    def transforms(self):
        return self._transforms

    @property
    def axis(self):
        return self._axis

    @property
    def _is_injective(self):
        return all(t._is_injective for t in self._transforms)

    @property
    def _domain(self):
        return variable.Stack([t._domain for t in self._transforms], self._axis)

    @property
    def _codomain(self):
        return variable.Stack([t._codomain for t in self._transforms], self._axis)

    def _zip_slices(self, v):
        from ..ops.manipulation import unstack

        slices = unstack(v, self._axis)
        if len(slices) != len(self._transforms):
            raise ValueError(
                f"Input has {len(slices)} slices along axis {self._axis}, "
                f"expected {len(self._transforms)}"
            )
        return slices

    def _forward(self, x):
        from ..ops.manipulation import stack

        return stack(
            [t.forward(v) for t, v in zip(self._transforms, self._zip_slices(x))],
            self._axis,
        )

    def _inverse(self, y):
        from ..ops.manipulation import stack

        return stack(
            [t.inverse(v) for t, v in zip(self._transforms, self._zip_slices(y))],
            self._axis,
        )

    def _forward_log_det_jacobian(self, x):
        from ..ops.manipulation import stack

        return stack(
            [
                t.forward_log_det_jacobian(v)
                for t, v in zip(self._transforms, self._zip_slices(x))
            ],
            self._axis,
        )


def _stickbreaking_fwd2(x):
    # numerically standard construction (matches torch/paddle):
    offset = x.shape[-1] + 1 - jnp.cumsum(jnp.ones_like(x), axis=-1)
    z = jax.nn.sigmoid(x - jnp.log(offset))
    one_minus_cumprod = jnp.cumprod(1.0 - z, axis=-1)
    pad = [(0, 0)] * (x.ndim - 1)
    y_head = z * jnp.concatenate(
        [jnp.ones(x.shape[:-1] + (1,), x.dtype), one_minus_cumprod[..., :-1]], axis=-1
    )
    y_tail = one_minus_cumprod[..., -1:]
    return jnp.concatenate([y_head, y_tail], axis=-1)


def _stickbreaking_inv(y):
    y_crop = y[..., :-1]
    offset = y.shape[-1] - jnp.cumsum(jnp.ones_like(y_crop), axis=-1)
    sf = 1.0 - jnp.cumsum(y_crop, axis=-1)
    x = jnp.log(y_crop) - jnp.log(sf) + jnp.log(offset)
    return x


def _stickbreaking_fldj(x):
    offset = x.shape[-1] + 1 - jnp.cumsum(jnp.ones_like(x), axis=-1)
    xo = x - jnp.log(offset)
    y = _stickbreaking_fwd2(x)
    return jnp.sum(-xo + jax.nn.log_sigmoid(xo) + jnp.log(y[..., :-1]), axis=-1)


_sb_fwd = dprim("stickbreaking_fwd", _stickbreaking_fwd2)
_sb_inv = dprim("stickbreaking_inv", _stickbreaking_inv)
_sb_fldj = dprim("stickbreaking_fldj", _stickbreaking_fldj)


class StickBreakingTransform(Transform):
    """R^(K-1) → K-simplex via stick-breaking (reference transform.py:1217)."""

    _type = _Type.BIJECTION

    @property
    def _domain(self):
        return variable.Independent(variable.real, 1)

    @property
    def _codomain(self):
        return variable.Variable(False, 1, None)

    def _forward(self, x):
        return _sb_fwd(x)

    def _inverse(self, y):
        return _sb_inv(y)

    def _forward_log_det_jacobian(self, x):
        return _sb_fldj(x)

    def forward_shape(self, shape):
        if not shape:
            raise ValueError("Too few dimensions on input")
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        if not shape:
            raise ValueError("Too few dimensions on input")
        return tuple(shape[:-1]) + (shape[-1] - 1,)


_tanh_fldj = dprim(
    "tanh_fldj",
    lambda x: 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x)),
)


class TanhTransform(Transform):
    """y = tanh(x) (reference transform.py:1283)."""

    _type = _Type.BIJECTION

    @property
    def _codomain(self):
        from .constraint import Range

        return variable.Variable(False, 0, Range(-1.0, 1.0))

    def _forward(self, x):
        from ..ops.math import tanh

        return tanh(x)

    def _inverse(self, y):
        from ..ops.math import atanh

        return atanh(y)

    def _forward_log_det_jacobian(self, x):
        return _tanh_fldj(x)
