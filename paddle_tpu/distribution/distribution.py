"""Distribution base class.

Reference: python/paddle/distribution/distribution.py — batch_shape /
event_shape bookkeeping, sample/rsample/prob/log_prob/entropy contract,
``sample_shape + batch_shape + event_shape`` sample layout.
"""
from __future__ import annotations

from ._ddefs import Tensor, ensure_tensor, to_shape_tuple


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = to_shape_tuple(batch_shape)
        self._event_shape = to_shape_tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        from ..ops.math import sqrt

        return sqrt(self.variance)

    def sample(self, shape=()):
        """Draw samples; default delegates to rsample without gradients
        (reference distribution.py sample→rsample contract)."""
        from .. import autograd

        with autograd.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ..ops.math import exp

        return exp(self.log_prob(value))

    # paddle exposes both prob() and probs() historically
    def probs(self, value):
        return self.prob(value)

    def entropy(self):
        raise NotImplementedError

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return to_shape_tuple(sample_shape) + self._batch_shape + self._event_shape

    def __repr__(self):
        return f"{type(self).__name__}(batch_shape={self._batch_shape}, event_shape={self._event_shape})"
