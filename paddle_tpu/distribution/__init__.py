"""``paddle.distribution`` parity package (reference: python/paddle/distribution/__init__.py)."""
from . import transform
from .bernoulli import Bernoulli
from .beta import Beta
from .binomial import Binomial
from .categorical import Categorical
from .cauchy import Cauchy
from .chi2 import Chi2
from .continuous_bernoulli import ContinuousBernoulli
from .dirichlet import Dirichlet
from .distribution import Distribution
from .exponential import Exponential
from .exponential_family import ExponentialFamily
from .gamma import Gamma
from .geometric import Geometric
from .gumbel import Gumbel
from .independent import Independent
from .kl import kl_divergence, register_kl
from .laplace import Laplace
from .lkj_cholesky import LKJCholesky
from .lognormal import LogNormal
from .multinomial import Multinomial
from .multivariate_normal import MultivariateNormal
from .normal import Normal
from .poisson import Poisson
from .student_t import StudentT
from .transform import (  # noqa: F401
    AbsTransform,
    AffineTransform,
    ChainTransform,
    ExpTransform,
    IndependentTransform,
    PowerTransform,
    ReshapeTransform,
    SigmoidTransform,
    SoftmaxTransform,
    StackTransform,
    StickBreakingTransform,
    TanhTransform,
    Transform,
)
from .transformed_distribution import TransformedDistribution
from .uniform import Uniform

__all__ = [
    "Bernoulli", "Beta", "Binomial", "Categorical", "Cauchy", "Chi2",
    "ContinuousBernoulli", "Dirichlet", "Distribution", "Exponential",
    "ExponentialFamily", "Gamma", "Geometric", "Gumbel", "Independent",
    "LKJCholesky", "Laplace", "LogNormal", "Multinomial",
    "MultivariateNormal", "Normal", "Poisson", "StudentT",
    "TransformedDistribution", "Uniform", "kl_divergence", "register_kl",
    "AbsTransform", "AffineTransform", "ChainTransform", "ExpTransform",
    "IndependentTransform", "PowerTransform", "ReshapeTransform",
    "SigmoidTransform", "SoftmaxTransform", "StackTransform",
    "StickBreakingTransform", "TanhTransform", "Transform",
]
