"""Chi-squared distribution (reference: python/paddle/distribution/chi2.py) —
Gamma(df/2, 1/2)."""
from __future__ import annotations

from ._ddefs import broadcast_params
from .gamma import Gamma


class Chi2(Gamma):
    def __init__(self, df, name=None):
        (df_t,) = broadcast_params(df)
        super().__init__(df_t * 0.5, df_t * 0.0 + 0.5)

    @property
    def df(self):
        return self.concentration * 2.0
