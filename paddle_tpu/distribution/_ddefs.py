"""Shared machinery for paddle.distribution.

Each distribution's math (log_prob/entropy/cdf/...) is registered as one
framework primitive (pure jnp function), so the whole expression compiles to
a single fused XLA program and differentiates through the framework autograd
(jax.vjp fallback in core/dispatch.py). Sampling draws keys from the global
generator stream (core/generator.py) like the random creation ops.

Reference analog: python/paddle/distribution/* compose per-op paddle calls;
collapsing each method into one primitive is the TPU-idiomatic equivalent
(one dispatch instead of dozens).
"""
from __future__ import annotations

import numbers

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ..ops._helpers import defprim, ensure_tensor
from ..ops.creation import _key_tensor

__all__ = [
    "Tensor", "apply", "defprim", "ensure_tensor", "jnp", "jax", "np",
    "dprim", "key_tensor", "broadcast_params", "to_shape_tuple",
]

_registered = set()


def dprim(name: str, fn, **kw):
    """Register a distribution primitive (idempotent) and return its caller."""
    pname = f"dist_{name}"
    if pname not in _registered:
        defprim(pname, fn, **kw)
        _registered.add(pname)

    def call(*tensors, **static):
        return apply(pname, *tensors, **static)

    call.__name__ = pname
    return call


def key_tensor() -> Tensor:
    return _key_tensor()


def broadcast_params(*params, dtype=None):
    """Convert params to Tensors of a common broadcast shape and dtype
    (reference distributions broadcast loc/scale in __init__)."""
    ts = []
    for p in params:
        if isinstance(p, Tensor):
            ts.append(p)
        elif isinstance(p, (numbers.Number, np.bool_)):
            ts.append(Tensor._from_value(jnp.asarray(p, dtype=np.dtype(dtype or "float32"))))
        else:
            ts.append(ensure_tensor(p))
    common = jnp.result_type(*[t._value for t in ts])
    if not jnp.issubdtype(common, jnp.floating):
        common = np.dtype(dtype or "float32")
    shape = jnp.broadcast_shapes(*[t._value.shape for t in ts])
    # broadcast/cast through framework ops so params stay connected to the
    # autograd graph (rsample/log_prob gradients reach the caller's tensors)
    from ..ops.math import cast
    from ..ops.manipulation import broadcast_to

    out = []
    for t in ts:
        if np.dtype(t.dtype) != np.dtype(common):
            t = cast(t, common)
        if tuple(t.shape) != tuple(shape):
            t = broadcast_to(t, shape)
        out.append(t)
    return out


def to_shape_tuple(shape):
    if shape is None:
        return ()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)
