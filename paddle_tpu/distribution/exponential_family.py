"""ExponentialFamily base (reference: python/paddle/distribution/exponential_family.py).

Subclasses expose natural parameters and a log-normalizer; the generic
KL between two members of the same family is a Bregman divergence of the
log-normalizer, computed in kl.py with jax autodiff (the reference computes
the same thing with paddle.grad)."""
from __future__ import annotations

from .distribution import Distribution


class ExponentialFamily(Distribution):
    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0
