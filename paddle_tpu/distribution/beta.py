"""Beta distribution (reference: python/paddle/distribution/beta.py)."""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution
from .exponential_family import ExponentialFamily


def _betaln(a, b):
    return (
        jax.scipy.special.gammaln(a)
        + jax.scipy.special.gammaln(b)
        - jax.scipy.special.gammaln(a + b)
    )


_beta_sample = dprim(
    "beta_sample",
    lambda key, a, b, *, shape: jax.random.beta(key, a, b, shape, dtype=a.dtype),
    nondiff=True,
)
_beta_log_prob = dprim(
    "beta_log_prob",
    lambda value, a, b: (a - 1.0) * jnp.log(value)
    + (b - 1.0) * jnp.log1p(-value)
    - _betaln(a, b),
)
_beta_entropy = dprim(
    "beta_entropy",
    lambda a, b: _betaln(a, b)
    - (a - 1.0) * jax.scipy.special.digamma(a)
    - (b - 1.0) * jax.scipy.special.digamma(b)
    + (a + b - 2.0) * jax.scipy.special.digamma(a + b),
)


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta, name=None):
        self.alpha, self.beta = broadcast_params(alpha, beta)
        super().__init__(tuple(self.alpha.shape))

    @property
    def mean(self):
        return self.alpha / (self.alpha + self.beta)

    @property
    def variance(self):
        s = self.alpha + self.beta
        return self.alpha * self.beta / (s * s * (s + 1.0))

    def sample(self, shape=()):
        full = to_shape_tuple(shape) + self.batch_shape
        return _beta_sample(key_tensor(), self.alpha, self.beta, shape=full)

    def log_prob(self, value):
        return _beta_log_prob(ensure_tensor(value), self.alpha, self.beta)

    def entropy(self):
        return _beta_entropy(self.alpha, self.beta)

    @property
    def _natural_parameters(self):
        return (self.alpha, self.beta)

    def _log_normalizer(self, x, y):
        from ..ops.math import lgamma

        return lgamma(x) + lgamma(y) - lgamma(x + y)
