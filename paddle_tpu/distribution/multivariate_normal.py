"""Multivariate normal (reference: python/paddle/distribution/multivariate_normal.py)."""
from __future__ import annotations

import math

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution

_mvn_chol = dprim("mvn_chol", lambda cov: jnp.linalg.cholesky(cov))
_mvn_chol_inv = dprim(
    "mvn_chol_inv",
    lambda prec: jnp.linalg.cholesky(
        jnp.linalg.inv(prec)
    ),
)
_mvn_std = dprim(
    "mvn_std",
    lambda key, *, shape, dtype: jax.random.normal(key, shape, jnp.dtype(dtype)),
    nondiff=True,
)
_mvn_affine = dprim(
    "mvn_affine",
    lambda eps, loc, tril: loc + jnp.einsum("...ij,...j->...i", tril, eps),
)


def _mvn_log_prob_fwd(value, loc, tril):
    diff = value - loc
    t = jnp.broadcast_to(tril, diff.shape[:-1] + tril.shape[-2:])
    m = jax.scipy.linalg.solve_triangular(t, diff[..., None], lower=True)[..., 0]
    half_log_det = jnp.sum(jnp.log(jnp.diagonal(tril, axis1=-2, axis2=-1)), axis=-1)
    k = value.shape[-1]
    return -0.5 * (k * math.log(2 * math.pi) + jnp.sum(m * m, axis=-1)) - half_log_det


_mvn_log_prob = dprim("mvn_log_prob", _mvn_log_prob_fwd)
_mvn_entropy = dprim(
    "mvn_entropy",
    lambda tril: 0.5 * tril.shape[-1] * (1.0 + math.log(2 * math.pi))
    + jnp.sum(jnp.log(jnp.diagonal(tril, axis1=-2, axis2=-1)), axis=-1),
)


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        given = sum(m is not None for m in (covariance_matrix, precision_matrix, scale_tril))
        if given != 1:
            raise ValueError(
                "Exactly one of covariance_matrix, precision_matrix or scale_tril must be specified."
            )
        (self.loc,) = broadcast_params(loc)
        if self.loc.ndim < 1:
            raise ValueError("loc must be at least 1-dimensional")
        if scale_tril is not None:
            (self.scale_tril,) = broadcast_params(scale_tril)
        elif covariance_matrix is not None:
            (cov,) = broadcast_params(covariance_matrix)
            self.covariance_matrix = cov
            self.scale_tril = _mvn_chol(cov)
        else:
            (prec,) = broadcast_params(precision_matrix)
            self.precision_matrix = prec
            self.scale_tril = _mvn_chol_inv(prec)
        batch = jnp.broadcast_shapes(
            tuple(self.loc.shape[:-1]), tuple(self.scale_tril.shape[:-2])
        )
        super().__init__(batch, tuple(self.loc.shape[-1:]))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        from ..ops.math import sum as sum_

        return sum_(self.scale_tril * self.scale_tril, axis=-1)

    def rsample(self, shape=()):
        import numpy as np

        full = to_shape_tuple(shape) + self.batch_shape + self.event_shape
        eps = _mvn_std(key_tensor(), shape=full, dtype=np.dtype(self.loc.dtype).name)
        return _mvn_affine(eps, self.loc, self.scale_tril)

    def log_prob(self, value):
        return _mvn_log_prob(ensure_tensor(value), self.loc, self.scale_tril)

    def entropy(self):
        return _mvn_entropy(self.scale_tril)
