"""Gumbel distribution (reference: python/paddle/distribution/gumbel.py)."""
from __future__ import annotations

import math

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution

_EULER = 0.57721566490153286060

_gumbel_std = dprim(
    "gumbel_std",
    lambda key, *, shape, dtype: jax.random.gumbel(key, shape, jnp.dtype(dtype)),
    nondiff=True,
)
_gumbel_log_prob = dprim(
    "gumbel_log_prob",
    lambda value, loc, scale: -(
        (value - loc) / scale + jnp.exp(-(value - loc) / scale)
    )
    - jnp.log(scale),
)
_gumbel_cdf = dprim(
    "gumbel_cdf",
    lambda value, loc, scale: jnp.exp(-jnp.exp(-(value - loc) / scale)),
)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = broadcast_params(loc, scale)
        super().__init__(tuple(self.loc.shape))

    @property
    def mean(self):
        return self.loc + self.scale * _EULER

    @property
    def variance(self):
        return self.scale * self.scale * (math.pi**2 / 6.0)

    def rsample(self, shape=()):
        import numpy as np

        full = to_shape_tuple(shape) + self.batch_shape
        g = _gumbel_std(key_tensor(), shape=full, dtype=np.dtype(self.loc.dtype).name)
        return self.loc + self.scale * g

    def log_prob(self, value):
        return _gumbel_log_prob(ensure_tensor(value), self.loc, self.scale)

    def entropy(self):
        from ..ops.math import log

        return log(self.scale) + (1.0 + _EULER)

    def cdf(self, value):
        return _gumbel_cdf(ensure_tensor(value), self.loc, self.scale)
