"""Uniform distribution (reference: python/paddle/distribution/uniform.py)."""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution

_std_uniform = dprim(
    "std_uniform",
    lambda key, *, shape, dtype: jax.random.uniform(key, shape, jnp.dtype(dtype)),
    nondiff=True,
)
_uniform_log_prob = dprim(
    "uniform_log_prob",
    lambda value, low, high: jnp.where(
        (value >= low) & (value < high),
        -jnp.log(high - low),
        -jnp.inf,
    ),
)
_uniform_cdf = dprim(
    "uniform_cdf",
    lambda value, low, high: jnp.clip((value - low) / (high - low), 0.0, 1.0),
)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low, self.high = broadcast_params(low, high)
        super().__init__(tuple(self.low.shape))

    @property
    def mean(self):
        return (self.low + self.high) / 2.0

    @property
    def variance(self):
        d = self.high - self.low
        return d * d / 12.0

    def rsample(self, shape=()):
        import numpy as np

        full = to_shape_tuple(shape) + self.batch_shape
        u = _std_uniform(key_tensor(), shape=full, dtype=np.dtype(self.low.dtype).name)
        return self.low + (self.high - self.low) * u

    def sample(self, shape=(), seed=0):
        from .. import autograd

        with autograd.no_grad():
            return self.rsample(shape)

    def log_prob(self, value):
        return _uniform_log_prob(ensure_tensor(value), self.low, self.high)

    def entropy(self):
        from ..ops.math import log

        return log(self.high - self.low)

    def cdf(self, value):
        return _uniform_cdf(ensure_tensor(value), self.low, self.high)

    def icdf(self, value):
        return self.low + (self.high - self.low) * ensure_tensor(value)
