"""Continuous Bernoulli (reference: python/paddle/distribution/continuous_bernoulli.py).

Density p(x|λ) = C(λ) λ^x (1-λ)^(1-x) on [0,1], with normalizing constant
C(λ) = 2 atanh(1-2λ)/(1-2λ) for λ≠1/2 and 2 for λ=1/2; a Taylor expansion is
used inside ``lims`` around 0.5 for numerical stability (same policy as the
reference's _cut_support_region)."""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution


def _safe_lam(p, lims):
    # clamp λ away from 0.5 inside the unstable region, remember the mask
    lo, hi = lims
    unstable = (p > lo) & (p < hi)
    return unstable, jnp.where(unstable, lo, p)


def _log_norm_const(p, lims):
    unstable, lam = _safe_lam(p, lims)
    exact = jnp.log(jnp.abs(2.0 * jnp.arctanh(1.0 - 2.0 * lam))) - jnp.log(
        jnp.abs(1.0 - 2.0 * lam)
    )
    # 2nd-order Taylor of log C around λ=1/2: log 2 + 4/3 (λ-1/2)^2
    taylor = jnp.log(2.0) + 4.0 / 3.0 * (p - 0.5) ** 2
    return jnp.where(unstable, taylor, exact)


def _cb_log_prob_fwd(value, p, *, lims):
    return (
        _log_norm_const(p, lims)
        + jax.scipy.special.xlogy(value, p)
        + jax.scipy.special.xlog1py(1.0 - value, -p)
    )


def _cb_mean_fwd(p, *, lims):
    unstable, lam = _safe_lam(p, lims)
    exact = lam / (2.0 * lam - 1.0) + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * lam))
    taylor = 0.5 + (p - 0.5) / 3.0
    return jnp.where(unstable, taylor, exact)


def _cb_var_fwd(p, *, lims):
    unstable, lam = _safe_lam(p, lims)
    atan_h = jnp.arctanh(1.0 - 2.0 * lam)
    exact = lam * (lam - 1.0) / (1.0 - 2.0 * lam) ** 2 + 1.0 / (2.0 * atan_h) ** 2
    taylor = 1.0 / 12.0 - (p - 0.5) ** 2 / 5.0
    return jnp.where(unstable, taylor, exact)


def _cb_cdf_fwd(value, p, *, lims):
    unstable, lam = _safe_lam(p, lims)
    # closed form: [λ^x (1-λ)^(1-x) + λ - 1] / (2λ - 1)
    cdf_exact = (
        jnp.power(lam, value) * jnp.power(1.0 - lam, 1.0 - value) + lam - 1.0
    ) / (2.0 * lam - 1.0)
    cdf_taylor = value  # λ≈1/2 → uniform
    out = jnp.where(unstable, cdf_taylor, cdf_exact)
    return jnp.clip(out, 0.0, 1.0)


def _cb_icdf_fwd(u, p, *, lims):
    unstable, lam = _safe_lam(p, lims)
    exact = jnp.log1p(u * (2.0 * lam - 1.0) / (1.0 - lam)) / (
        jnp.log(lam) - jnp.log1p(-lam)
    )
    return jnp.where(unstable, u, exact)


_log_prob_p = dprim("cb_log_prob", _cb_log_prob_fwd)
_mean_p = dprim("cb_mean", _cb_mean_fwd)
_var_p = dprim("cb_var", _cb_var_fwd)
_cdf_p = dprim("cb_cdf", _cb_cdf_fwd)
_icdf_p = dprim("cb_icdf", _cb_icdf_fwd)
_u_p = dprim(
    "cb_uniform",
    lambda key, *, shape, dtype: jax.random.uniform(key, shape, jnp.dtype(dtype)),
    nondiff=True,
)


class ContinuousBernoulli(Distribution):
    _log_prob_p = staticmethod(_log_prob_p)
    _mean_p = staticmethod(_mean_p)
    _var_p = staticmethod(_var_p)
    _cdf_p = staticmethod(_cdf_p)
    _icdf_p = staticmethod(_icdf_p)
    _u_p = staticmethod(_u_p)

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        (self.probs,) = broadcast_params(probs)
        self._lims = (float(lims[0]), float(lims[1]))
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return self._mean_p(self.probs, lims=self._lims)

    @property
    def variance(self):
        return self._var_p(self.probs, lims=self._lims)

    def sample(self, shape=()):
        from .. import autograd

        with autograd.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        import numpy as np

        full = to_shape_tuple(shape) + self.batch_shape
        u = self._u_p(key_tensor(), shape=full, dtype=np.dtype(self.probs.dtype).name)
        return self._icdf_p(u, self.probs, lims=self._lims)

    def log_prob(self, value):
        return self._log_prob_p(ensure_tensor(value), self.probs, lims=self._lims)

    def entropy(self):
        # H = -(E[X] logit(λ) + log(1-λ) + log C(λ))
        from ..ops.math import log

        logits = log(self.probs / (1.0 - self.probs))
        log_c = Tensor_log_norm(self.probs, self._lims)
        return -(self.mean * logits + log(1.0 - self.probs) + log_c)

    def cdf(self, value):
        return self._cdf_p(ensure_tensor(value), self.probs, lims=self._lims)

    def icdf(self, value):
        return self._icdf_p(ensure_tensor(value), self.probs, lims=self._lims)


_log_norm_p = dprim("cb_log_norm", lambda p, *, lims: _log_norm_const(p, lims))


def Tensor_log_norm(p, lims):
    return _log_norm_p(p, lims=lims)
