"""Cauchy distribution (reference: python/paddle/distribution/cauchy.py)."""
from __future__ import annotations

import math

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution

_cauchy_std = dprim(
    "cauchy_std",
    lambda key, *, shape, dtype: jax.random.cauchy(key, shape, jnp.dtype(dtype)),
    nondiff=True,
)
_cauchy_log_prob = dprim(
    "cauchy_log_prob",
    lambda value, loc, scale: -jnp.log(math.pi * scale)
    - jnp.log1p(((value - loc) / scale) ** 2),
)
_cauchy_cdf = dprim(
    "cauchy_cdf",
    lambda value, loc, scale: jnp.arctan((value - loc) / scale) / math.pi + 0.5,
)
_cauchy_icdf = dprim(
    "cauchy_icdf",
    lambda p, loc, scale: loc + scale * jnp.tan(math.pi * (p - 0.5)),
)


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = broadcast_params(loc, scale)
        super().__init__(tuple(self.loc.shape))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean.")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance.")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev.")

    def rsample(self, shape=()):
        import numpy as np

        full = to_shape_tuple(shape) + self.batch_shape
        z = _cauchy_std(key_tensor(), shape=full, dtype=np.dtype(self.loc.dtype).name)
        return self.loc + self.scale * z

    def log_prob(self, value):
        return _cauchy_log_prob(ensure_tensor(value), self.loc, self.scale)

    def entropy(self):
        from ..ops.math import log

        return log(4.0 * math.pi * self.scale)

    def cdf(self, value):
        return _cauchy_cdf(ensure_tensor(value), self.loc, self.scale)

    def icdf(self, value):
        return _cauchy_icdf(ensure_tensor(value), self.loc, self.scale)
