"""Random-variable domain descriptors (reference: python/paddle/distribution/variable.py)."""
from __future__ import annotations

from . import constraint as _constraint


class Variable:
    def __init__(self, is_discrete=False, event_rank=0, constraint=None):
        self._is_discrete = is_discrete
        self._event_rank = event_rank
        self._constraint = constraint

    @property
    def is_discrete(self):
        return self._is_discrete

    @property
    def event_rank(self):
        return self._event_rank

    def constraint(self, value):
        return self._constraint(value)


class Real(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, _constraint.real)


class Positive(Variable):
    def __init__(self, event_rank=0):
        super().__init__(False, event_rank, _constraint.positive)


class Independent(Variable):
    def __init__(self, base, reinterpreted_batch_rank):
        self._base = base
        self._reinterpreted_batch_rank = reinterpreted_batch_rank
        super().__init__(
            base.is_discrete, base.event_rank + reinterpreted_batch_rank
        )

    def constraint(self, value):
        ret = self._base.constraint(value)
        if ret.ndim < self._reinterpreted_batch_rank:
            raise ValueError(
                f"Input dimensions must be equal or greater than {self._reinterpreted_batch_rank}"
            )
        from ..ops.math import all as all_

        return all_(
            ret,
            axis=tuple(range(ret.ndim - self._reinterpreted_batch_rank, ret.ndim)),
        )


class Stack(Variable):
    def __init__(self, vars, axis=0):
        self._vars = vars
        self._axis = axis

    @property
    def is_discrete(self):
        return any(v.is_discrete for v in self._vars)

    @property
    def event_rank(self):
        rank = max(v.event_rank for v in self._vars)
        if self._axis + rank < 0:
            rank += 1
        return rank

    def constraint(self, value):
        from ..ops.manipulation import stack, unstack

        return stack(
            [v.constraint(vv) for v, vv in zip(self._vars, unstack(value, self._axis))],
            self._axis,
        )


real = Real()
positive = Positive()
