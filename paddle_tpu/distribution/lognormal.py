"""LogNormal distribution (reference: python/paddle/distribution/lognormal.py) —
a TransformedDistribution of Normal through ExpTransform, with closed-form
moments."""
from __future__ import annotations

from ._ddefs import broadcast_params
from .normal import Normal
from .transform import ExpTransform
from .transformed_distribution import TransformedDistribution


class LogNormal(TransformedDistribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = broadcast_params(loc, scale)
        self._base = Normal(self.loc, self.scale)
        super().__init__(self._base, [ExpTransform()])

    @property
    def mean(self):
        from ..ops.math import exp

        return exp(self.loc + self.scale * self.scale / 2.0)

    @property
    def variance(self):
        from ..ops.math import exp

        s2 = self.scale * self.scale
        return (exp(s2) - 1.0) * exp(2.0 * self.loc + s2)

    def entropy(self):
        return self._base.entropy() + self.loc
