"""Dirichlet distribution (reference: python/paddle/distribution/dirichlet.py)."""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution
from .exponential_family import ExponentialFamily

_dir_sample = dprim(
    "dir_sample",
    lambda key, conc, *, shape: jax.random.dirichlet(key, conc, shape, dtype=conc.dtype),
    nondiff=True,
)
_dir_log_prob = dprim(
    "dir_log_prob",
    lambda value, conc: jnp.sum((conc - 1.0) * jnp.log(value), axis=-1)
    - jnp.sum(jax.scipy.special.gammaln(conc), axis=-1)
    + jax.scipy.special.gammaln(jnp.sum(conc, axis=-1)),
)


def _dir_entropy_fwd(conc):
    a0 = jnp.sum(conc, axis=-1)
    k = conc.shape[-1]
    log_b = jnp.sum(jax.scipy.special.gammaln(conc), axis=-1) - jax.scipy.special.gammaln(a0)
    return (
        log_b
        + (a0 - k) * jax.scipy.special.digamma(a0)
        - jnp.sum((conc - 1.0) * jax.scipy.special.digamma(conc), axis=-1)
    )


_dir_entropy = dprim("dir_entropy", _dir_entropy_fwd)


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration, name=None):
        (self.concentration,) = broadcast_params(concentration)
        if self.concentration.ndim < 1:
            raise ValueError("concentration must be at least 1-dimensional")
        super().__init__(
            tuple(self.concentration.shape[:-1]), tuple(self.concentration.shape[-1:])
        )

    @property
    def mean(self):
        return self.concentration / self.concentration.sum(axis=-1, keepdim=True)

    @property
    def variance(self):
        a0 = self.concentration.sum(axis=-1, keepdim=True)
        m = self.concentration / a0
        return m * (1.0 - m) / (a0 + 1.0)

    def sample(self, shape=()):
        full = to_shape_tuple(shape) + self.batch_shape
        return _dir_sample(key_tensor(), self.concentration, shape=full)

    def log_prob(self, value):
        return _dir_log_prob(ensure_tensor(value), self.concentration)

    def entropy(self):
        return _dir_entropy(self.concentration)

    @property
    def _natural_parameters(self):
        return (self.concentration,)

    def _log_normalizer(self, x):
        from ..ops.math import lgamma

        return lgamma(x).sum(axis=-1) - lgamma(x.sum(axis=-1))
