"""Geometric distribution (reference: python/paddle/distribution/geometric.py).

Support k ∈ {0, 1, 2, ...} with pmf (1-p)^k p (geometric.py:129 docstring);
mean = 1/p - 1 (:112)."""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution

_geom_rsample = dprim(
    "geom_rsample",
    lambda key, probs, *, shape: jnp.floor(
        jnp.log(
            jax.random.uniform(key, shape, probs.dtype, jnp.finfo(probs.dtype).tiny, 1.0)
        )
        / jnp.log1p(-probs)
    ),
    nondiff=True,
)
_geom_entropy = dprim(
    "geom_entropy",
    lambda p: -(
        jax.scipy.special.xlogy(p, p) + jax.scipy.special.xlog1py(1.0 - p, -p)
    )
    / p,
)
_geom_cdf = dprim(
    "geom_cdf", lambda k, p: 1.0 - jnp.power(1.0 - p, k + 1.0)
)


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        (self.probs,) = broadcast_params(probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return 1.0 / self.probs - 1.0

    @property
    def variance(self):
        return (1.0 / self.probs - 1.0) / self.probs

    def pmf(self, k):
        from ..ops.math import pow as pow_

        return pow_(1.0 - self.probs, ensure_tensor(k)) * self.probs

    def log_pmf(self, k):
        from ..ops.math import log

        return log(self.pmf(k))

    def log_prob(self, value):
        return self.log_pmf(value)

    def sample(self, shape=()):
        from .. import autograd

        with autograd.no_grad():
            return self.rsample(shape)

    def rsample(self, shape=()):
        full = to_shape_tuple(shape) + self.batch_shape
        return _geom_rsample(key_tensor(), self.probs, shape=full)

    def entropy(self):
        return _geom_entropy(self.probs)

    def cdf(self, k):
        return _geom_cdf(ensure_tensor(k), self.probs)
