"""Gamma distribution (reference: python/paddle/distribution/gamma.py)."""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution
from .exponential_family import ExponentialFamily

# jax.random.gamma implements implicit reparameterization gradients wrt the
# concentration, so the vjp fallback makes rsample differentiable — the TPU
# analog of the reference's standard_gamma backward.
_gamma_rsample = dprim(
    "gamma_rsample",
    lambda key, conc, rate, *, shape: jax.random.gamma(key, conc, shape, dtype=conc.dtype) / rate,
)
_gamma_log_prob = dprim(
    "gamma_log_prob",
    lambda value, conc, rate: conc * jnp.log(rate)
    + (conc - 1.0) * jnp.log(value)
    - rate * value
    - jax.scipy.special.gammaln(conc),
)
_gamma_entropy = dprim(
    "gamma_entropy",
    lambda conc, rate: conc
    - jnp.log(rate)
    + jax.scipy.special.gammaln(conc)
    + (1.0 - conc) * jax.scipy.special.digamma(conc),
)


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate, name=None):
        self.concentration, self.rate = broadcast_params(concentration, rate)
        super().__init__(tuple(self.concentration.shape))

    @property
    def mean(self):
        return self.concentration / self.rate

    @property
    def variance(self):
        return self.concentration / (self.rate * self.rate)

    def rsample(self, shape=()):
        full = to_shape_tuple(shape) + self.batch_shape
        return _gamma_rsample(key_tensor(), self.concentration, self.rate, shape=full)

    def log_prob(self, value):
        return _gamma_log_prob(ensure_tensor(value), self.concentration, self.rate)

    def entropy(self):
        return _gamma_entropy(self.concentration, self.rate)

    @property
    def _natural_parameters(self):
        return (self.concentration - 1.0, -self.rate)

    def _log_normalizer(self, x, y):
        from ..ops.math import lgamma, log

        return lgamma(x + 1.0) + (x + 1.0) * log(-(1.0 / y))
