"""Binomial distribution (reference: python/paddle/distribution/binomial.py)."""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution

_binom_sample = dprim(
    "binom_sample",
    lambda key, n, p, *, shape: jax.random.binomial(key, n, p, shape).astype(p.dtype),
    nondiff=True,
    jittable=False,
)
_binom_log_prob = dprim(
    "binom_log_prob",
    lambda value, n, p: jax.scipy.special.gammaln(n + 1.0)
    - jax.scipy.special.gammaln(value + 1.0)
    - jax.scipy.special.gammaln(n - value + 1.0)
    + jax.scipy.special.xlogy(value, p)
    + jax.scipy.special.xlog1py(n - value, -p),
)


def _binom_entropy_fwd(n, p):
    upper = int(jnp.max(n)) + 1
    values = jnp.arange(0, upper, dtype=p.dtype).reshape((-1,) + (1,) * p.ndim)
    lp = (
        jax.scipy.special.gammaln(n + 1.0)
        - jax.scipy.special.gammaln(values + 1.0)
        - jax.scipy.special.gammaln(n - values + 1.0)
        + jax.scipy.special.xlogy(values, p)
        + jax.scipy.special.xlog1py(n - values, -p)
    )
    lp = jnp.where(values <= n, lp, -jnp.inf)
    probs = jnp.exp(lp)
    return -jnp.sum(jnp.where(probs > 0.0, probs * lp, 0.0), axis=0)


_binom_entropy = dprim("binom_entropy", _binom_entropy_fwd, jittable=False)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count, self.probs = broadcast_params(total_count, probs)
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return self.total_count * self.probs

    @property
    def variance(self):
        return self.total_count * self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        full = to_shape_tuple(shape) + self.batch_shape
        return _binom_sample(key_tensor(), self.total_count, self.probs, shape=full)

    def log_prob(self, value):
        return _binom_log_prob(ensure_tensor(value), self.total_count, self.probs)

    def entropy(self):
        return _binom_entropy(self.total_count, self.probs)
