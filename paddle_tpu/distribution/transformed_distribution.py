"""TransformedDistribution (reference: python/paddle/distribution/transformed_distribution.py).

Pushes a base distribution through a chain of transforms; log_prob walks the
chain backwards accumulating inverse log-det jacobians."""
from __future__ import annotations

from .distribution import Distribution
from .transform import ChainTransform, Transform


def _sum_rightmost(t, n):
    if n <= 0:
        return t
    from ..ops.math import sum as sum_

    return sum_(t, axis=tuple(range(t.ndim - n, t.ndim)))


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        if not isinstance(base, Distribution):
            raise TypeError("base should be a Distribution instance")
        if not all(isinstance(t, Transform) for t in transforms):
            raise TypeError("transforms must be a sequence of Transform")
        self._base = base
        self._transforms = list(transforms)
        chain = ChainTransform(self._transforms) if self._transforms else None
        base_shape = base.batch_shape + base.event_shape
        if chain is not None:
            out_shape = chain.forward_shape(base_shape)
            event_rank = max(
                chain._codomain.event_rank,
                len(base.event_shape)
                + (len(out_shape) - len(base_shape)),
            )
        else:
            out_shape = base_shape
            event_rank = len(base.event_shape)
        cut = len(out_shape) - event_rank
        super().__init__(out_shape[:cut], out_shape[cut:])

    @property
    def transforms(self):
        return self._transforms

    def sample(self, shape=()):
        x = self._base.sample(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def rsample(self, shape=()):
        x = self._base.rsample(shape)
        for t in self._transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        from ._ddefs import ensure_tensor

        y = ensure_tensor(value)
        log_prob = 0.0
        event_rank = len(self.event_shape)
        for t in reversed(self._transforms):
            x = t.inverse(y)
            ldj = t.forward_log_det_jacobian(x)
            log_prob = log_prob - _sum_rightmost(
                ldj, event_rank - t._domain.event_rank
            )
            event_rank += t._domain.event_rank - t._codomain.event_rank
            y = x
        log_prob = log_prob + _sum_rightmost(
            self._base.log_prob(y), event_rank - len(self._base.event_shape)
        )
        return log_prob
