"""Normal distribution (reference: python/paddle/distribution/normal.py)."""
from __future__ import annotations

import math

from ._ddefs import broadcast_params, dprim, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution

_HALF_LOG_2PI = 0.5 * math.log(2 * math.pi)

_std_normal = dprim(
    "std_normal",
    lambda key, *, shape, dtype: jax.random.normal(key, shape, jnp.dtype(dtype)),
    nondiff=True,
)
_normal_log_prob = dprim(
    "normal_log_prob",
    lambda value, loc, scale: -((value - loc) ** 2) / (2.0 * scale**2)
    - jnp.log(scale) - _HALF_LOG_2PI,
)
_normal_entropy = dprim(
    "normal_entropy", lambda scale: 0.5 + _HALF_LOG_2PI + jnp.log(scale)
)
_normal_cdf = dprim(
    "normal_cdf",
    lambda value, loc, scale: 0.5
    * (1.0 + jax.scipy.special.erf((value - loc) / (scale * math.sqrt(2.0)))),
)
_normal_icdf = dprim(
    "normal_icdf",
    lambda p, loc, scale: loc
    + scale * math.sqrt(2.0) * jax.scipy.special.erfinv(2.0 * p - 1.0),
)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = broadcast_params(loc, scale)
        super().__init__(tuple(self.loc.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale * self.scale

    @property
    def stddev(self):
        return self.scale

    def rsample(self, shape=()):
        full = to_shape_tuple(shape) + self.batch_shape
        import numpy as np

        eps = _std_normal(key_tensor(), shape=full, dtype=np.dtype(self.loc.dtype).name)
        return self.loc + self.scale * eps

    def log_prob(self, value):
        from ._ddefs import ensure_tensor

        return _normal_log_prob(ensure_tensor(value), self.loc, self.scale)

    def entropy(self):
        return _normal_entropy(self.scale)

    def cdf(self, value):
        from ._ddefs import ensure_tensor

        return _normal_cdf(ensure_tensor(value), self.loc, self.scale)

    def icdf(self, value):
        from ._ddefs import ensure_tensor

        return _normal_icdf(ensure_tensor(value), self.loc, self.scale)
