"""Bernoulli distribution (reference: python/paddle/distribution/bernoulli.py)."""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution
from .exponential_family import ExponentialFamily

_bern_sample = dprim(
    "bern_sample",
    lambda key, probs, *, shape: jax.random.bernoulli(
        key, probs, shape
    ).astype(probs.dtype),
    nondiff=True,
)
# reparameterized sample: sigmoid((logits + logistic noise) / temperature)
# (reference bernoulli.py rsample — Gumbel-softmax style relaxation)
_bern_rsample = dprim(
    "bern_rsample",
    lambda key, probs, *, shape, temperature: jax.nn.sigmoid(
        (
            jnp.log(probs) - jnp.log1p(-probs)
            + (lambda u: jnp.log(u) - jnp.log1p(-u))(
                jax.random.uniform(
                    key, shape, probs.dtype, jnp.finfo(probs.dtype).tiny, 1.0
                )
            )
        )
        / temperature
    ),
)
_bern_log_prob = dprim(
    "bern_log_prob",
    lambda value, probs: jax.scipy.special.xlogy(value, probs)
    + jax.scipy.special.xlog1py(1.0 - value, -probs),
)
_bern_entropy = dprim(
    "bern_entropy",
    lambda probs: -(
        jax.scipy.special.xlogy(probs, probs)
        + jax.scipy.special.xlog1py(1.0 - probs, -probs)
    ),
)
_bern_cdf = dprim(
    "bern_cdf",
    lambda value, probs: jnp.where(
        value < 0.0, 0.0, jnp.where(value < 1.0, 1.0 - probs, 1.0)
    ),
)


class Bernoulli(ExponentialFamily):
    def __init__(self, probs, name=None):
        (self.probs,) = broadcast_params(probs)
        self.logits = None  # paddle exposes probs; logits derived lazily
        super().__init__(tuple(self.probs.shape))

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return self.probs * (1.0 - self.probs)

    def sample(self, shape=()):
        full = to_shape_tuple(shape) + self.batch_shape
        return _bern_sample(key_tensor(), self.probs, shape=full)

    def rsample(self, shape=(), temperature=1.0):
        full = to_shape_tuple(shape) + self.batch_shape
        return _bern_rsample(
            key_tensor(), self.probs, shape=full, temperature=float(temperature)
        )

    def log_prob(self, value):
        return _bern_log_prob(ensure_tensor(value), self.probs)

    def entropy(self):
        return _bern_entropy(self.probs)

    def cdf(self, value):
        return _bern_cdf(ensure_tensor(value), self.probs)

    @property
    def _natural_parameters(self):
        from ..ops.math import log

        return (log(self.probs / (1.0 - self.probs)),)

    def _log_normalizer(self, x):
        from ..ops.math import exp, log

        return log(1.0 + exp(x))
