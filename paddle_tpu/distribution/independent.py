"""Independent distribution wrapper (reference: python/paddle/distribution/independent.py).

Reinterprets the rightmost ``reinterpreted_batch_rank`` batch dims of a base
distribution as event dims: log_prob/entropy sum over them."""
from __future__ import annotations

from .distribution import Distribution


class Independent(Distribution):
    def __init__(self, base, reinterpreted_batch_rank):
        if not isinstance(base, Distribution):
            raise TypeError("base should be a Distribution instance")
        r = int(reinterpreted_batch_rank)
        if not 0 < r <= len(base.batch_shape):
            raise ValueError(
                f"reinterpreted_batch_rank {r} out of range for batch_shape {base.batch_shape}"
            )
        self._base = base
        self._reinterpreted_batch_rank = r
        shape = base.batch_shape + base.event_shape
        cut = len(base.batch_shape) - r
        super().__init__(shape[:cut], shape[cut:])

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    def log_prob(self, value):
        return self._sum_rightmost(self._base.log_prob(value))

    def entropy(self):
        return self._sum_rightmost(self._base.entropy())

    def _sum_rightmost(self, t):
        r = self._reinterpreted_batch_rank
        if r == 0:
            return t
        from ..ops.math import sum as sum_

        return sum_(t, axis=tuple(range(t.ndim - r, t.ndim)))
