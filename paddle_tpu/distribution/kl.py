"""KL divergence registry (reference: python/paddle/distribution/kl.py).

``register_kl(P, Q)`` registers a pairwise rule; ``kl_divergence`` dispatches
on the most specific registered (type(p), type(q)) pair. The generic
exponential-family fallback computes the Bregman divergence of the
log-normalizer with jax autodiff (the reference uses paddle.grad for the
same construction, kl.py:242-280)."""
from __future__ import annotations

import functools

from ._ddefs import dprim, jax, jnp, Tensor
from .bernoulli import Bernoulli
from .beta import Beta
from .binomial import Binomial
from .categorical import Categorical
from .cauchy import Cauchy
from .continuous_bernoulli import ContinuousBernoulli
from .dirichlet import Dirichlet
from .distribution import Distribution
from .exponential import Exponential
from .exponential_family import ExponentialFamily
from .gamma import Gamma
from .geometric import Geometric
from .laplace import Laplace
from .lognormal import LogNormal
from .multivariate_normal import MultivariateNormal
from .normal import Normal
from .poisson import Poisson
from .uniform import Uniform

__all__ = ["kl_divergence", "register_kl"]

_REGISTRY = {}


def register_kl(cls_p, cls_q):
    if not issubclass(cls_p, Distribution) or not issubclass(cls_q, Distribution):
        raise TypeError("cls_p and cls_q must be Distribution subclasses")

    def decorator(fn):
        _REGISTRY[(cls_p, cls_q)] = fn
        return fn

    return decorator


def _dispatch(type_p, type_q):
    matches = [
        (p, q) for (p, q) in _REGISTRY
        if issubclass(type_p, p) and issubclass(type_q, q)
    ]
    if not matches:
        raise NotImplementedError(
            f"No KL(p||q) registered for p={type_p.__name__}, q={type_q.__name__}"
        )

    def specificity(pair):
        p, q = pair
        return (type_p.__mro__.index(p), type_q.__mro__.index(q))

    return _REGISTRY[min(matches, key=specificity)]


def kl_divergence(p, q):
    return _dispatch(type(p), type(q))(p, q)


# -- pairwise rules ---------------------------------------------------------

_kl_normal = dprim(
    "kl_normal_normal",
    lambda lp, sp, lq, sq: jnp.log(sq / sp)
    + (sp**2 + (lp - lq) ** 2) / (2.0 * sq**2)
    - 0.5,
)
_kl_bern = dprim(
    "kl_bern_bern",
    lambda p, q: p * (jnp.log(p) - jnp.log(q))
    + (1.0 - p) * (jnp.log1p(-p) - jnp.log1p(-q)),
)
_kl_beta = dprim(
    "kl_beta_beta",
    lambda a1, b1, a2, b2: (
        jax.scipy.special.gammaln(a2)
        + jax.scipy.special.gammaln(b2)
        - jax.scipy.special.gammaln(a2 + b2)
    )
    - (
        jax.scipy.special.gammaln(a1)
        + jax.scipy.special.gammaln(b1)
        - jax.scipy.special.gammaln(a1 + b1)
    )
    + (a1 - a2) * jax.scipy.special.digamma(a1)
    + (b1 - b2) * jax.scipy.special.digamma(b1)
    + (a2 - a1 + b2 - b1) * jax.scipy.special.digamma(a1 + b1),
)


def _kl_dirichlet_fwd(c1, c2):
    s1 = jnp.sum(c1, axis=-1)
    return (
        jax.scipy.special.gammaln(s1)
        - jax.scipy.special.gammaln(jnp.sum(c2, axis=-1))
        - jnp.sum(jax.scipy.special.gammaln(c1), axis=-1)
        + jnp.sum(jax.scipy.special.gammaln(c2), axis=-1)
        + jnp.sum(
            (c1 - c2)
            * (jax.scipy.special.digamma(c1) - jax.scipy.special.digamma(s1)[..., None]),
            axis=-1,
        )
    )


_kl_dirichlet = dprim("kl_dirichlet", _kl_dirichlet_fwd)
_kl_cauchy = dprim(
    "kl_cauchy_cauchy",
    lambda lp, sp, lq, sq: jnp.log(((sp + sq) ** 2 + (lp - lq) ** 2) / (4.0 * sp * sq)),
)
_kl_uniform = dprim(
    "kl_uniform_uniform",
    lambda lo_p, hi_p, lo_q, hi_q: jnp.where(
        (lo_q <= lo_p) & (hi_p <= hi_q),
        jnp.log((hi_q - lo_q) / (hi_p - lo_p)),
        jnp.inf,
    ),
)
_kl_laplace = dprim(
    "kl_laplace_laplace",
    lambda lp, sp, lq, sq: jnp.log(sq / sp)
    + jnp.abs(lp - lq) / sq
    + sp / sq * jnp.exp(-jnp.abs(lp - lq) / sp)
    - 1.0,
)
_kl_geometric = dprim(
    "kl_geometric",
    lambda pp, pq: jnp.log(pp / pq)
    + (1.0 - pp) / pp * (jnp.log1p(-pp) - jnp.log1p(-pq)),
)
_kl_exponential = dprim(
    "kl_exponential",
    lambda rp, rq: jnp.log(rp / rq) + rq / rp - 1.0,
)
_kl_gamma = dprim(
    "kl_gamma_gamma",
    lambda ap, bp, aq, bq: (ap - aq) * jax.scipy.special.digamma(ap)
    - jax.scipy.special.gammaln(ap)
    + jax.scipy.special.gammaln(aq)
    + aq * (jnp.log(bp) - jnp.log(bq))
    + ap * (bq - bp) / bp,
)
_kl_poisson = dprim(
    "kl_poisson",
    lambda rp, rq: rp * (jnp.log(rp) - jnp.log(rq)) - rp + rq,
)
_kl_binomial = dprim(
    "kl_binomial",
    lambda n, pp, pq: n
    * (
        pp * (jnp.log(pp) - jnp.log(pq))
        + (1.0 - pp) * (jnp.log1p(-pp) - jnp.log1p(-pq))
    ),
)


def _kl_mvn_fwd(lp, tp, lq, tq):
    k = lp.shape[-1]
    half_logdet_p = jnp.sum(jnp.log(jnp.diagonal(tp, axis1=-2, axis2=-1)), axis=-1)
    half_logdet_q = jnp.sum(jnp.log(jnp.diagonal(tq, axis1=-2, axis2=-1)), axis=-1)
    m = jax.scipy.linalg.solve_triangular(tq, tp, lower=True)
    trace = jnp.sum(m * m, axis=(-2, -1))
    diff = jax.scipy.linalg.solve_triangular(tq, (lq - lp)[..., None], lower=True)[..., 0]
    maha = jnp.sum(diff * diff, axis=-1)
    return half_logdet_q - half_logdet_p + 0.5 * (trace + maha - k)


_kl_mvn = dprim("kl_mvn", _kl_mvn_fwd)


@register_kl(Bernoulli, Bernoulli)
def _bern_bern(p, q):
    return _kl_bern(p.probs, q.probs)


@register_kl(Beta, Beta)
def _beta_beta(p, q):
    return _kl_beta(p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Binomial, Binomial)
def _binom_binom(p, q):
    import numpy as np

    np_, nq_ = np.asarray(p.total_count._value), np.asarray(q.total_count._value)
    if np.all(np_ == nq_):
        return _kl_binomial(p.total_count, p.probs, q.probs)
    if np.all(np_ > nq_):
        # support(p) ⊄ support(q) → divergence is infinite
        from ..ops.creation import full

        return full(list(np.broadcast_shapes(np_.shape, nq_.shape)) or [1], float("inf"))
    raise NotImplementedError(
        "KL between Binomials with p.total_count < q.total_count is not implemented"
    )


@register_kl(Dirichlet, Dirichlet)
def _dir_dir(p, q):
    return _kl_dirichlet(p.concentration, q.concentration)


@register_kl(Categorical, Categorical)
def _cat_cat(p, q):
    return p.kl_divergence(q)


@register_kl(Cauchy, Cauchy)
def _cauchy_cauchy(p, q):
    return _kl_cauchy(p.loc, p.scale, q.loc, q.scale)


@register_kl(ContinuousBernoulli, ContinuousBernoulli)
def _cb_cb(p, q):
    from .continuous_bernoulli import Tensor_log_norm
    from ..ops.math import log

    logit_p = log(p.probs / (1.0 - p.probs))
    logit_q = log(q.probs / (1.0 - q.probs))
    return (
        Tensor_log_norm(p.probs, p._lims)
        - Tensor_log_norm(q.probs, q._lims)
        + p.mean * (logit_p - logit_q)
        + log(1.0 - p.probs)
        - log(1.0 - q.probs)
    )


@register_kl(Normal, Normal)
def _normal_normal(p, q):
    return _kl_normal(p.loc, p.scale, q.loc, q.scale)


@register_kl(MultivariateNormal, MultivariateNormal)
def _mvn_mvn(p, q):
    return _kl_mvn(p.loc, p.scale_tril, q.loc, q.scale_tril)


@register_kl(Uniform, Uniform)
def _uniform_uniform(p, q):
    return _kl_uniform(p.low, p.high, q.low, q.high)


@register_kl(Laplace, Laplace)
def _laplace_laplace(p, q):
    return _kl_laplace(p.loc, p.scale, q.loc, q.scale)


@register_kl(Geometric, Geometric)
def _geom_geom(p, q):
    return _kl_geometric(p.probs, q.probs)


@register_kl(Exponential, Exponential)
def _exp_exp(p, q):
    return _kl_exponential(p.rate, q.rate)


@register_kl(Gamma, Gamma)
def _gamma_gamma(p, q):
    return _kl_gamma(p.concentration, p.rate, q.concentration, q.rate)


@register_kl(LogNormal, LogNormal)
def _lognormal_lognormal(p, q):
    return _kl_normal(p.loc, p.scale, q.loc, q.scale)


@register_kl(Poisson, Poisson)
def _poisson_poisson(p, q):
    return _kl_poisson(p.rate, q.rate)


@register_kl(ExponentialFamily, ExponentialFamily)
def _expfamily_expfamily(p, q):
    """Bregman divergence of the log-normalizer (reference kl.py:242)."""
    if type(p) is not type(q):
        raise NotImplementedError(
            "KL between different exponential-family types is not implemented; "
            "register an explicit rule."
        )
    p_nat = [t._value for t in p._natural_parameters]
    q_nat = [t._value for t in q._natural_parameters]

    def log_norm(*arrays):
        out = p._log_normalizer(*[Tensor._from_value(a) for a in arrays])
        return out._value.sum(), out._value

    grads, lognorm_p = jax.grad(log_norm, argnums=tuple(range(len(p_nat))), has_aux=True)(
        *p_nat
    )
    lognorm_q = p._log_normalizer(
        *[Tensor._from_value(a) for a in q_nat]
    )._value
    kl = lognorm_q - lognorm_p
    for gp, pn, qn in zip(grads, p_nat, q_nat):
        term = gp * (qn - pn)
        # sum event dims if natural params carry them
        extra = term.ndim - kl.ndim
        if extra > 0:
            term = term.sum(axis=tuple(range(term.ndim - extra, term.ndim)))
        kl = kl - term
    return Tensor._from_value(kl)
