"""Categorical distribution (reference: python/paddle/distribution/categorical.py).

Paddle convention: ``logits`` are unnormalized non-negative weights,
normalized by their sum (categorical.py:146-147), NOT softmax logits.
"""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution

_cat_sample = dprim(
    "cat_sample",
    lambda key, probs, *, shape: jax.random.categorical(
        key, jnp.log(probs), axis=-1, shape=shape
    ),
    nondiff=True,
)
_cat_entropy = dprim(
    "cat_entropy",
    lambda probs: -jnp.sum(jax.scipy.special.xlogy(probs, probs), axis=-1),
)
_cat_kl = dprim(
    "cat_kl",
    lambda p, q: jnp.sum(
        p * (jnp.log(p) - jnp.log(q)), axis=-1
    ),
)
def _cat_gather_fwd(probs, idx):
    idx = idx.astype(jnp.int64)
    if probs.ndim == 1:
        return probs[idx]
    # idx: sample_shape + batch_shape, probs: batch_shape + (K,) — broadcast
    # probs over the leading sample dims before gathering along categories
    extra = idx.ndim - (probs.ndim - 1)
    if extra > 0:
        probs = jnp.broadcast_to(probs, idx.shape[:extra] + probs.shape)
    return jnp.take_along_axis(probs, idx[..., None], axis=-1)[..., 0]


_cat_gather = dprim("cat_gather", _cat_gather_fwd)


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        (self.logits,) = broadcast_params(logits)
        s = self.logits.sum(axis=-1, keepdim=True)
        self._prob_t = self.logits / s
        super().__init__(tuple(self.logits.shape[:-1]))

    def sample(self, shape=()):
        full = to_shape_tuple(shape) + self.batch_shape
        return _cat_sample(key_tensor(), self._prob_t, shape=full)

    def entropy(self):
        return _cat_entropy(self._prob_t)

    def kl_divergence(self, other):
        return _cat_kl(self._prob_t, other._prob_t)

    def probs(self, value):
        return _cat_gather(self._prob_t, ensure_tensor(value))

    def log_prob(self, value):
        from ..ops.math import log

        return log(self.probs(value))
