"""Laplace distribution (reference: python/paddle/distribution/laplace.py)."""
from __future__ import annotations

import math

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution

_laplace_noise = dprim(
    "laplace_noise",
    lambda key, *, shape, dtype: jax.random.uniform(
        key, shape, jnp.dtype(dtype), -0.5 + jnp.finfo(jnp.dtype(dtype)).tiny, 0.5
    ),
    nondiff=True,
)
_laplace_log_prob = dprim(
    "laplace_log_prob",
    lambda value, loc, scale: -jnp.abs(value - loc) / scale
    - jnp.log(2.0 * scale),
)
_laplace_cdf = dprim(
    "laplace_cdf",
    lambda value, loc, scale: 0.5
    - 0.5 * jnp.sign(value - loc) * jnp.expm1(-jnp.abs(value - loc) / scale),
)
_laplace_icdf = dprim(
    "laplace_icdf",
    lambda p, loc, scale: loc
    - scale * jnp.sign(p - 0.5) * jnp.log1p(-2.0 * jnp.abs(p - 0.5)),
)
_laplace_from_u = dprim(
    "laplace_from_u",
    lambda u, loc, scale: loc - scale * jnp.sign(u) * jnp.log1p(-2.0 * jnp.abs(u)),
)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = broadcast_params(loc, scale)
        super().__init__(tuple(self.loc.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2.0 * self.scale * self.scale

    @property
    def stddev(self):
        return math.sqrt(2.0) * self.scale

    def rsample(self, shape=()):
        import numpy as np

        full = to_shape_tuple(shape) + self.batch_shape
        u = _laplace_noise(key_tensor(), shape=full, dtype=np.dtype(self.loc.dtype).name)
        return _laplace_from_u(u, self.loc, self.scale)

    def log_prob(self, value):
        return _laplace_log_prob(ensure_tensor(value), self.loc, self.scale)

    def entropy(self):
        from ..ops.math import log

        return 1.0 + log(2.0 * self.scale)

    def cdf(self, value):
        return _laplace_cdf(ensure_tensor(value), self.loc, self.scale)

    def icdf(self, value):
        return _laplace_icdf(ensure_tensor(value), self.loc, self.scale)
