"""LKJ distribution over Cholesky factors of correlation matrices.

Reference: python/paddle/distribution/lkj_cholesky.py — onion and cvine
samplers from Lewandowski, Kurowicka & Joe (2009), log_prob with the
multivariate-gamma normalizer. Implemented here as fully vectorized jnp
samplers (scatter into tril indices instead of the reference's reshape
gymnastics)."""
from __future__ import annotations

import math

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution


def _mvlgamma(a, p):
    j = jnp.arange(1, p + 1, dtype=a.dtype if hasattr(a, "dtype") else None)
    return p * (p - 1) / 4.0 * math.log(math.pi) + jnp.sum(
        jax.scipy.special.gammaln(a[..., None] + (1.0 - j) / 2.0), axis=-1
    )


def _onion_fwd(key, conc, *, dim, shape):
    k1, k2 = jax.random.split(key)
    batch = shape + conc.shape
    dt = conc.dtype
    # per-row beta parameters (reference lkj_cholesky.py:205-218)
    marginal = conc + 0.5 * (dim - 2)
    offset = jnp.concatenate([jnp.zeros(1, dt), jnp.arange(dim - 1, dtype=dt)])
    b1 = offset + 0.5                                    # (dim,)
    b0 = marginal[..., None] - 0.5 * offset              # (batch..., dim)
    y = jax.random.beta(k1, b1, b0, shape + b0.shape, dtype=dt)[..., None]  # (..., dim, 1)
    u_normal = jnp.tril(
        jax.random.normal(k2, batch + (dim, dim), dt), -1
    )
    norm = jnp.linalg.norm(u_normal, axis=-1, keepdims=True)
    u_hyper = u_normal / jnp.where(norm == 0.0, jnp.asarray(1.0, dt), norm)
    u_hyper = u_hyper.at[..., 0, :].set(jnp.asarray(0.0, dt))
    w = jnp.sqrt(y) * u_hyper
    diag = jnp.sqrt(jnp.clip(1.0 - jnp.sum(w * w, axis=-1), jnp.finfo(dt).tiny))
    return w + jnp.eye(dim, dtype=dt) * diag[..., None]


def _cvine_fwd(key, conc, *, dim, shape):
    dt = conc.dtype
    batch = shape + conc.shape
    marginal = conc + 0.5 * (dim - 2)
    rows, cols = jnp.tril_indices(dim - 1)
    # beta concentration per partial correlation (reference :219-224)
    bc = marginal[..., None] - 0.5 * cols.astype(dt)     # (batch..., T)
    p = jax.random.beta(key, bc, bc, shape + bc.shape, dtype=dt)
    partial = 2.0 * p - 1.0
    eps = jnp.finfo(dt).tiny
    partial = jnp.clip(
        partial, jnp.asarray(-1.0 + eps, dt), jnp.asarray(1.0 - eps, dt)
    )
    r = jnp.zeros(batch + (dim, dim), dt).at[..., rows + 1, cols].set(partial)
    z1m_sqrt = jnp.cumprod(jnp.sqrt(1.0 - r * r), axis=-1)
    shifted = jnp.concatenate(
        [jnp.ones(batch + (dim, 1), dt), z1m_sqrt[..., :-1]], axis=-1
    )
    return (r + jnp.eye(dim, dtype=dt)) * shifted


def _lkj_log_prob_fwd(value, conc, *, dim):
    dt = conc.dtype
    diag = jnp.diagonal(value, axis1=-2, axis2=-1)[..., 1:]
    order = 2.0 * (conc - 1.0)[..., None] + dim - jnp.arange(2, dim + 1, dtype=dt)
    unnorm = jnp.sum(order * jnp.log(diag), axis=-1)
    dm1 = dim - 1
    alpha = conc + 0.5 * dm1
    denominator = jax.scipy.special.gammaln(alpha) * dm1
    numerator = _mvlgamma(alpha - 0.5, dm1)
    pi_constant = 0.5 * dm1 * math.log(math.pi)
    return unnorm - (pi_constant + numerator - denominator)


_onion = dprim("lkj_onion", _onion_fwd, nondiff=True)
_cvine = dprim("lkj_cvine", _cvine_fwd, nondiff=True)
_lkj_log_prob = dprim("lkj_log_prob", _lkj_log_prob_fwd)


class LKJCholesky(Distribution):
    def __init__(self, dim, concentration=1.0, sample_method="onion", name=None):
        if int(dim) < 2:
            raise ValueError(f"Expected dim >= 2, got {dim}")
        if sample_method not in ("onion", "cvine"):
            raise ValueError("`sample_method` should be one of 'cvine' or 'onion'.")
        self.dim = int(dim)
        (self.concentration,) = broadcast_params(concentration)
        self.sample_method = sample_method
        super().__init__(tuple(self.concentration.shape), (self.dim, self.dim))

    def sample(self, shape=()):
        fn = _onion if self.sample_method == "onion" else _cvine
        return fn(
            key_tensor(), self.concentration, dim=self.dim, shape=to_shape_tuple(shape)
        )

    def log_prob(self, value):
        return _lkj_log_prob(ensure_tensor(value), self.concentration, dim=self.dim)
