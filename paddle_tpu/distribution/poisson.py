"""Poisson distribution (reference: python/paddle/distribution/poisson.py).

Entropy follows the reference's bounded-support enumeration
(poisson.py:146-200, 30-sigma rule) — data-dependent support size, so the
entropy primitive is registered non-jittable and runs eagerly."""
from __future__ import annotations

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution

_poisson_sample = dprim(
    "poisson_sample",
    lambda key, rate, *, shape: jax.random.poisson(key, rate, shape).astype(rate.dtype),
    nondiff=True,
)
_poisson_log_prob = dprim(
    "poisson_log_prob",
    lambda value, rate: jax.scipy.special.xlogy(value, rate)
    - rate
    - jax.scipy.special.gammaln(value + 1.0),
)


def _poisson_entropy_fwd(rate):
    r = jnp.asarray(rate)
    s_max = jnp.sqrt(jnp.maximum(jnp.max(r), 1.0))
    upper = int(jnp.max(r + 30.0 * s_max))
    values = jnp.arange(0, max(upper, 1), dtype=r.dtype).reshape((-1,) + (1,) * r.ndim)
    lp = jax.scipy.special.xlogy(values, r) - r - jax.scipy.special.gammaln(values + 1.0)
    ent = -jnp.sum(jnp.exp(lp) * lp, axis=0)
    return jnp.where(r != 0.0, ent, 0.0)


_poisson_entropy = dprim("poisson_entropy", _poisson_entropy_fwd, jittable=False)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        (self.rate,) = broadcast_params(rate)
        super().__init__(tuple(self.rate.shape))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        full = to_shape_tuple(shape) + self.batch_shape
        return _poisson_sample(key_tensor(), self.rate, shape=full)

    def log_prob(self, value):
        return _poisson_log_prob(ensure_tensor(value), self.rate)

    def entropy(self):
        return _poisson_entropy(self.rate)
