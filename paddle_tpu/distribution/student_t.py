"""Student's t distribution (reference: python/paddle/distribution/student_t.py)."""
from __future__ import annotations

import math

from ._ddefs import broadcast_params, dprim, ensure_tensor, jax, jnp, key_tensor, to_shape_tuple
from .distribution import Distribution

_t_std = dprim(
    "t_std",
    lambda key, df, *, shape: jax.random.t(key, df, shape, dtype=df.dtype),
    nondiff=True,
)
_t_log_prob = dprim(
    "t_log_prob",
    lambda value, df, loc, scale: jax.scipy.special.gammaln((df + 1.0) / 2.0)
    - jax.scipy.special.gammaln(df / 2.0)
    - 0.5 * jnp.log(df * math.pi)
    - jnp.log(scale)
    - (df + 1.0) / 2.0 * jnp.log1p(((value - loc) / scale) ** 2 / df),
)


def _t_entropy_fwd(df, scale):
    half = (df + 1.0) / 2.0
    return (
        half * (jax.scipy.special.digamma(half) - jax.scipy.special.digamma(df / 2.0))
        + 0.5 * jnp.log(df)
        + jax.scipy.special.gammaln(df / 2.0)
        + jax.scipy.special.gammaln(0.5)
        - jax.scipy.special.gammaln(half)
        + jnp.log(scale)
    )


_t_entropy = dprim("t_entropy", _t_entropy_fwd)
_t_variance = dprim(
    "t_variance",
    lambda df, scale: jnp.where(
        df > 2.0,
        scale * scale * df / jnp.where(df > 2.0, df - 2.0, 1.0),
        jnp.where(df > 1.0, jnp.inf, jnp.nan),
    ),
)


class StudentT(Distribution):
    def __init__(self, df, loc, scale, name=None):
        self.df, self.loc, self.scale = broadcast_params(df, loc, scale)
        super().__init__(tuple(self.df.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        # undefined moments: inf for 1 < df <= 2, nan for df <= 1
        return _t_variance(self.df, self.scale)

    def sample(self, shape=()):
        full = to_shape_tuple(shape) + self.batch_shape
        z = _t_std(key_tensor(), self.df, shape=full)
        from .. import autograd

        with autograd.no_grad():
            return self.loc + self.scale * z

    def log_prob(self, value):
        return _t_log_prob(ensure_tensor(value), self.df, self.loc, self.scale)

    def entropy(self):
        return _t_entropy(self.df, self.scale)
