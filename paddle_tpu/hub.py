"""Model hub (reference: python/paddle/hub.py — list/help/load over github/
gitee/local sources via a repo's hubconf.py).

The local source is fully supported; remote sources raise a clear error in
this zero-egress environment."""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"
_VAR_DEPS = "dependencies"


def _import_hubconf(directory):
    path = os.path.join(directory, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"hubconf.py not found in {directory}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    module = importlib.util.module_from_spec(spec)
    sys.path.insert(0, directory)
    try:
        spec.loader.exec_module(module)
    finally:
        sys.path.remove(directory)
    deps = getattr(module, _VAR_DEPS, [])
    missing = [d for d in deps if importlib.util.find_spec(d) is None]
    if missing:
        raise RuntimeError(f"Missing dependencies required by hubconf: {missing}")
    return module

def _resolve(repo_dir, source):
    if source not in ("local", "github", "gitee"):
        raise ValueError(
            f"Unknown source: {source}. Valid sources: 'github', 'gitee', 'local'."
        )
    if source in ("github", "gitee"):
        raise RuntimeError(
            f"source={source!r} requires network access, which is unavailable; "
            "clone the repository and use source='local'."
        )
    return _import_hubconf(os.path.expanduser(repo_dir))


def list(repo_dir, source="github", force_reload=False):
    """List callable entry points exposed by the repo's hubconf.py."""
    module = _resolve(repo_dir, source)
    return [
        name
        for name, obj in vars(module).items()
        if callable(obj) and not name.startswith("_")
    ]


def help(repo_dir, model, source="github", force_reload=False):
    """Return the docstring of a hub entry point."""
    module = _resolve(repo_dir, source)
    fn = getattr(module, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"Cannot find callable {model} in hubconf")
    return fn.__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Build a model from a hub entry point."""
    module = _resolve(repo_dir, source)
    fn = getattr(module, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"Cannot find callable {model} in hubconf")
    return fn(**kwargs)
