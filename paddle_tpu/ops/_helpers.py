"""Op-layer helpers: primitive definition + argument normalization.

This is the analog of the reference codegen pipelines (SURVEY §2.2): where
the reference generates C++ APIs / Python bindings / GradNodes from ops.yaml
(phi/api/generator/api_gen.py, eager_gen.py), here each op is one
``defprim`` registration (pure jax forward, optional explicit VJP) plus a
thin Python wrapper that normalizes arguments — codegen collapses into
first-class functions because jax IS the kernel language.
"""
from __future__ import annotations

import numbers
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.dtype import convert_dtype, is_floating_point
from ..core.tensor import Parameter, Tensor, apply

__all__ = [
    "defprim",
    "apply",
    "ensure_tensor",
    "binary_args",
    "scalar_tensor",
    "axis_tuple",
    "Tensor",
]


def defprim(name: str, forward, **kwargs):
    """Register a primitive; returns a raw caller fn(*tensors, **static)."""
    dispatch.register_primitive(name, forward, **kwargs)

    def call(*tensors, **static):
        return apply(name, *tensors, **static)

    call.__name__ = name
    return call


def ensure_tensor(x, dtype=None) -> Tensor:
    if isinstance(x, Tensor):
        return x if dtype is None else _maybe_cast(x, dtype)
    dt = convert_dtype(dtype)
    if isinstance(x, (numbers.Number, np.bool_)) and dt is None:
        # weak scalar: default int64/float32/bool like paddle's to_tensor
        if isinstance(x, (bool, np.bool_)):
            dt = np.dtype("bool")
        elif isinstance(x, numbers.Integral):
            dt = np.dtype("int64")
        else:
            dt = np.dtype("float32")
    return Tensor._from_value(jnp.asarray(x, dtype=dt))


def _maybe_cast(t: Tensor, dtype):
    dt = convert_dtype(dtype)
    if np.dtype(t.dtype) == dt:
        return t
    from .math import cast

    return cast(t, dt)


def scalar_tensor(scalar, ref_dtype) -> Tensor:
    """Convert a python scalar to a Tensor adopting the peer tensor's dtype
    when compatible (paddle math_op_patch scalar promotion)."""
    ref = np.dtype(ref_dtype)
    if isinstance(scalar, (bool, np.bool_)):
        dt = ref if ref == np.dtype(bool) else np.dtype(bool)
    elif isinstance(scalar, numbers.Integral):
        dt = ref if ref.kind in "iuf" or is_floating_point(ref) else np.dtype("int64")
    else:  # float/complex scalar
        if is_floating_point(ref) or ref.kind in "fc":
            dt = ref
        else:
            dt = np.dtype("float32")
    return Tensor._from_value(jnp.asarray(scalar, dtype=dt))


def binary_args(x, y):
    """Normalize (x, y) for a broadcasting binary op: Tensors of a common
    dtype (numpy-lattice promotion, matching paddle's implicit promotion)."""
    xt = isinstance(x, Tensor)
    yt = isinstance(y, Tensor)
    if xt and not yt:
        if isinstance(y, numbers.Number):
            y = scalar_tensor(y, x.dtype)
        else:
            y = ensure_tensor(y)
    elif yt and not xt:
        if isinstance(x, numbers.Number):
            x = scalar_tensor(x, y.dtype)
        else:
            x = ensure_tensor(x)
    elif not xt and not yt:
        x, y = ensure_tensor(x), ensure_tensor(y)
    if np.dtype(x.dtype) != np.dtype(y.dtype):
        common = jnp.promote_types(x.dtype, y.dtype)
        x = _maybe_cast(x, common)
        y = _maybe_cast(y, common)
    return x, y


def axis_tuple(axis, ndim: int) -> Optional[tuple]:
    """Normalize an axis spec to a sorted tuple of non-negative ints."""
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (int, np.integer)):
        axis = (int(axis),)
    axis = tuple(int(a) % ndim if ndim else int(a) for a in axis)
    return tuple(sorted(set(axis)))
