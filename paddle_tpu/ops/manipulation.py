"""Shape manipulation + indexing ops.

Reference surface: python/paddle/tensor/manipulation.py (reshape, transpose,
concat, split, gather, scatter, tile, expand, flip, roll, pad, ...) and the
stride/view kernels (phi/kernels/stride/). jax arrays are immutable, so
"views" are value-level ops XLA turns into free layout changes; __setitem__
is functionalized through scatter (the reference's set_value op).
"""
from __future__ import annotations

import builtins
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch
from ..core.tensor import Tensor, apply
from ._helpers import axis_tuple, binary_args, defprim, ensure_tensor

__all__ = [
    "reshape", "reshape_", "transpose", "flatten", "squeeze", "unsqueeze",
    "squeeze_", "unsqueeze_", "concat", "stack", "split", "chunk", "unbind",
    "unstack",
    "tile", "expand", "expand_as", "broadcast_to", "flip", "rot90", "roll",
    "gather", "gather_nd", "scatter", "scatter_nd", "scatter_nd_add", "index_select",
    "index_sample", "index_add", "index_put", "take_along_axis",
    "put_along_axis", "masked_select", "masked_fill", "where", "nonzero",
    "topk", "sort", "argsort", "argmax", "argmin", "unique", "unique_consecutive",
    "numel", "shape", "pad", "strided_slice", "slice", "crop", "tensordot",
    "moveaxis", "swapaxes", "as_complex", "as_real", "repeat_interleave",
    "diagonal", "t", "atleast_1d", "atleast_2d", "atleast_3d", "view",
    "tensor_split", "hsplit", "vsplit", "dsplit", "diag_embed",
]


# ---------------------------------------------------------------------------
defprim("reshape_p", lambda x, *, shape: jnp.reshape(x, shape))


def _infer_shape(x, shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    out = []
    for s in shape:
        if isinstance(s, Tensor):
            s = int(s.item())
        out.append(int(s))
    # paddle semantics: 0 means "copy this dim from input"
    for i, s in enumerate(out):
        if s == 0 and i < x.ndim:
            out[i] = x.shape[i]
    return tuple(out)


def reshape(x, shape, name=None):
    x = ensure_tensor(x)
    return apply("reshape_p", x, shape=_infer_shape(x, shape))


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._replace_value(out._value)
    x._node, x._out_slot, x.stop_gradient = out._node, out._out_slot, out.stop_gradient
    return x


view = reshape


defprim("transpose_p", lambda x, *, perm: jnp.transpose(x, perm))


def transpose(x, perm, name=None):
    x = ensure_tensor(x)
    perm = tuple(int(p) % x.ndim for p in perm)
    return apply("transpose_p", x, perm=perm)


def t(x, name=None):
    x = ensure_tensor(x)
    if x.ndim < 2:
        return x
    return transpose(x, list(range(x.ndim - 2)) + [x.ndim - 1, x.ndim - 2])


def moveaxis(x, source, destination, name=None):
    x = ensure_tensor(x)
    return apply(
        "moveaxis_p",
        x,
        source=tuple(np.atleast_1d(source).tolist()),
        destination=tuple(np.atleast_1d(destination).tolist()),
    )


defprim(
    "moveaxis_p", lambda x, *, source, destination: jnp.moveaxis(x, source, destination)
)


def swapaxes(x, axis1, axis2, name=None):
    x = ensure_tensor(x)
    perm = list(range(x.ndim))
    a1, a2 = axis1 % x.ndim, axis2 % x.ndim
    perm[a1], perm[a2] = perm[a2], perm[a1]
    return transpose(x, perm)


swapdims = swapaxes


defprim(
    "flatten_p",
    lambda x, *, start, stop: jnp.reshape(
        x,
        x.shape[:start]
        + (int(np.prod(x.shape[start : stop + 1]) or 1),)
        + x.shape[stop + 1 :],
    ),
)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    if x.ndim == 0:
        return reshape(x, [1])
    start = start_axis % x.ndim
    stop = stop_axis % x.ndim
    return apply("flatten_p", x, start=start, stop=stop)


def squeeze(x, axis=None, name=None):
    x = ensure_tensor(x)
    if axis is None:
        ax = tuple(i for i, s in enumerate(x.shape) if s == 1)
    else:
        ax = axis_tuple(axis, x.ndim)
        ax = tuple(a for a in ax if x.shape[a] == 1)
    return apply("squeeze_p", x, axis=ax)


defprim("squeeze_p", lambda x, *, axis: jnp.squeeze(x, axis) if axis else x)


def unsqueeze(x, axis, name=None):
    x = ensure_tensor(x)
    if isinstance(axis, (int, np.integer)):
        axis = [int(axis)]
    elif isinstance(axis, Tensor):
        axis = [int(a) for a in axis.tolist()]
    ndim_out = x.ndim + len(axis)
    ax = tuple(sorted(int(a) % ndim_out for a in axis))
    return apply("unsqueeze_p", x, axis=ax)


defprim("unsqueeze_p", lambda x, *, axis: jnp.expand_dims(x, axis))


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._replace_value(out._value)
    x._node, x._out_slot, x.stop_gradient = out._node, out._out_slot, out.stop_gradient
    return x


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._replace_value(out._value)
    x._node, x._out_slot, x.stop_gradient = out._node, out._out_slot, out.stop_gradient
    return x


# ---------------------------------------------------------------------------
# concat / stack / split — variadic prims registered per-arity
# ---------------------------------------------------------------------------
def _variadic(base, fn_builder, n, **static):
    name = f"{base}_{n}"
    if name not in dispatch.PRIMITIVES:
        dispatch.register_primitive(name, fn_builder(n))
    return name


def concat(x, axis=0, name=None):
    ts = [ensure_tensor(t) for t in x]
    if len(ts) == 1:
        return ts[0]
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    # promote to common dtype
    common = ts[0].dtype
    for t_ in ts[1:]:
        common = jnp.promote_types(common, t_.dtype)
    from .math import cast

    ts = [cast(t_, common) for t_ in ts]
    name_p = _variadic(
        "concat", lambda n: (lambda *xs, axis: jnp.concatenate(xs, axis=axis)), len(ts)
    )
    return apply(name_p, *ts, axis=int(axis) % ts[0].ndim if ts[0].ndim else 0)


def stack(x, axis=0, name=None):
    ts = [ensure_tensor(t) for t in x]
    name_p = _variadic(
        "stack", lambda n: (lambda *xs, axis: jnp.stack(xs, axis=axis)), len(ts)
    )
    return apply(name_p, *ts, axis=int(axis))


def _split_sections(x, num_or_sections, axis):
    dim = x.shape[axis]
    if isinstance(num_or_sections, (int, np.integer)):
        n = int(num_or_sections)
        size = dim // n
        return [size] * n
    secs = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in num_or_sections]
    if -1 in secs:
        known = sum(s for s in secs if s != -1)
        secs[secs.index(-1)] = dim - known
    return secs


def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = int(axis) % x.ndim
    secs = tuple(_split_sections(x, num_or_sections, axis))
    name_p = f"split_{len(secs)}"
    if name_p not in dispatch.PRIMITIVES:
        n_out = len(secs)

        def fwd(x, *, sections, axis):
            idx = np.cumsum(sections[:-1]).tolist()
            return tuple(jnp.split(x, idx, axis=axis))

        dispatch.register_primitive(name_p, fwd, multi_out=True)
    return list(apply(name_p, x, sections=secs, axis=axis))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def tensor_split(x, num_or_indices, axis=0, name=None):
    x = ensure_tensor(x)
    axis = int(axis) % x.ndim
    dim = x.shape[axis]
    if isinstance(num_or_indices, (int, np.integer)):
        n = int(num_or_indices)
        base, rem = divmod(dim, n)
        secs = [base + (1 if i < rem else 0) for i in range(n)]
    else:
        idx = [0] + list(num_or_indices) + [dim]
        secs = [idx[i + 1] - idx[i] for i in range(len(idx) - 1)]
    return split(x, secs, axis)


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if ensure_tensor(x).ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def unbind(x, axis=0, name=None):
    x = ensure_tensor(x)
    axis = int(axis) % x.ndim
    outs = split(x, x.shape[axis], axis)
    return [squeeze(o, axis) for o in outs]


def unstack(x, axis=0, num=None, name=None):
    """Unpack a tensor into ``num`` slices along ``axis``
    (reference: python/paddle/tensor/manipulation.py unstack)."""
    x = ensure_tensor(x)
    ax = int(axis) % x.ndim
    if num is not None and num != x.shape[ax]:
        raise ValueError(
            f"num({num}) must match the size of axis {axis} ({x.shape[ax]})"
        )
    return unbind(x, ax)


# ---------------------------------------------------------------------------
# broadcast / tile / flip / roll / pad
# ---------------------------------------------------------------------------
defprim("tile_p", lambda x, *, reps: jnp.tile(x, reps))


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.tolist()
    return apply(
        "tile_p", ensure_tensor(x), reps=tuple(int(r) for r in repeat_times)
    )


defprim("broadcast_to_p", lambda x, *, shape: jnp.broadcast_to(x, shape))


def broadcast_to(x, shape, name=None):
    x = ensure_tensor(x)
    return apply("broadcast_to_p", x, shape=_infer_shape(x, shape))


def expand(x, shape, name=None):
    x = ensure_tensor(x)
    shape = _infer_shape(x, shape)
    # paddle expand: -1 keeps dim
    full = []
    offset = len(shape) - x.ndim
    for i, s in enumerate(shape):
        if s == -1:
            full.append(x.shape[i - offset] if i >= offset else 1)
        else:
            full.append(s)
    return apply("broadcast_to_p", x, shape=tuple(full))


def expand_as(x, y, name=None):
    return expand(x, ensure_tensor(y).shape)


defprim("flip_p", lambda x, *, axis: jnp.flip(x, axis))


def flip(x, axis, name=None):
    x = ensure_tensor(x)
    return apply("flip_p", x, axis=axis_tuple(axis, x.ndim))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90_p", ensure_tensor(x), k=int(k), axes=tuple(axes))


defprim("rot90_p", lambda x, *, k, axes: jnp.rot90(x, k, axes))


defprim("roll_p", lambda x, *, shifts, axis: jnp.roll(x, shifts, axis))


def roll(x, shifts, axis=None, name=None):
    x = ensure_tensor(x)
    if isinstance(shifts, Tensor):
        shifts = shifts.tolist()
    shifts = tuple(np.atleast_1d(shifts).tolist())
    ax = axis_tuple(axis, x.ndim) if axis is not None else None
    if ax is None:
        return apply(
            "roll_flat_p", x, shifts=int(np.sum(shifts)), shape=tuple(x.shape)
        )
    return apply("roll_p", x, shifts=shifts, axis=ax)


defprim(
    "roll_flat_p",
    lambda x, *, shifts, shape: jnp.roll(x.reshape(-1), shifts).reshape(shape),
)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """paddle.nn.functional.pad semantics (nn/functional/common.py:pad)."""
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        # full-form: [before0, after0, before1, after1, ...] paddle uses
        # per-dim pairs in dim order
        widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial form applies to trailing spatial dims (NCHW/NCL/NCDHW)
        n_spatial = len(pad) // 2
        widths = [(0, 0)] * nd
        if data_format.endswith("C"):  # NHWC-style: spatial dims precede C
            spatial_dims = list(range(1, 1 + n_spatial))
        else:
            spatial_dims = list(range(nd - n_spatial, nd))
        # paddle's flat pad list is reversed-last-dim-first like torch
        for i, d in enumerate(reversed(spatial_dims)):
            widths[d] = (pad[2 * i], pad[2 * i + 1])
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    return apply(
        "pad_p", x, widths=tuple(widths), mode=jmode, value=float(value)
    )


def _pad_fwd(x, *, widths, mode, value):
    if mode == "constant":
        return jnp.pad(x, widths, mode=mode, constant_values=value)
    return jnp.pad(x, widths, mode=mode)


defprim("pad_p", _pad_fwd)


# ---------------------------------------------------------------------------
# gather/scatter family
# ---------------------------------------------------------------------------
defprim(
    "gather_p",
    lambda x, index, *, axis: jnp.take(x, index.astype(jnp.int32), axis=axis),
)


def gather(x, index, axis=0, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    if index.ndim == 2 and index.shape[1] == 1:
        index = squeeze(index, 1)
    return apply("gather_p", x, index, axis=int(axis) % x.ndim)


def _gather_nd_fwd(x, index):
    idx = tuple(jnp.moveaxis(index.astype(jnp.int32), -1, 0))
    return x[idx]


defprim("gather_nd_p", _gather_nd_fwd)


def gather_nd(x, index, name=None):
    return apply("gather_nd_p", ensure_tensor(x), ensure_tensor(index))


def _scatter_fwd(x, index, updates, *, overwrite):
    idx = index.astype(jnp.int32)
    if idx.ndim == 2 and idx.shape[-1] == 1:
        idx = idx[:, 0]
    if overwrite:
        return x.at[idx].set(updates)
    # paddle: non-overwrite means zero-out then add (accumulate duplicates)
    zeroed = x.at[idx].set(jnp.zeros_like(updates))
    return zeroed.at[idx].add(updates)


defprim("scatter_p", _scatter_fwd)


def scatter(x, index, updates, overwrite=True, name=None):
    return apply(
        "scatter_p",
        ensure_tensor(x),
        ensure_tensor(index),
        ensure_tensor(updates),
        overwrite=bool(overwrite),
    )


def _scatter_nd_add_fwd(x, index, updates):
    idx = tuple(jnp.moveaxis(index.astype(jnp.int32), -1, 0))
    return x.at[idx].add(updates)


defprim("scatter_nd_add_p", _scatter_nd_add_fwd)


def scatter_nd_add(x, index, updates, name=None):
    return apply(
        "scatter_nd_add_p", ensure_tensor(x), ensure_tensor(index), ensure_tensor(updates)
    )


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros

    updates = ensure_tensor(updates)
    return scatter_nd_add(zeros(shape, updates.dtype), index, updates)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis)


defprim(
    "index_sample_p",
    lambda x, index: jnp.take_along_axis(x, index.astype(jnp.int32), axis=1),
)


def index_sample(x, index):
    return apply("index_sample_p", ensure_tensor(x), ensure_tensor(index))


def _index_add_fwd(x, index, value, *, axis):
    moved = jnp.moveaxis(x, axis, 0)
    vmoved = jnp.moveaxis(value, axis, 0)
    out = moved.at[index.astype(jnp.int32)].add(vmoved)
    return jnp.moveaxis(out, 0, axis)


defprim("index_add_p", _index_add_fwd)


def index_add(x, index, axis, value, name=None):
    return apply(
        "index_add_p", ensure_tensor(x), ensure_tensor(index), ensure_tensor(value),
        axis=int(axis),
    )


def _index_put_fwd(x, v, *index_arrays, accumulate):
    idx = tuple(a.astype(jnp.int32) for a in index_arrays)
    if accumulate:
        return x.at[idx].add(v.astype(x.dtype))
    return x.at[idx].set(v.astype(x.dtype))


defprim("index_put_p", _index_put_fwd)


def index_put(x, indices, value, accumulate=False, name=None):
    x = ensure_tensor(x)
    v = ensure_tensor(value, dtype=x.dtype)
    idx = [ensure_tensor(i) for i in indices]
    return apply("index_put_p", x, v, *idx, accumulate=bool(accumulate))


def index_put_(x, indices, value, accumulate=False, name=None):
    out = index_put(x, indices, value, accumulate)
    x._replace_value(out._value)
    x._node, x._out_slot, x.stop_gradient = out._node, out._out_slot, out.stop_gradient
    return x


defprim(
    "take_along_axis_p",
    lambda x, index, *, axis: jnp.take_along_axis(
        x, index.astype(jnp.int32), axis=axis
    ),
)


def take_along_axis(x, indices, axis, broadcast=True, name=None):
    x, indices = ensure_tensor(x), ensure_tensor(indices)
    return apply("take_along_axis_p", x, indices, axis=int(axis) % x.ndim)


def _put_along_axis_fwd(x, index, value, *, axis, reduce):
    idx = index.astype(jnp.int32)
    value = jnp.broadcast_to(value, idx.shape).astype(x.dtype)
    dims = list(range(x.ndim))
    ii = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
    full_idx = tuple(idx if d == axis else ii[d] for d in dims)
    if reduce == "assign":
        return x.at[full_idx].set(value)
    if reduce == "add":
        return x.at[full_idx].add(value)
    if reduce == "multiply":
        return x.at[full_idx].multiply(value)
    raise ValueError(reduce)


defprim("put_along_axis_p", _put_along_axis_fwd)


def put_along_axis(x, indices, values, axis, reduce="assign", name=None, **kw):
    x = ensure_tensor(x)
    v = ensure_tensor(values, dtype=x.dtype)
    return apply(
        "put_along_axis_p", x, ensure_tensor(indices), v,
        axis=int(axis) % x.ndim, reduce=reduce,
    )


def masked_fill(x, mask, value, name=None):
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    v = ensure_tensor(value, dtype=x.dtype)
    return apply("masked_fill_p", x, mask, v)


defprim(
    "masked_fill_p",
    lambda x, mask, v: jnp.where(mask, v.astype(x.dtype), x),
)


def masked_select(x, mask, name=None):
    """Dynamic-shape op: returns a 1-D tensor of selected elements. Executes
    eagerly un-jitted (XLA needs static shapes; reference equivalent is a
    dynamic-output kernel)."""
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    xv, mv = np.asarray(x._value), np.asarray(mask._value)
    mv = np.broadcast_to(mv, xv.shape)
    return Tensor._from_value(jnp.asarray(xv[mv]))


def where(condition, x=None, y=None, name=None):
    condition = ensure_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    x, y = binary_args(x, y)
    return apply("where_p", condition, x, y)


defprim(
    "where_p",
    lambda c, x, y: jnp.where(c, x, y),
    vjp=lambda g, saved, **kw: (
        None,
        jnp.where(saved[0], g[0], 0).reshape(saved[1]) if False else _where_gx(g[0], saved),
        _where_gy(g[0], saved),
    ),
    save=lambda ins, outs: (ins[0], ins[1].shape, ins[2].shape),
)


def _where_gx(g, saved):
    from .math import _unbcast

    c, xs, ys = saved
    return _unbcast(jnp.where(c, g, 0), xs)


def _where_gy(g, saved):
    from .math import _unbcast

    c, xs, ys = saved
    return _unbcast(jnp.where(c, 0, g), ys)


def nonzero(x, as_tuple=False):
    """Dynamic-shape op — eager only (see masked_select note)."""
    x = ensure_tensor(x)
    nz = np.nonzero(np.asarray(x._value))
    if as_tuple:
        return tuple(Tensor._from_value(jnp.asarray(i[:, None])) for i in nz)
    return Tensor._from_value(jnp.asarray(np.stack(nz, axis=1)))


# ---------------------------------------------------------------------------
# search / sort
# ---------------------------------------------------------------------------
defprim(
    "topk_p",
    lambda x, *, k, axis, largest: _topk_impl(x, k, axis, largest),
    multi_out=True,
)


def _topk_impl(x, k, axis, largest):
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(xm if largest else -xm, k)
    if not largest:
        vals = -vals
    return (
        jnp.moveaxis(vals, -1, axis),
        jnp.moveaxis(idx.astype(jnp.int64), -1, axis),
    )


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())
    return apply(
        "topk_p", x, k=int(k), axis=int(axis) % x.ndim, largest=bool(largest)
    )


defprim(
    "sort_p",
    lambda x, *, axis, descending: (
        -jnp.sort(-x, axis=axis) if descending else jnp.sort(x, axis=axis)
    ),
)
defprim(
    "argsort_p",
    lambda x, *, axis, descending: (
        jnp.argsort(-x, axis=axis) if descending else jnp.argsort(x, axis=axis)
    ).astype(jnp.int64),
    nondiff=True,
)


def sort(x, axis=-1, descending=False, name=None):
    x = ensure_tensor(x)
    return apply("sort_p", x, axis=int(axis) % x.ndim, descending=bool(descending))


def argsort(x, axis=-1, descending=False, name=None):
    x = ensure_tensor(x)
    return apply("argsort_p", x, axis=int(axis) % x.ndim, descending=bool(descending))


defprim(
    "argmax_p",
    lambda x, *, axis, keepdim, dtype: jnp.argmax(x, axis=axis, keepdims=keepdim).astype(
        jnp.dtype(dtype)
    ),
    nondiff=True,
)
defprim(
    "argmin_p",
    lambda x, *, axis, keepdim, dtype: jnp.argmin(x, axis=axis, keepdims=keepdim).astype(
        jnp.dtype(dtype)
    ),
    nondiff=True,
)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = ensure_tensor(x)
    return apply(
        "argmax_p", x, axis=int(axis) if axis is not None else None,
        keepdim=bool(keepdim), dtype=np.dtype(dtype).name,
    )


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = ensure_tensor(x)
    return apply(
        "argmin_p", x, axis=int(axis) if axis is not None else None,
        keepdim=bool(keepdim), dtype=np.dtype(dtype).name,
    )


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    """Dynamic-shape op — eager only."""
    x = ensure_tensor(x)
    res = np.unique(
        np.asarray(x._value), return_index=return_index,
        return_inverse=return_inverse, return_counts=return_counts, axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor._from_value(jnp.asarray(res))
    return tuple(Tensor._from_value(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    x = ensure_tensor(x)
    arr = np.asarray(x._value)
    if axis is None:
        arr = arr.reshape(-1)
        keep = np.concatenate([[True], arr[1:] != arr[:-1]])
        out = arr[keep]
        outs = [Tensor._from_value(jnp.asarray(out))]
        if return_inverse:
            inv = np.cumsum(keep) - 1
            outs.append(Tensor._from_value(jnp.asarray(inv)))
        if return_counts:
            idx = np.nonzero(keep)[0]
            counts = np.diff(np.append(idx, arr.size))
            outs.append(Tensor._from_value(jnp.asarray(counts)))
        return outs[0] if len(outs) == 1 else tuple(outs)
    # axis mode: deduplicate consecutive equal SLICES along `axis`
    axis = int(axis) % arr.ndim
    moved = np.moveaxis(arr, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    if flat.shape[0] == 0:
        keep = np.zeros((0,), bool)
    else:
        keep = np.concatenate([[True], (flat[1:] != flat[:-1]).any(axis=1)])
    out = np.moveaxis(moved[keep], 0, axis)
    outs = [Tensor._from_value(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor._from_value(jnp.asarray(inv)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, flat.shape[0]))
        outs.append(Tensor._from_value(jnp.asarray(counts)))
    return outs[0] if len(outs) == 1 else tuple(outs)


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------
def numel(x, name=None):
    return Tensor._from_value(jnp.asarray(ensure_tensor(x).size, jnp.int64))


def shape(x):
    return Tensor._from_value(jnp.asarray(ensure_tensor(x).shape, jnp.int32))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply(
        "diagonal_p", ensure_tensor(x), offset=int(offset),
        axis1=int(axis1), axis2=int(axis2),
    )


defprim(
    "diagonal_p",
    lambda x, *, offset, axis1, axis2: jnp.diagonal(x, offset, axis1, axis2),
)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    x = ensure_tensor(x)
    return apply("diag_embed_p", x, offset=int(offset), dim1=int(dim1), dim2=int(dim2))


def _diag_embed_fwd(x, *, offset, dim1, dim2):
    n = x.shape[-1] + builtins.abs(offset)
    out = jnp.zeros(x.shape + (n,), x.dtype)
    idx = jnp.arange(x.shape[-1])
    rows = idx + builtins.max(-offset, 0)
    cols = idx + builtins.max(offset, 0)
    out = out.at[..., rows, cols].set(x)
    # move the two result dims to dim1/dim2
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    rest = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
    order = []
    src = {d1: nd - 2, d2: nd - 1}
    it = iter(rest)
    for i in range(nd):
        order.append(src[i] if i in src else next(it))
    return jnp.transpose(out, order)


defprim("diag_embed_p", _diag_embed_fwd)


def repeat_interleave(x, repeats, axis=None, name=None):
    x = ensure_tensor(x)
    if axis is None:
        x = flatten(x)
        axis = 0
    if isinstance(repeats, Tensor):
        return apply(
            "repeat_interleave_t_p", x, repeats, axis=int(axis) % x.ndim
        )
    return apply(
        "repeat_interleave_p", x, repeats=int(repeats), axis=int(axis) % x.ndim
    )


defprim(
    "repeat_interleave_p",
    lambda x, *, repeats, axis: jnp.repeat(x, repeats, axis=axis),
)
defprim(
    "repeat_interleave_t_p",
    lambda x, r, *, axis: jnp.repeat(
        x, r, axis=axis, total_repeat_length=int(np.asarray(r).sum())
    ),
    jittable=False,
)


def as_complex(x, name=None):
    return apply("as_complex_p", ensure_tensor(x))


defprim(
    "as_complex_p", lambda x: jax.lax.complex(x[..., 0], x[..., 1])
)


def as_real(x, name=None):
    return apply("as_real_p", ensure_tensor(x))


defprim(
    "as_real_p", lambda x: jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)
)


def atleast_1d(*inputs, name=None):
    outs = [reshape(t, [1]) if ensure_tensor(t).ndim == 0 else ensure_tensor(t) for t in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = []
    for t in inputs:
        t = ensure_tensor(t)
        while t.ndim < 2:
            t = unsqueeze(t, 0)
        outs.append(t)
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = []
    for t in inputs:
        t = ensure_tensor(t)
        while t.ndim < 3:
            t = unsqueeze(t, t.ndim)
        outs.append(t)
    return outs[0] if len(outs) == 1 else outs


def crop(x, shape=None, offsets=None, name=None):
    x = ensure_tensor(x)
    shape = _infer_shape(x, shape) if shape is not None else tuple(x.shape)
    offsets = tuple(int(o) for o in (offsets or [0] * x.ndim))
    slices = tuple(builtins.slice(o, o + s) for o, s in zip(offsets, shape))
    return _getitem(x, slices)


def tensordot(x, y, axes=2, name=None):
    x, y = binary_args(x, y)
    if isinstance(axes, Tensor):
        axes = axes.tolist()
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else (a,) for a in axes)
    return apply("tensordot_p", x, y, axes=axes if isinstance(axes, int) else tuple(map(tuple, axes)))


defprim("tensordot_p", lambda x, y, *, axes: jnp.tensordot(x, y, axes))


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = ensure_tensor(x)
    idx = [builtins.slice(None)] * x.ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = builtins.slice(int(s), int(e), int(st))
    return _getitem(x, tuple(idx))


def slice(x, axes, starts, ends):
    x = ensure_tensor(x)
    idx = [builtins.slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        s = int(s.item()) if isinstance(s, Tensor) else int(s)
        e = int(e.item()) if isinstance(e, Tensor) else int(e)
        idx[a] = builtins.slice(s, e)
    return _getitem(x, tuple(idx))


# ---------------------------------------------------------------------------
# __getitem__ / __setitem__  (reference: pybind slice_utils.h, set_value op)
# ---------------------------------------------------------------------------
def _encode_index(idx):
    """Encode an index tuple into a hashable static key + list of tensor
    operands. Tensors in the index become operands (advanced indexing)."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    static = []
    operands = []
    for it in idx:
        if isinstance(it, Tensor):
            static.append(("t", len(operands)))
            operands.append(it)
        elif isinstance(it, (np.ndarray, list)):
            arr = np.asarray(it)
            if arr.dtype == object:
                raise TypeError("ragged index")
            t = Tensor._from_value(jnp.asarray(arr))
            static.append(("t", len(operands)))
            operands.append(t)
        elif isinstance(it, builtins.slice):
            static.append(("s", it.start, it.stop, it.step))
        elif it is None:
            static.append(("n",))
        elif it is Ellipsis:
            static.append(("e",))
        elif isinstance(it, (int, np.integer)):
            static.append(("i", int(it)))
        elif isinstance(it, (bool, np.bool_)):
            static.append(("b", bool(it)))
        else:
            raise TypeError(f"unsupported index: {it!r}")
    return tuple(static), operands


def _decode_index(static, arrays):
    out = []
    for item in static:
        kind = item[0]
        if kind == "t":
            a = arrays[item[1]]
            out.append(a.astype(jnp.int32) if jnp.issubdtype(a.dtype, jnp.integer) else a)
        elif kind == "s":
            out.append(builtins.slice(item[1], item[2], item[3]))
        elif kind == "n":
            out.append(None)
        elif kind == "e":
            out.append(Ellipsis)
        elif kind == "i":
            out.append(item[1])
        elif kind == "b":
            out.append(item[1])
    return tuple(out)


def _getitem_fwd(x, *index_arrays, static_idx):
    return x[_decode_index(static_idx, index_arrays)]


defprim("getitem_p", _getitem_fwd)


def _getitem(x, idx):
    # bool-mask fancy indexing produces dynamic shapes → eager numpy path
    def _has_bool_mask(i):
        items = i if isinstance(i, tuple) else (i,)
        for it in items:
            if isinstance(it, Tensor) and np.dtype(it.dtype) == np.dtype(bool):
                return True
            if isinstance(it, np.ndarray) and it.dtype == np.bool_:
                return True
        return False

    if _has_bool_mask(idx):
        items = idx if isinstance(idx, tuple) else (idx,)
        np_idx = tuple(
            np.asarray(it._value) if isinstance(it, Tensor) else it for it in items
        )
        return Tensor._from_value(jnp.asarray(np.asarray(x._value)[np_idx]))
    static, operands = _encode_index(idx)
    return apply("getitem_p", x, *operands, static_idx=static)


def _setitem_fwd(x, v, *index_arrays, static_idx):
    return x.at[_decode_index(static_idx, index_arrays)].set(v.astype(x.dtype))


defprim("setitem_p", _setitem_fwd)


def _setitem(x, idx, value):
    v = ensure_tensor(value, dtype=x.dtype)
    static, operands = _encode_index(idx)
    out = apply("setitem_p", x, v, *operands, static_idx=static)
    x._replace_value(out._value)
    x._node, x._out_slot, x.stop_gradient = out._node, out._out_slot, out.stop_gradient
    return x
