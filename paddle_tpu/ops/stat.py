"""Statistics ops.

Reference surface: python/paddle/tensor/stat.py (mean/std/var/median/
quantile/mode/kthvalue).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, apply
from ._helpers import axis_tuple, defprim, ensure_tensor

__all__ = [
    "std", "var", "median", "nanmedian", "quantile", "nanquantile", "mode",
    "kthvalue",
]

defprim(
    "var_p",
    lambda x, *, axis, unbiased, keepdim: jnp.var(
        x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim
    ),
)
defprim(
    "std_p",
    lambda x, *, axis, unbiased, keepdim: jnp.std(
        x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim
    ),
)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply(
        "var_p", x, axis=axis_tuple(axis, x.ndim), unbiased=bool(unbiased),
        keepdim=bool(keepdim),
    )


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply(
        "std_p", x, axis=axis_tuple(axis, x.ndim), unbiased=bool(unbiased),
        keepdim=bool(keepdim),
    )


defprim(
    "median_p",
    lambda x, *, axis, keepdim, mode: (
        jnp.median(x, axis=axis, keepdims=keepdim)
        if mode == "avg"
        else jnp.quantile(x, 0.5, axis=axis, keepdims=keepdim, method="lower")
    ),
)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    x = ensure_tensor(x)
    out = apply(
        "median_p", x, axis=int(axis) if axis is not None else None,
        keepdim=bool(keepdim), mode=mode,
    )
    if mode == "min" and axis is not None:
        # paddle returns (values, indices) for mode='min' with axis
        from .manipulation import argsort

        return out, None
    return out


defprim(
    "nanmedian_p",
    lambda x, *, axis, keepdim: jnp.nanmedian(x, axis=axis, keepdims=keepdim),
)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    x = ensure_tensor(x)
    return apply(
        "nanmedian_p", x, axis=axis_tuple(axis, x.ndim), keepdim=bool(keepdim)
    )


defprim(
    "quantile_p",
    lambda x, *, q, axis, keepdim, interpolation: jnp.quantile(
        x, jnp.asarray(q), axis=axis, keepdims=keepdim, method=interpolation
    ),
)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    x = ensure_tensor(x)
    qv = tuple(np.atleast_1d(q).tolist()) if not isinstance(q, float) else q
    out = apply(
        "quantile_p", x, q=qv, axis=int(axis) if axis is not None else None,
        keepdim=bool(keepdim), interpolation=interpolation,
    )
    return out


defprim(
    "nanquantile_p",
    lambda x, *, q, axis, keepdim, interpolation: jnp.nanquantile(
        x, jnp.asarray(q), axis=axis, keepdims=keepdim, method=interpolation
    ),
)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    x = ensure_tensor(x)
    qv = tuple(np.atleast_1d(q).tolist()) if not isinstance(q, float) else q
    return apply(
        "nanquantile_p", x, q=qv, axis=int(axis) if axis is not None else None,
        keepdim=bool(keepdim), interpolation=interpolation,
    )


def _mode_fwd(x, *, axis, keepdim):
    # most frequent value along axis, ties → smallest (paddle: largest index?
    # reference kernel returns the last occurrence; we match scipy-style).
    sorted_x = jnp.sort(x, axis=axis)
    n = x.shape[axis]

    def count_runs(a):
        # a: 1-d sorted
        eq = a[:, None] == a[None, :]
        counts = eq.sum(-1)
        best = jnp.argmax(counts)
        return a[best]

    moved = jnp.moveaxis(sorted_x, axis, -1)
    flat = moved.reshape(-1, n)
    vals = jax.vmap(count_runs)(flat)
    vals = vals.reshape(moved.shape[:-1])
    idx = jnp.argmax(
        jnp.moveaxis(x, axis, -1).reshape(-1, n) == vals[..., None].reshape(-1, 1),
        axis=-1,
    ).reshape(moved.shape[:-1])
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(jnp.int64)


defprim("mode_p", _mode_fwd, multi_out=True)


def mode(x, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply("mode_p", x, axis=int(axis) % x.ndim, keepdim=bool(keepdim))


def _kthvalue_fwd(x, *, k, axis, keepdim):
    moved = jnp.moveaxis(x, axis, -1)
    sorted_x = jnp.sort(moved, axis=-1)
    argsorted = jnp.argsort(moved, axis=-1)
    vals = sorted_x[..., k - 1]
    idx = argsorted[..., k - 1]
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(jnp.int64)


defprim("kthvalue_p", _kthvalue_fwd, multi_out=True)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)
    return apply(
        "kthvalue_p", x, k=int(k), axis=int(axis) % x.ndim, keepdim=bool(keepdim)
    )
