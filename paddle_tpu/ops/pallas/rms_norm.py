"""Pallas fused RMSNorm (forward + backward) for TPU.

TPU-native analog of the reference fused kernel
(reference: phi/kernels/gpu/rms_norm_kernel.cu, surfaced as
paddle.incubate.nn.functional.fused_rms_norm). One pass per row block:
fp32 mean-of-squares on the VPU, scaled write-back. Backward recomputes the
inverse RMS from the saved input (cheaper than storing a residual) and
accumulates the weight gradient across row blocks in VMEM scratch — the grid
is sequential on TPU so the accumulator carries without atomics.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...core import dispatch
from .flash_attention import Z, _interpret, _pick_block


def _fwd_kernel(x_ref, w_ref, y_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    invr = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    y_ref[:] = (x * invr * w[None, :]).astype(y_ref.dtype)


def _bwd_kernel(x_ref, w_ref, g_ref, dx_ref, dw_ref, dw_scr, *, eps, nr):
    r = pl.program_id(0)

    @pl.when(r == 0)
    def _init():
        dw_scr[:] = jnp.zeros(dw_scr.shape, jnp.float32)

    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    invr = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    gw = g * w[None, :]
    c = jnp.mean(gw * x, axis=-1, keepdims=True) * invr * invr * invr
    dx_ref[:] = (gw * invr - x * c).astype(dx_ref.dtype)
    dw_scr[:] += jnp.sum(g * x * invr, axis=0)

    @pl.when(r == nr - 1)
    def _finalize():
        dw_ref[:] = dw_scr[:].astype(dw_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps",))
def _rms_fwd(x, w, *, eps):
    hidden = x.shape[-1]
    x2 = x.reshape(-1, hidden)
    rows = x2.shape[0]
    block_r = _pick_block(rows, 256)
    y = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=(rows // block_r,),
        in_specs=[
            pl.BlockSpec((block_r, hidden), lambda r: (r, Z)),
            pl.BlockSpec((hidden,), lambda r: (Z,)),
        ],
        out_specs=pl.BlockSpec((block_r, hidden), lambda r: (r, Z)),
        out_shape=jax.ShapeDtypeStruct((rows, hidden), x.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(x2, w)
    return y.reshape(x.shape)


@functools.partial(jax.jit, static_argnames=("eps",))
def _rms_bwd(x, w, g, *, eps):
    hidden = x.shape[-1]
    x2 = x.reshape(-1, hidden)
    g2 = g.reshape(-1, hidden)
    rows = x2.shape[0]
    block_r = _pick_block(rows, 256)
    nr = rows // block_r
    dx, dw = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps, nr=nr),
        grid=(nr,),
        in_specs=[
            pl.BlockSpec((block_r, hidden), lambda r: (r, Z)),
            pl.BlockSpec((hidden,), lambda r: (Z,)),
            pl.BlockSpec((block_r, hidden), lambda r: (r, Z)),
        ],
        out_specs=[
            pl.BlockSpec((block_r, hidden), lambda r: (r, Z)),
            pl.BlockSpec((hidden,), lambda r: (Z,)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, hidden), x.dtype),
            jax.ShapeDtypeStruct((hidden,), w.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((hidden,), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=_interpret(),
    )(x2, w, g2)
    return dx.reshape(x.shape), dw


def _vjp(grads_out, saved, *, eps):
    x, w = saved
    return _rms_bwd(x, w, grads_out[0], eps=eps)


dispatch.register_primitive(
    "rms_norm_pallas_p",
    lambda x, w, *, eps: _rms_fwd(x, w, eps=eps),
    vjp=_vjp,
    save=lambda arrays, outs: arrays,
    jittable=False,  # jitted internally
)


# NOTE: the dispatch gate lives in nn/functional/norm.py (_use_pallas_rms)
# so the XLA fallback path never imports this module.
