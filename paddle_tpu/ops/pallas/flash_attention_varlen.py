"""Pallas varlen (unpadded) flash attention for TPU.

TPU-native replacement for the reference's varlen CUDA kernels
(reference: phi/kernels/gpu/flash_attn_kernel.cu:35 FlashAttnUnpaddedKernel,
Python surface python/paddle/nn/functional/flash_attention.py:602).

Design: the packed token axis [T, H, D] stays packed — no per-segment
slicing, no recompiles when the segment layout changes. cu_seqlens are
turned into three per-token int32 vectors outside the kernel (segment id
for q rows, segment id for k rows, and for causal masking the global
k-column bound each q row may attend to, bottom-right aligned per
segment). The kernels are the same online-softmax flash loops as the
dense ones (flash_attention.py), with the (row, col) mask computed from
the segment vectors: valid iff same segment and (causal) col <= bound.
Cross-segment blocks are skipped via block-level min/max tests on the
(sorted) segment ids, so the work done is ~block-diagonal, matching the
varlen kernel's O(sum_i len_i^2) cost rather than O(T^2).

GQA is expressed through the BlockSpec kv-head index map; grids carry no
batch axis (batch is the packing). Padding rows (to block multiples) get
sentinel segment ids that never match, and fully-masked rows emit zeros
(lse = -inf) exactly like the dense kernel's drain path.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...core import dispatch
from .flash_attention import _dropout_keep
from .flash_attention import (_interpret, _kv_head_map, _pick_block,
                              LANES, NEG_INF, Z)


def _pad_to(n: int, mult: int) -> int:
    return (n + mult - 1) // mult * mult


def _seg_vectors(cu_q, cu_k, t_q, t_k, pad_q, pad_k, n_seqs):
    """Per-token segment ids + causal column bounds from cu_seqlens.

    Returns (seg_q [pad_q], seg_k [pad_k], bound [pad_q]) int32. Padding
    rows get sentinel ids (n_seqs for q, n_seqs+1 for k) that keep the
    vectors nondecreasing but never equal, and bound = -1 (mask all).
    """
    cu_q = cu_q.astype(jnp.int32)
    cu_k = cu_k.astype(jnp.int32)
    pos_q = jnp.arange(pad_q, dtype=jnp.int32)
    pos_k = jnp.arange(pad_k, dtype=jnp.int32)
    seg_q = jnp.searchsorted(cu_q[1:], pos_q, side="right").astype(jnp.int32)
    seg_k = jnp.searchsorted(cu_k[1:], pos_k, side="right").astype(jnp.int32)
    seg_q = jnp.where(pos_q < t_q, seg_q, n_seqs)
    seg_k = jnp.where(pos_k < t_k, seg_k, n_seqs + 1)
    sq = jnp.clip(seg_q, 0, n_seqs - 1)
    len_q = cu_q[sq + 1] - cu_q[sq]
    len_k = cu_k[sq + 1] - cu_k[sq]
    local = pos_q - cu_q[sq]
    bound = cu_k[sq] + local + (len_k - len_q)
    bound = jnp.where(pos_q < t_q, bound, -1)
    return seg_q, seg_k, bound


def _mask_for(sq, sk, bound, j, block_k, causal):
    """[bq, bk] validity mask from per-row segment vectors."""
    same = sq[:, None] == sk[None, :]
    if causal:
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (sq.shape[0], block_k), 1)
        same = same & (cols <= bound[:, None])
    return same


def _skip_block(sq, sk, bound, j, block_k, causal):
    """True when this (q block, k block) pair has no valid pair: segment
    ids are nondecreasing, so ranges must overlap; under causal masking
    the k block must start at or below the largest row bound."""
    disjoint = (jnp.max(sq) < jnp.min(sk)) | (jnp.min(sq) > jnp.max(sk))
    if causal:
        disjoint = disjoint | (j * block_k > jnp.max(bound))
    return disjoint


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _vfwd_kernel(*refs, scale, causal, block_q, block_k, nk, rate):
    if rate > 0.0:
        (q_ref, k_ref, v_ref, segq_ref, segk_ref, bound_ref, seed_ref,
         o_ref, lse_ref, m_scr, l_scr, acc_scr) = refs
    else:
        (q_ref, k_ref, v_ref, segq_ref, segk_ref, bound_ref,
         o_ref, lse_ref, m_scr, l_scr, acc_scr) = refs
        seed_ref = None
    h = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    sq = segq_ref[:]
    sk = segk_ref[:]
    bound = bound_ref[:]

    @pl.when(~_skip_block(sq, sk, bound, j, block_k, causal))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        s = jnp.where(_mask_for(sq, sk, bound, j, block_k, causal),
                      s, NEG_INF)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        m_eff = jnp.where(m_new == NEG_INF, 0.0, m_new)
        alpha = jnp.exp(m_prev - m_eff)
        p = jnp.exp(s - m_eff)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        if rate > 0.0:
            # same contract as the dense kernel (_fwd_kernel): denominator
            # undropped, value accumulation masked+rescaled; bits keyed on
            # packed-token coordinates so fwd and both bwd kernels agree
            keep = _dropout_keep(seed_ref[0], h, i, j, block_q, block_k,
                                 rate)
            p_use = p * keep * (1.0 / (1.0 - rate))
        else:
            p_use = p
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p_use, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        m = m_scr[:, :1]
        lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe))
        lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "n_seqs",
                                              "dropout_rate"))
def _vflash_fwd(q, k, v, cu_q, cu_k, seed=None, *, causal, scale, n_seqs,
                dropout_rate=0.0):
    """q: [H, Tq, D]; k, v: [Hkv, Tk, D] (already padded to block
    multiples); returns (out [H, Tq, D], lse [H, Tq])."""
    H, Tq, D = q.shape
    Hkv, Tk = k.shape[0], k.shape[1]
    g = H // Hkv
    block_q = _pick_block(Tq)
    block_k = _pick_block(Tk)
    nq, nk = Tq // block_q, Tk // block_k
    kv_head = _kv_head_map(g)
    seg_q, seg_k, bound = _seg_vectors(
        cu_q, cu_k, cu_q[-1], cu_k[-1], Tq, Tk, n_seqs)
    kernel = functools.partial(
        _vfwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk, rate=dropout_rate)
    in_specs = [
            pl.BlockSpec((1, block_q, D), lambda h, i, j: (h, i, Z)),
            pl.BlockSpec((1, block_k, D), lambda h, i, j: (kv_head(h), j, Z)),
            pl.BlockSpec((1, block_k, D), lambda h, i, j: (kv_head(h), j, Z)),
            pl.BlockSpec((block_q,), lambda h, i, j: (i,)),
            pl.BlockSpec((block_k,), lambda h, i, j: (j,)),
            pl.BlockSpec((block_q,), lambda h, i, j: (i,)),
    ]
    inputs = [q, k, v, seg_q, seg_k, bound]
    if dropout_rate > 0.0:
        in_specs.append(pl.BlockSpec((1,), lambda h, i, j: (Z,),
                                     memory_space=pltpu.SMEM))
        inputs.append(seed)
    out, lse = pl.pallas_call(
        kernel,
        grid=(H, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda h, i, j: (h, i, Z)),
            pl.BlockSpec((1, block_q, LANES), lambda h, i, j: (h, i, Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((H, Tq, D), q.dtype),
            jax.ShapeDtypeStruct((H, Tq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(*inputs)
    return out, lse[:, :, 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _vbwd_dq_kernel(*refs, scale, causal, block_q, block_k, nk, rate):
    if rate > 0.0:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         segq_ref, segk_ref, bound_ref, seed_ref, dq_ref, dq_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         segq_ref, segk_ref, bound_ref, dq_ref, dq_scr) = refs
        seed_ref = None
    h = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    sq = segq_ref[:]
    sk = segk_ref[:]
    bound = bound_ref[:]

    @pl.when(~_skip_block(sq, sk, bound, j, block_k, causal))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        s = jnp.where(_mask_for(sq, sk, bound, j, block_k, causal),
                      s, NEG_INF)
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse)
        p = jnp.exp(s - lse_safe)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if rate > 0.0:
            keep = _dropout_keep(seed_ref[0], h, i, j, block_q, block_k,
                                 rate)
            dp = dp * keep * (1.0 / (1.0 - rate))
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _vbwd_dkv_kernel(*refs, scale, causal, block_q, block_k, nq, rate):
    if rate > 0.0:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         segq_ref, segk_ref, bound_ref, seed_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         segq_ref, segk_ref, bound_ref,
         dk_ref, dv_ref, dk_scr, dv_scr) = refs
        seed_ref = None
    h = pl.program_id(0)
    j = pl.program_id(1)  # k block
    i = pl.program_id(2)  # q block (innermost: accumulate)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    sq = segq_ref[:]
    sk = segk_ref[:]
    bound = bound_ref[:]

    @pl.when(~_skip_block(sq, sk, bound, j, block_k, causal))
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        s = jnp.where(_mask_for(sq, sk, bound, j, block_k, causal),
                      s, NEG_INF)
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse)
        p = jnp.exp(s - lse_safe)
        if rate > 0.0:
            keep = _dropout_keep(seed_ref[0], h, i, j, block_q, block_k,
                                 rate)
            p_drop = p * keep * (1.0 / (1.0 - rate))
        else:
            p_drop = p
        dv_scr[:] += jax.lax.dot_general(
            p_drop, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if rate > 0.0:
            dp = dp * keep * (1.0 / (1.0 - rate))
        ds = p * (dp - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "n_seqs",
                                              "dropout_rate"))
def _vflash_bwd(q, k, v, cu_q, cu_k, out, lse, do, seed=None, *, causal,
                scale, n_seqs, dropout_rate=0.0):
    H, Tq, D = q.shape
    Hkv, Tk = k.shape[0], k.shape[1]
    g = H // Hkv
    block_q = _pick_block(Tq)
    block_k = _pick_block(Tk)
    nq, nk = Tq // block_q, Tk // block_k
    kv_head = _kv_head_map(g)
    seg_q, seg_k, bound = _seg_vectors(
        cu_q, cu_k, cu_q[-1], cu_k[-1], Tq, Tk, n_seqs)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    lse_p = jnp.broadcast_to(lse[..., None], (H, Tq, LANES))
    delta_p = jnp.broadcast_to(delta[..., None], (H, Tq, LANES))

    dq_in_specs = [
            pl.BlockSpec((1, block_q, D), lambda h, i, j: (h, i, Z)),
            pl.BlockSpec((1, block_k, D), lambda h, i, j: (kv_head(h), j, Z)),
            pl.BlockSpec((1, block_k, D), lambda h, i, j: (kv_head(h), j, Z)),
            pl.BlockSpec((1, block_q, D), lambda h, i, j: (h, i, Z)),
            pl.BlockSpec((1, block_q, LANES), lambda h, i, j: (h, i, Z)),
            pl.BlockSpec((1, block_q, LANES), lambda h, i, j: (h, i, Z)),
            pl.BlockSpec((block_q,), lambda h, i, j: (i,)),
            pl.BlockSpec((block_k,), lambda h, i, j: (j,)),
            pl.BlockSpec((block_q,), lambda h, i, j: (i,)),
    ]
    dq_inputs = [q, k, v, do, lse_p, delta_p, seg_q, seg_k, bound]
    if dropout_rate > 0.0:
        dq_in_specs.append(pl.BlockSpec((1,), lambda h, i, j: (Z,),
                                        memory_space=pltpu.SMEM))
        dq_inputs.append(seed)
    dq = pl.pallas_call(
        functools.partial(_vbwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk,
                          rate=dropout_rate),
        grid=(H, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, D), lambda h, i, j: (h, i, Z)),
        out_shape=jax.ShapeDtypeStruct((H, Tq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(*dq_inputs)

    dkv_in_specs = [
            pl.BlockSpec((1, block_q, D), lambda h, j, i: (h, i, Z)),
            pl.BlockSpec((1, block_k, D), lambda h, j, i: (kv_head(h), j, Z)),
            pl.BlockSpec((1, block_k, D), lambda h, j, i: (kv_head(h), j, Z)),
            pl.BlockSpec((1, block_q, D), lambda h, j, i: (h, i, Z)),
            pl.BlockSpec((1, block_q, LANES), lambda h, j, i: (h, i, Z)),
            pl.BlockSpec((1, block_q, LANES), lambda h, j, i: (h, i, Z)),
            pl.BlockSpec((block_q,), lambda h, j, i: (i,)),
            pl.BlockSpec((block_k,), lambda h, j, i: (j,)),
            pl.BlockSpec((block_q,), lambda h, j, i: (i,)),
    ]
    dkv_inputs = [q, k, v, do, lse_p, delta_p, seg_q, seg_k, bound]
    if dropout_rate > 0.0:
        dkv_in_specs.append(pl.BlockSpec((1,), lambda h, j, i: (Z,),
                                         memory_space=pltpu.SMEM))
        dkv_inputs.append(seed)
    dk_h, dv_h = pl.pallas_call(
        functools.partial(_vbwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq,
                          rate=dropout_rate),
        grid=(H, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda h, j, i: (h, j, Z)),
            pl.BlockSpec((1, block_k, D), lambda h, j, i: (h, j, Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((H, Tk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_interpret(),
    )(*dkv_inputs)
    if g > 1:
        dk = dk_h.reshape(Hkv, g, Tk, D).sum(axis=1).astype(k.dtype)
        dv = dv_h.reshape(Hkv, g, Tk, D).sum(axis=1).astype(v.dtype)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk, dv


# ---------------------------------------------------------------------------
# array-level API (packed [T, H, D] layout) + primitive registration
# ---------------------------------------------------------------------------
def _to_htd(x, t_pad):
    """[T, H, D] -> [H, T_pad, D] (transpose + zero-pad the token axis)."""
    x = jnp.swapaxes(x, 0, 1)
    if t_pad > x.shape[1]:
        x = jnp.pad(x, ((0, 0), (0, t_pad - x.shape[1]), (0, 0)))
    return x


def flash_attn_varlen_thd(q, k, v, cu_q, cu_k, seed=None, *, causal=False,
                          scale=None, n_seqs=None, dropout_rate=0.0):
    """Array-level varlen attention over packed [T, H, D] tensors.

    cu_seqlens are data (not static): one compile serves every segment
    layout with the same packed lengths. ``seed`` (int32 [1]) enables
    in-kernel attention dropout at ``dropout_rate``. Returns
    (out [Tq, H, D], lse [H, Tq_pad])."""
    Tq = q.shape[0]
    Tk = k.shape[0]
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if n_seqs is None:
        n_seqs = cu_q.shape[0] - 1
    pad_q = _pad_to(Tq, 128)
    pad_k = _pad_to(Tk, 128)
    qh = _to_htd(q, pad_q)
    kh = _to_htd(k, pad_k)
    vh = _to_htd(v, pad_k)
    out, lse = _vflash_fwd(qh, kh, vh, cu_q, cu_k, seed, causal=bool(causal),
                           scale=float(scale), n_seqs=int(n_seqs),
                           dropout_rate=float(dropout_rate))
    return jnp.swapaxes(out[:, :Tq], 0, 1), lse


def _varlen_fwd_prim(q, k, v, cu_q, cu_k, seed=None, *, causal, scale,
                     n_seqs, dropout_rate=0.0):
    out, lse = flash_attn_varlen_thd(q, k, v, cu_q, cu_k, seed,
                                     causal=causal, scale=scale,
                                     n_seqs=n_seqs,
                                     dropout_rate=dropout_rate)
    return out, lse


def _varlen_vjp(grads_out, saved, *, causal, scale, n_seqs,
                dropout_rate=0.0):
    *ins, out, lse = saved
    q, k, v, cu_q, cu_k = ins[:5]
    seed = ins[5] if len(ins) > 5 else None
    do = grads_out[0]
    Tq, Tk = q.shape[0], k.shape[0]
    pad_q = lse.shape[1]
    pad_k = _pad_to(Tk, 128)
    dq, dk, dv = _vflash_bwd(
        _to_htd(q, pad_q), _to_htd(k, pad_k), _to_htd(v, pad_k),
        cu_q, cu_k, _to_htd(out, pad_q), lse, _to_htd(do, pad_q), seed,
        causal=causal, scale=float(scale), n_seqs=int(n_seqs),
        dropout_rate=float(dropout_rate))
    grads = (jnp.swapaxes(dq[:, :Tq], 0, 1), jnp.swapaxes(dk[:, :Tk], 0, 1),
             jnp.swapaxes(dv[:, :Tk], 0, 1), None, None)
    if seed is not None:
        grads = grads + (None,)
    return grads


dispatch.register_primitive(
    "flash_attn_varlen_p",
    _varlen_fwd_prim,
    vjp=_varlen_vjp,
    save=lambda arrays, outs: (*arrays, outs[0], outs[1]),
    multi_out=True,
    jittable=False,  # jitted internally; pallas_call dislikes re-trace
)
