"""Pallas TPU kernel library.

TPU-native analog of the reference fused-kernel libraries
(paddle/phi/kernels/fusion/, paddle/fluid/operators/fused/, and the
third_party/flashattn integration at phi/kernels/gpu/flash_attn_kernel.cu:35).
Where the reference hand-writes CUDA, here the hot ops are Pallas kernels
tiled for MXU/VMEM; every kernel has an interpret-mode path so the numerics
are testable on the XLA-CPU virtual backend.
"""
from . import (flash_attention, flash_attention_varlen,  # noqa: F401
               paged_attention, rms_norm)
