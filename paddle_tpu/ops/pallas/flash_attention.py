"""Pallas flash attention (forward + backward) for TPU.

TPU-native replacement for the reference's CUDA flashattn integration
(reference: phi/kernels/gpu/flash_attn_kernel.cu:35, Python surface
python/paddle/nn/functional/flash_attention.py:198,991). Design: classic
flash-attention online-softmax over a (batch, q_head, q_block, k_block)
sequential grid — the k_block axis is innermost so VMEM scratch carries the
running (max, sum, accumulator) across k blocks; backward recomputes P from
the saved logsumexp (no O(S^2) residuals). GQA is expressed in the BlockSpec
index maps (kv head = q head // group), so grouped KV blocks are fetched
once per q head without materialising the repeat.

Layouts: public API uses paddle's [B, S, H, D]; kernels run [B, H, S, D].
Compute is fp32 on the MXU (`preferred_element_type`), outputs cast back.

On non-TPU backends the same kernels run under `interpret=True`, which is
how the OpTest suite checks them against the XLA composition oracle.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...core import dispatch

NEG_INF = float("-inf")
Z = __import__("numpy").int32(0)  # index-map literal: stays i32 under jax_enable_x64
LANES = 128  # lse/delta lane padding (TPU (8,128) tiling; see _fwd_kernel)


def _c32(u):
    """uint32 literal as a wrapping int32 constant."""
    import numpy as np

    return jnp.int32(np.uint32(u).astype(np.int32))


def _dropout_keep(seed, bh, i, j, block_q, block_k, rate):
    """Counter-based attention-dropout mask for the (i, j) tile of head bh.

    P(keep) = 1 - rate. murmur3-style int32 mixing over
    (seed, batch*head, global row, global col) — pure vector int ops, so
    the SAME bits regenerate in the forward and both backward kernels
    (their grids visit the same (b, h, i, j) tiles) and under
    ``interpret=True`` (``pltpu.prng_*`` has no interpret lowering).
    Reference semantics: dropout on the softmax WEIGHTS
    (flash_attention.py:991 attn_dropout), denominator excluded.
    """
    rows = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    x = (rows * _c32(0x9E3779B1)) ^ (cols * _c32(0x85EBCA77))
    x = x ^ (bh * _c32(0xC2B2AE3D)) ^ seed
    shr = lambda a, n: jax.lax.shift_right_logical(a, jnp.int32(n))
    x = x ^ shr(x, 16)
    x = x * _c32(0x85EBCA6B)
    x = x ^ shr(x, 13)
    x = x * _c32(0xC2B2AE35)
    x = x ^ shr(x, 16)
    thresh = jnp.int32(int(min(float(rate), 1.0) * 2147483647.0))
    keep = (x & _c32(0x7FFFFFFF)) >= thresh
    return keep.astype(jnp.float32)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(n: int, target: int = 512) -> int:
    """Largest power-of-two divisor of n, capped at target (>=128 when
    possible so blocks tile the lane dimension)."""
    b = min(n, target)
    while b > 1 and n % b:
        b //= 2
    return max(b, 1)



def _kv_head_map(g: int):
    """Index-map component mapping q head -> kv head (GQA). `h // g` via
    jnp inside an index map trips an int-promotion convert_element_type
    cycle in Mosaic lowering; use an identity map for g==1 and a
    same-dtype lax.div otherwise."""
    if g == 1:
        return lambda h: h
    import numpy as _np

    return lambda h: jax.lax.div(h, _np.int32(g))


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(*refs, scale, causal, block_q, block_k, nk, offset,
                rate, n_heads, has_bias=False):
    # offset = Sk - Sq: bottom-right-aligned causal mask (query i attends
    # keys <= i + offset), matching paddle/XLA semantics for Sq != Sk
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    n = 3
    bias_ref = refs[n] if has_bias else None
    n += int(has_bias)
    seed_ref = refs[n] if rate > 0.0 else None
    n += int(rate > 0.0)
    o_ref, lse_ref, m_scr, l_scr, acc_scr = refs[n:]
    i = pl.program_id(2)
    j = pl.program_id(3)
    # hoisted: pl.program_id is not available inside a pl.when body under
    # interpret mode
    bh = pl.program_id(0) * n_heads + pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if has_bias:
            # additive per-key bias (broadcast over query rows): the
            # [B, 1, 1, Sk] padding-mask pattern of sdpa_mask_p
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + offset >= cols, s, NEG_INF)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # rows may be fully masked inside a partially-causal block; keep the
        # exp args finite so those rows stay exactly zero instead of NaN
        m_eff = jnp.where(m_new == NEG_INF, 0.0, m_new)
        alpha = jnp.exp(m_prev - m_eff)  # exp(-inf)=0 for first visit
        p = jnp.exp(s - m_eff)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        if rate > 0.0:
            # softmax denominator (l) stays over the UNDROPPED weights;
            # only the value accumulation sees the mask (post-softmax
            # dropout semantics, matching the XLA oracle path)
            keep = _dropout_keep(seed_ref[0], bh, i, j, block_q, block_k,
                                 rate)
            p_use = p * keep * (1.0 / (1.0 - rate))
        else:
            p_use = p
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p_use, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip blocks strictly above the (offset) diagonal
        @pl.when(j * block_k <= i * block_q + (block_q - 1) + offset)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        m = m_scr[:, :1]
        lse = jnp.where(l == 0.0, NEG_INF, m + jnp.log(l_safe))
        # lse is carried in a 128-lane layout ([..., Sq, LANES]) — TPU block
        # shapes need the last two dims (8, 128)-tileable, so a [B, H, Sq]
        # output with (1, 1, block_q) blocks is not expressible
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref[0, 0].shape)


@functools.partial(jax.jit,
                   static_argnames=("causal", "scale", "dropout_rate"))
def _flash_fwd_bhsd(q, k, v, seed=None, key_bias=None, *, causal, scale,
                    dropout_rate=0.0):
    """q: [B,H,Sq,D]; k,v: [B,Hkv,Sk,D] -> (out [B,H,Sq,D], lse [B,H,Sq]).
    seed: int32 [1] dropout seed, required when dropout_rate > 0.
    key_bias: [B, Sk] additive logit bias broadcast over heads/rows (the
    padding-mask pattern), added BEFORE the causal mask/softmax."""
    B, H, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    g = H // Hkv
    block_q = _pick_block(Sq)
    block_k = _pick_block(Sk)
    nq, nk = Sq // block_q, Sk // block_k
    kv_head = _kv_head_map(g)
    grid = (B, H, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk, offset=Sk - Sq,
        rate=dropout_rate, n_heads=H, has_bias=key_bias is not None)
    in_specs = [
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, Z)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, kv_head(h), j, Z)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, kv_head(h), j, Z)),
    ]
    inputs = [q, k, v]
    if key_bias is not None:
        # [B, 1, Sk] with (1, 1, block_k) blocks: Mosaic wants the last
        # two block dims (8, 128)-divisible or equal to the array dims.
        # A batch-1 bias (mask shared across the batch) pins the index
        # map to row 0 instead of materializing B copies.
        bmap = ((lambda b, h, i, j: (Z, Z, j)) if key_bias.shape[0] == 1
                else (lambda b, h, i, j: (b, Z, j)))
        in_specs.append(pl.BlockSpec((1, 1, block_k), bmap))
        inputs.append(key_bias.reshape(key_bias.shape[0], 1,
                                       key_bias.shape[1]))
    if dropout_rate > 0.0:
        in_specs.append(pl.BlockSpec((1,), lambda b, h, i, j: (Z,),
                                  memory_space=pltpu.SMEM))
        inputs.append(seed)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, Z)),
            pl.BlockSpec((1, 1, block_q, LANES),
                         lambda b, h, i, j: (b, h, i, Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * Sq * Sk * D,
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=B * H * Sq * Sk,
        ),
        interpret=_interpret(),
    )(*inputs)
    return out, lse[:, :, :, 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(*refs, scale, causal, block_q, block_k, nk, offset,
                   rate, n_heads, has_bias=False):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    n = 6
    bias_ref = refs[n] if has_bias else None
    n += int(has_bias)
    seed_ref = refs[n] if rate > 0.0 else None
    n += int(rate > 0.0)
    dq_ref, dq_scr = refs[n:]
    i = pl.program_id(2)
    j = pl.program_id(3)
    # hoisted: pl.program_id is not available inside a pl.when body under
    # interpret mode
    bh = pl.program_id(0) * n_heads + pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]  # lane-padded [block_q, LANES]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + offset >= cols, s, NEG_INF)
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse)
        p = jnp.exp(s - lse_safe)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if rate > 0.0:
            # d/ds of out = (keep∘c∘softmax(s)) @ v with the softmax
            # denominator undropped: ds_j = p_j (keep_j c dp_j - delta),
            # delta = rowsum(do∘o) (absorbs the Σ p·dp term exactly)
            keep = _dropout_keep(seed_ref[0], bh, i, j, block_q, block_k,
                                 rate)
            dp = dp * keep * (1.0 / (1.0 - rate))
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    if causal:
        @pl.when(j * block_k <= i * block_q + (block_q - 1) + offset)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, block_q, block_k, nq, offset,
                    rate, n_heads, has_bias=False):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    n = 6
    bias_ref = refs[n] if has_bias else None
    n += int(has_bias)
    seed_ref = refs[n] if rate > 0.0 else None
    n += int(rate > 0.0)
    dk_ref, dv_ref, dk_scr, dv_scr = refs[n:]
    j = pl.program_id(2)  # k block
    i = pl.program_id(3)  # q block (innermost: accumulate over q)
    bh = pl.program_id(0) * n_heads + pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[:] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)
        k = k_ref[0, 0].astype(jnp.float32)
        v = v_ref[0, 0].astype(jnp.float32)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]  # lane-padded [block_q, LANES]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if has_bias:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(rows + offset >= cols, s, NEG_INF)
        lse_safe = jnp.where(lse == NEG_INF, 0.0, lse)
        p = jnp.exp(s - lse_safe)
        if rate > 0.0:
            # same (b, h, i, j) tile bits as fwd/dq — note i is pid 3 here
            keep = _dropout_keep(seed_ref[0], bh, i, j, block_q, block_k,
                                 rate)
            p_drop = p * keep * (1.0 / (1.0 - rate))
        else:
            p_drop = p
        # dV += (keep∘c∘P)^T dO
        dv_scr[:] += jax.lax.dot_general(
            p_drop, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if rate > 0.0:
            dp = dp * keep * (1.0 / (1.0 - rate))
        ds = p * (dp - delta) * scale
        # dK += dS^T Q
        dk_scr[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    if causal:
        @pl.when(i * block_q + (block_q - 1) + offset >= j * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("causal", "scale", "dropout_rate"))
def _flash_bwd_bhsd(q, k, v, out, lse, do, seed=None, key_bias=None, *,
                    causal, scale, dropout_rate=0.0):
    B, H, Sq, D = q.shape
    Hkv, Sk = k.shape[1], k.shape[2]
    g = H // Hkv
    block_q = _pick_block(Sq)
    block_k = _pick_block(Sk)
    nq, nk = Sq // block_q, Sk // block_k
    kv_head = _kv_head_map(g)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    # lane-pad lse/delta to [B, H, Sq, LANES] (see _fwd_kernel finalize)
    lse = jnp.broadcast_to(lse[..., None], (B, H, Sq, LANES))
    delta = jnp.broadcast_to(delta[..., None], (B, H, Sq, LANES))

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nk=nk, offset=Sk - Sq,
        rate=dropout_rate, n_heads=H, has_bias=key_bias is not None)
    dq_in_specs = [
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, Z)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, kv_head(h), j, Z)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, i, j: (b, kv_head(h), j, Z)),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, Z)),
            pl.BlockSpec((1, 1, block_q, LANES),
                         lambda b, h, i, j: (b, h, i, Z)),
            pl.BlockSpec((1, 1, block_q, LANES),
                         lambda b, h, i, j: (b, h, i, Z)),
    ]
    dq_inputs = [q, k, v, do, lse, delta]
    if key_bias is not None:
        bmap = ((lambda b, h, i, j: (Z, Z, j)) if key_bias.shape[0] == 1
                else (lambda b, h, i, j: (b, Z, j)))
        dq_in_specs.append(pl.BlockSpec((1, 1, block_k), bmap))
        dq_inputs.append(key_bias.reshape(key_bias.shape[0], 1,
                                          key_bias.shape[1]))
    if dropout_rate > 0.0:
        dq_in_specs.append(pl.BlockSpec((1,), lambda b, h, i, j: (Z,),
                                  memory_space=pltpu.SMEM))
        dq_inputs.append(seed)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(B, H, nq, nk),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i, j: (b, h, i, Z)),
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=_interpret(),
    )(*dq_inputs)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, nq=nq, offset=Sk - Sq,
        rate=dropout_rate, n_heads=H, has_bias=key_bias is not None)
    dkv_in_specs = [
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, j, i: (b, h, i, Z)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, j, i: (b, kv_head(h), j, Z)),
            pl.BlockSpec((1, 1, block_k, D),
                         lambda b, h, j, i: (b, kv_head(h), j, Z)),
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, j, i: (b, h, i, Z)),
            pl.BlockSpec((1, 1, block_q, LANES),
                         lambda b, h, j, i: (b, h, i, Z)),
            pl.BlockSpec((1, 1, block_q, LANES),
                         lambda b, h, j, i: (b, h, i, Z)),
    ]
    dkv_inputs = [q, k, v, do, lse, delta]
    if key_bias is not None:
        # note swapped grid axes here: j=pid2 (k block), i=pid3 (q block)
        bmap = ((lambda b, h, j, i: (Z, Z, j)) if key_bias.shape[0] == 1
                else (lambda b, h, j, i: (b, Z, j)))
        dkv_in_specs.append(pl.BlockSpec((1, 1, block_k), bmap))
        dkv_inputs.append(key_bias.reshape(key_bias.shape[0], 1,
                                           key_bias.shape[1]))
    if dropout_rate > 0.0:
        dkv_in_specs.append(pl.BlockSpec((1,), lambda b, h, i, j: (Z,),
                                  memory_space=pltpu.SMEM))
        dkv_inputs.append(seed)
    # dK/dV computed per q-head ([B,H,Sk,D]) then group-reduced to kv heads
    dk_h, dv_h = pl.pallas_call(
        dkv_kernel,
        grid=(B, H, nk, nq),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, i: (b, h, j, Z)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, i: (b, h, j, Z)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sk, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, Sk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        interpret=_interpret(),
    )(*dkv_inputs)
    if g > 1:
        dk = dk_h.reshape(B, Hkv, g, Sk, D).sum(axis=2).astype(k.dtype)
        dv = dv_h.reshape(B, Hkv, g, Sk, D).sum(axis=2).astype(v.dtype)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk, dv


# ---------------------------------------------------------------------------
# array-level API (paddle [B, S, H, D] layout) + primitive registration
# ---------------------------------------------------------------------------
def flash_attention_bshd(q, k, v, *extras, causal=False, scale=None,
                         dropout_rate=0.0, has_bias=False):
    """Array-level flash attention in paddle layout. Returns (out, lse).

    ``extras`` holds the optional inputs IN ORDER: ``key_bias`` ([B, Sk]
    additive logit bias, present when ``has_bias``) then ``seed``
    (int32 [1], present when ``dropout_rate > 0`` — reference flash_attn
    dropout parity, flash_attn_kernel.cu:35 rng plumbing)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    extras = list(extras)
    key_bias = extras.pop(0) if has_bias else None
    seed = extras.pop(0) if dropout_rate > 0.0 else None
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out, lse = _flash_fwd_bhsd(qt, kt, vt, seed, key_bias, causal=causal,
                               scale=float(scale),
                               dropout_rate=float(dropout_rate))
    return jnp.swapaxes(out, 1, 2), lse


def _flash_vjp(grads_out, saved, *, causal, scale, dropout_rate=0.0,
               has_bias=False):
    *ins, out, lse = saved
    q, k, v = ins[:3]
    rest = list(ins[3:])
    key_bias = rest.pop(0) if has_bias else None
    seed = rest.pop(0) if dropout_rate > 0.0 else None
    do = grads_out[0]
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    ot, dot = jnp.swapaxes(out, 1, 2), jnp.swapaxes(do, 1, 2)
    dq, dk, dv = _flash_bwd_bhsd(qt, kt, vt, ot, lse, dot, seed, key_bias,
                                 causal=causal, scale=float(scale),
                                 dropout_rate=float(dropout_rate))
    grads = (jnp.swapaxes(dq, 1, 2), jnp.swapaxes(dk, 1, 2),
             jnp.swapaxes(dv, 1, 2))
    # optional inputs (bias, seed) take no grads: the bias is a mask
    grads = grads + (None,) * (len(ins) - 3)
    return grads


dispatch.register_primitive(
    "flash_attention_p",
    flash_attention_bshd,
    vjp=_flash_vjp,
    save=lambda arrays, outs: (*arrays, outs[0], outs[1]),
    multi_out=True,
    jittable=False,  # already jitted internally; pallas_call dislikes re-trace
)


def flash_attention_fused(q, k, v, *, causal=False, scale=None,
                          dropout_p=0.0, rng=None, key_bias=None):
    """Tensor-level entry used by nn.functional.scaled_dot_product_attention.
    Returns the attention output Tensor (lse is kept for backward only).
    ``dropout_p`` > 0 requires ``rng`` (a Tensor wrapping a jax PRNG key);
    the key is folded to an int32 seed for the in-kernel counter RNG.
    ``key_bias`` is a [B, Sk] additive logit bias Tensor (the padding-mask
    pattern), broadcast over heads and query rows inside the kernel."""
    from ...core.tensor import Tensor, apply

    scale = (float(scale) if scale is not None
             else 1.0 / math.sqrt(q.shape[-1]))
    extras = []
    statics = dict(causal=bool(causal), scale=scale)
    if key_bias is not None:
        if not getattr(key_bias, "stop_gradient", True):
            raise ValueError(
                "flash_attention_fused: key_bias is a mask input and "
                "receives no gradient; a trainable additive bias must "
                "use the XLA attention path (sdpa with attn_mask).")
        extras.append(key_bias)
        statics["has_bias"] = True
    if not 0.0 <= dropout_p < 1.0:
        raise ValueError(
            f"flash_attention_fused: dropout_p must be in [0, 1), "
            f"got {dropout_p} (the 1/(1-p) keep-scale diverges at 1)")
    if dropout_p > 0.0:
        if rng is None:
            raise ValueError(
                "flash_attention_fused: dropout_p > 0 requires rng (a "
                "Tensor wrapping a jax PRNG key) for the in-kernel "
                "counter RNG")
        key_bits = jax.lax.bitcast_convert_type(
            jax.random.key_data(rng._value), jnp.int32).ravel()
        extras.append(Tensor._from_value((key_bits[:1] ^ key_bits[-1:])))
        statics["dropout_rate"] = float(dropout_p)
    out, _lse = apply("flash_attention_p", q, k, v, *extras, **statics)
    return out
