"""Decode-specialized paged-attention Pallas kernel.

The serving decode step reads a PAGED KV cache: each sequence's context
lives in fixed-size pages scattered across a shared pool, addressed
through a per-sequence block table (the PagedAttention / vLLM layout;
reference surface: incubate/nn/functional/block_multihead_attention.py,
whose jnp gather program is the semantics oracle here).

Why a decode-shape-specialized kernel: the official generic Pallas
``paged_attention`` is built for long contexts — a multi-stage pipeline
of per-compute-block async copies whose fixed overhead dominates at
serving shapes (tools/paged_kernel_probe.py MEASURED: 1350 us/step at
B=8/NH=16/DH=128 with 2 pages/seq vs a ~200 us dense per-layer decode
budget). At short context the problem is overhead, not reuse, so this
kernel strips the machinery down to the decode case:

- ONE query token per sequence (q ``[B, NH, DH]``), no q-block grid
  axis and no query-side masking;
- grid ``(B, pages_per_seq)`` — each program consumes one whole page
  for ALL heads of one sequence, with the online-softmax running state
  (m, l, acc) carried in VMEM scratch across the page axis;
- the block table and sequence lengths ride in SMEM via scalar
  prefetch (``pltpu.PrefetchScalarGridSpec``), so the page index map
  resolves logical page ``i`` of sequence ``b`` to its physical pool
  page before the kernel body runs — the gather IS the DMA schedule,
  no gathered copy of K/V ever materializes;
- GQA folds into the head axis: q heads are grouped by kv head
  (``[KVH, G, DH]``) and each page is fetched ONCE per sequence, never
  repeated per q head;
- length masking is fused: pages past a sequence's length are clamped
  to its last valid page by the index map (no out-of-bounds fetch) and
  their lanes masked out of the softmax, so ragged batches cost the
  masked lanes only.

Layouts match jax's kernel convention: ``k_pages``/``v_pages`` are
``[KVH, total_pages, page_size, DH]`` (the serve engine stores its pool
this way; ``_bmha_fwd``'s ``[nb, kvh, bs, dh]`` transposes into it).

CPU CI runs :func:`paged_attention_decode_reference` — the same masked
softmax as a plain jnp gather program — or the kernel itself under
``interpret=True`` (tests/test_paged_attention_kernel.py pins kernel ==
reference == the block_mha gather path).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "paged_attention_decode",
    "paged_attention_decode_reference",
    "paged_attention_decode_kernel",
]


def _check_shapes(q, k_pages, v_pages, lengths, block_tables):
    if q.ndim != 3:
        raise ValueError(f"q must be [B, NH, DH], got {q.shape}")
    if k_pages.ndim != 4 or v_pages.shape != k_pages.shape:
        raise ValueError(
            f"k_pages/v_pages must both be [KVH, pages, page_size, DH], "
            f"got {k_pages.shape} / {v_pages.shape}")
    b, nh, dh = q.shape
    kvh = k_pages.shape[0]
    if k_pages.shape[-1] != dh:
        raise ValueError(
            f"head_dim mismatch: q has {dh}, k_pages has "
            f"{k_pages.shape[-1]}")
    if nh % kvh:
        raise ValueError(
            f"num q heads ({nh}) must be a multiple of kv heads ({kvh})")
    if lengths.shape != (b,):
        raise ValueError(
            f"lengths must be [B]={b}, got {lengths.shape}")
    if block_tables.ndim != 2 or block_tables.shape[0] != b:
        raise ValueError(
            f"block_tables must be [B, pages_per_seq], got "
            f"{block_tables.shape}")


def paged_attention_decode_reference(q, k_pages, v_pages, lengths,
                                     block_tables, *, sm_scale=None):
    """jnp gather reference: the masked-softmax program the kernel must
    match (one q token per row, GQA by repeat, -inf beyond ``lengths``).

    This is the CPU-CI code path AND the equivalence oracle promoted
    from tools/paged_kernel_probe.py. fp32 softmax, output in q.dtype.
    """
    _check_shapes(q, k_pages, v_pages, lengths, block_tables)
    b, nh, dh = q.shape
    kvh, _, page, _ = k_pages.shape
    pps = block_tables.shape[1]
    s_pad = pps * page
    scale = dh ** -0.5 if sm_scale is None else sm_scale
    # [KVH, B, PPS, PAGE, DH] -> [B, S_pad, KVH, DH]
    k_rows = k_pages[:, block_tables].transpose(1, 2, 3, 0, 4).reshape(
        b, s_pad, kvh, dh)
    v_rows = v_pages[:, block_tables].transpose(1, 2, 3, 0, 4).reshape(
        b, s_pad, kvh, dh)
    if kvh != nh:
        k_rows = jnp.repeat(k_rows, nh // kvh, axis=2)
        v_rows = jnp.repeat(v_rows, nh // kvh, axis=2)
    scores = jnp.einsum("bhd,bshd->bhs", q.astype(jnp.float32),
                        k_rows.astype(jnp.float32)) * scale
    valid = jnp.arange(s_pad)[None, :] < lengths[:, None]
    scores = jnp.where(valid[:, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    # a zero-length row is fully masked -> NaN; serve engines carry such
    # rows for inactive slots, so return 0 instead (matches the kernel)
    probs = jnp.where(valid[:, None, :], probs, 0.0)
    return jnp.einsum("bhs,bshd->bhd", probs,
                      v_rows.astype(jnp.float32)).astype(q.dtype)


def _decode_kernel_body(len_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref,
                        m_scr, l_scr, acc_scr, *, kvh, group, page, scale):
    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, -jnp.inf, jnp.float32)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    length = len_ref[b]
    dh = q_ref.shape[-1]
    # q heads grouped by kv head: head h = kv_head * group + g
    q = q_ref[0].astype(jnp.float32).reshape(kvh, group, dh)
    k = k_ref[:, 0].astype(jnp.float32)        # [KVH, PAGE, DH]
    v = v_ref[:, 0].astype(jnp.float32)
    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32) * scale     # [KVH, G, PAGE]
    pos = i * page + jax.lax.broadcasted_iota(
        jnp.int32, (kvh, group, page), 2)
    in_len = pos < length
    s = jnp.where(in_len, s, -jnp.inf)

    m_prev = m_scr[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
    p = jnp.where(in_len, jnp.exp(s - m_new[..., None]), 0.0)
    # m_prev is -inf until the first valid lane; exp(-inf - -inf) trap
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - m_new), 0.0)
    l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1)
    acc_scr[:] = acc_scr[:] * alpha[..., None] + jax.lax.dot_general(
        p, v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32)             # [KVH, G, DH]
    m_scr[:] = m_new

    @pl.when(i == pl.num_programs(1) - 1)
    def _emit():
        l = l_scr[:][..., None]
        out = jnp.where(l > 0.0, acc_scr[:] / jnp.where(l > 0.0, l, 1.0),
                        0.0)
        o_ref[0] = out.reshape(kvh * group, dh).astype(o_ref.dtype)


def paged_attention_decode_kernel(q, k_pages, v_pages, lengths,
                                  block_tables, *, sm_scale=None,
                                  interpret=False):
    """The Pallas kernel proper (TPU; ``interpret=True`` on CPU)."""
    _check_shapes(q, k_pages, v_pages, lengths, block_tables)
    b, nh, dh = q.shape
    kvh, _npages, page, _ = k_pages.shape
    pps = block_tables.shape[1]
    group = nh // kvh
    scale = dh ** -0.5 if sm_scale is None else sm_scale
    lengths = lengths.astype(jnp.int32)
    block_tables = block_tables.astype(jnp.int32)

    def page_map(bi, i, len_ref, tbl_ref):
        # clamp fully-masked trailing pages to the row's last valid page
        # so no out-of-range pool page is ever fetched; their lanes are
        # masked out of the softmax by `in_len` anyway
        valid_pages = jax.lax.div(len_ref[bi] + (page - 1),
                                  jnp.int32(page))
        pi = jnp.minimum(i, jnp.maximum(valid_pages - 1, 0))
        return (0, tbl_ref[bi, pi], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, pps),
        in_specs=[
            pl.BlockSpec((1, nh, dh), lambda bi, i, *_: (bi, 0, 0)),
            pl.BlockSpec((kvh, 1, page, dh), page_map),
            pl.BlockSpec((kvh, 1, page, dh), page_map),
        ],
        out_specs=pl.BlockSpec((1, nh, dh), lambda bi, i, *_: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kvh, group), jnp.float32),
            pltpu.VMEM((kvh, group), jnp.float32),
            pltpu.VMEM((kvh, group, dh), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel_body, kvh=kvh, group=group,
                               page=page, scale=scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nh, dh), q.dtype),
        interpret=interpret,
    )(lengths, block_tables, q, k_pages, v_pages)


def paged_attention_decode(q, k_pages, v_pages, lengths, block_tables, *,
                           sm_scale=None, backend="auto"):
    """Paged-attention for ONE decode step.

    Args:
      q: ``[B, NH, DH]`` — one query token per sequence. With GQA, q
        heads are grouped by kv head (head ``h`` reads kv head
        ``h // (NH // KVH)``, the standard repeat layout).
      k_pages / v_pages: ``[KVH, total_pages, page_size, DH]`` pool.
      lengths: ``[B]`` int32 — valid context length per sequence
        (including the just-written token). Length 0 rows (inactive
        serving slots) return zeros instead of NaN.
      block_tables: ``[B, pages_per_seq]`` int32 physical page ids.
      backend: ``"auto"`` (kernel on TPU, jnp reference elsewhere),
        ``"kernel"``, ``"reference"``, or ``"interpret"`` (kernel under
        the Pallas interpreter — the CPU-CI equivalence path).

    Returns ``[B, NH, DH]`` in q.dtype.
    """
    if backend == "auto":
        backend = ("kernel" if jax.default_backend() == "tpu"
                   else "reference")
    if backend == "reference":
        return paged_attention_decode_reference(
            q, k_pages, v_pages, lengths, block_tables, sm_scale=sm_scale)
    if backend in ("kernel", "interpret"):
        return paged_attention_decode_kernel(
            q, k_pages, v_pages, lengths, block_tables, sm_scale=sm_scale,
            interpret=(backend == "interpret"))
    raise ValueError(
        f"paged_attention_decode: unknown backend {backend!r} "
        f"(use 'auto', 'kernel', 'reference' or 'interpret')")
